"""The read-only subscriber peer: parameter subscription with verified
bounded-staleness reads.

Protocol (all on the existing tree overlay — a subscriber is one more leaf
in the transport's join walk):

1. join: the transport grafts us under some writer; on LINK_UP(uplink) we
   send SYNC with compat.SYNC_FLAG_READ_ONLY (+ SYNC_FLAG_RANGE and a
   wire.RANGE message for a paged subscription) and DONE. The parent
   answers WELCOME + a snapshot of our subscribed pages as CHUNKs + DONE +
   a FRESH mark stamped at snapshot time, then opens the codec stream —
   the seed rides the CONTROL plane (which chaos never touches, the r06
   rule), so joins and resyncs complete deterministically on a lossy data
   plane. The post-seed codec stream arms the seq gap detector at 1.
2. steady state: the parent streams unledgered DATA/BURST (full table) or
   RDATA (range) messages, each carrying the r09 trace stamp; applying one
   advances our *verified freshness* to the stamp's origin time. An IDLE
   parent sends FRESH drain marks instead ("as of t you have everything"),
   so a quiet tree does not read as ever-staler.
3. loss: subscriber links have no ACK ledger by design (writers skip all
   delivery state for read-only leaves), so a swallowed message surfaces
   as a seq gap here. We DESYNC — reads refuse past the staleness bound,
   never silently serve a diverged replica — and repair by re-running the
   SYNC/DONE handshake on the same link (rate-limited), which re-seeds the
   whole subscription. The transport's normal re-join handles a dead
   uplink the same way.

Reads never touch the data plane: the recv thread publishes each applied
batch through a :class:`core.SnapshotPublisher` double buffer, and
``read()`` is a lock-free reference read + staleness verification (the
reference's ``copyToTensor`` copies under the data-plane lock; serving
fleets must not — see SnapshotPublisher's docstring).

The subscriber runs pure numpy (it never initializes a JAX backend — the
host-tier rule); :class:`serve.ServingHandle` does the JAX conversion in
the inference process.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

import numpy as np

from .. import obs as _obs
from ..compat import SYNC_FLAG_RANGE, SYNC_FLAG_READ_ONLY, wire_protocol_version
from ..comm import wire
from ..comm.transport import EventKind, TransportNode
from ..config import Config
from ..core import SnapshotPublisher
from ..ops.codec import SAT as _SAT
from ..ops.codec_np import _layout, unflatten_np
from ..ops.table import make_spec

log = logging.getLogger("shared_tensor_tpu.serve")


def epoch() -> int:
    """A freshness epoch token: CLOCK_MONOTONIC nanoseconds, the same clock
    the r09 origin stamps and FRESH marks carry. Capture one AFTER a
    write (``peer.add()``), then ``Subscriber.wait_fresh(token)`` — valid
    within one host (the r09 staleness caveat; cross-host needs synced
    clocks)."""
    return time.monotonic_ns()


class StalenessError(RuntimeError):
    """A read's staleness bound could not be VERIFIED: the subscriber is
    desynced (gap/resync in progress), still seeding, or its newest
    verified-fresh instant (origin stamp / FRESH mark) is older than the
    bound. Raised instead of returning possibly-stale weights — the serving
    tier's contract is "fresh-enough or loud", never silent staleness."""

    def __init__(self, msg: str, staleness: float = float("inf")):
        super().__init__(msg)
        #: Seconds since the newest verified-fresh instant (inf = never
        #: verified / desynced).
        self.staleness = staleness


class Subscriber:
    """One read-only leaf: joins the tree at (host, port), subscribes to
    the full table or a sub-range, and serves verified bounded-staleness
    reads. Never ``add()``\\ s — there is deliberately no write API here.
    """

    def __init__(
        self,
        host: str,
        port: int,
        template: Any,
        config: Config | None = None,
    ):
        self.config = config or Config()
        tcfg = self.config.transport
        scfg = self.config.serve
        if tcfg.wire_compat:
            raise ValueError(
                "the serving tier needs the native protocol (the reference "
                "compat wire has no handshake to advertise read-only on)"
            )
        self.spec = make_spec(template)
        self._offs, self._ns, self._padded = _layout(self.spec)
        words = self.spec.total // 32
        # element range -> outward-rounded word range
        if scfg.range is not None:
            lo, hi = scfg.range
            if not (0 <= lo < hi <= self.spec.total):
                raise ValueError(
                    f"serve range [{lo}, {hi}) outside the "
                    f"{self.spec.total}-element table"
                )
            self._wlo = lo // 32
            self._wcnt = -(-hi // 32) - self._wlo
        else:
            self._wlo, self._wcnt = 0, words
        self._ranged = self._wlo > 0 or self._wcnt < words
        self._elo = self._wlo * 32
        n_el = self._wcnt * 32
        # per-element leaf index + live (non-padding) mask for the range —
        # the apply kernel's geometry (mirrors codec_np._scale_per_element /
        # _live_mask_np, restricted to the buffered pages)
        bounds = np.cumsum(self._padded)
        el = np.arange(self._elo, self._elo + n_el)
        self._leaf_of = np.searchsorted(bounds, el, side="right").astype(
            np.int64
        )
        starts = self._offs[self._leaf_of]
        self._live = (
            (el - starts) < self._ns[self._leaf_of]
        ).astype(np.float32)
        # the ONLY buffered state: the subscribed pages (plus the published
        # double-buffer copies) — a ranged subscriber never allocates the
        # full table
        self._vals = np.zeros(n_el, np.float32)
        self._pub = SnapshotPublisher()
        self._version = 0
        self._fresh_ns = 0  # newest VERIFIED-fresh instant (stamp/FRESH)
        self._wire_version = wire_protocol_version(self.config)
        self._synced = False  # seq detector armed (post-seed)
        self._await_welcome = False
        self._seeding = False  # WELCOME seen, CHUNK seed in flight
        self._staging: bytes | bytearray = b""
        self._expected_seq = 1
        self._last_resync = 0.0
        self._handshake_t0 = 0.0
        self._uplink: Optional[int] = None
        self._error: Optional[Exception] = None
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._digest_last = 0.0

        self.node = TransportNode(
            host,
            port,
            tcfg,
            frame_bytes=wire.frame_wire_bytes(self.spec),
            max_children=1,
            keepalive_sec=min(1.0, max(0.05, tcfg.peer_timeout_sec / 4)),
        )
        if self.node.is_master:
            # a read-only replica cannot seed state: claiming an empty
            # rendezvous would serve zeros forever (and orphan real writers
            # behind us). Fail loudly; start the writers first.
            self.node.close()
            raise ConnectionError(
                f"no tree to subscribe to at {host}:{port} — a read-only "
                f"subscriber cannot become master; start a writer first"
            )
        # observability: own registry under the canonical st_read_*/st_sub_*
        # schema + digest beats up the tree (the cluster view includes
        # subscribers)
        self._obs_on = _obs.obs_enabled() and self.config.obs.enabled
        self._hub = _obs.hub() if self._obs_on else None
        self._reg = _obs.Registry()
        self._m_reads = self._reg.counter(
            "st_read_total", help="serving reads served (bound verified)"
        )
        self._m_stale = self._reg.counter(
            "st_read_stale_total",
            help="reads refused: staleness bound not verifiable",
        )
        self._m_staleness = self._reg.histogram(
            "st_read_staleness_seconds",
            buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0),
            help="verified staleness observed at read time",
        )
        self._m_resyncs = self._reg.counter(
            "st_sub_resyncs_total", help="re-seed handshakes"
        )
        self._m_gaps = self._reg.counter(
            "st_sub_gap_discards_total",
            help="data messages discarded while desynced",
        )
        self._m_fresh = self._reg.counter(
            "st_sub_fresh_marks_total", help="FRESH drain marks applied"
        )
        self._reg.register_collector(self._collect)
        self._label = f"sub-{self.node.obs_id}"
        if self._hub is not None:
            self._hub.register_registry(self._label, self._reg)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="st-sub"
        )
        self._thread.start()

    # -- user API ------------------------------------------------------------

    def read(self, max_staleness: Optional[float] = None) -> Any:
        """The subscribed state, VERIFIED at most ``max_staleness`` seconds
        behind (default: ServeConfig.max_staleness_sec) — or raise
        :class:`StalenessError`. Full-table subscriptions return the
        caller's pytree structure (the reference's ``copyToTensor`` shape);
        ranged ones return the raw f32 page array (use :meth:`read_flat`'s
        twin semantics). Lock-free: a read can never block the apply path
        (or a writer's ``add()``) — it touches only the published double
        buffer."""
        flat, _staleness, _ver = self.read_flat(max_staleness)
        if self._ranged:
            return flat
        return unflatten_np(flat, self.spec)

    def read_flat(
        self, max_staleness: Optional[float] = None
    ) -> tuple[np.ndarray, float, int]:
        """(flat f32 snapshot of the subscribed pages, verified staleness
        seconds, snapshot version) — the allocation-light spelling
        :class:`ServingHandle` refreshes from. All three come from ONE
        publisher acquire, so the version can never label a different
        array than the one returned (a torn pair would let a handle skip
        the real newest snapshot forever on an idle tree). Raises
        StalenessError when the bound cannot be verified."""
        bound = (
            self.config.serve.max_staleness_sec
            if max_staleness is None
            else float(max_staleness)
        )
        err = self._error
        if err is not None:
            self._m_stale.inc()
            raise StalenessError(f"subscriber failed: {err}") from err
        arr, fresh_ns, ver = self._pub.acquire()
        if arr is None or fresh_ns <= 0:
            self._m_stale.inc()
            raise StalenessError(
                "no verified-fresh state yet (still seeding)"
            )
        staleness = max(0.0, (time.monotonic_ns() - fresh_ns) / 1e9)
        if staleness > bound:
            self._m_stale.inc()
            raise StalenessError(
                f"state is {staleness:.3f}s behind, bound {bound:.3f}s "
                f"(desynced or writer unreachable — reads refuse rather "
                f"than serve silently-stale weights)",
                staleness,
            )
        self._m_reads.inc()
        self._m_staleness.observe(staleness)
        return arr, staleness, ver

    def wait_fresh(self, epoch_ns: int, timeout: float = 30.0) -> None:
        """Block until the replica provably includes every update
        originated at or before ``epoch_ns`` (a :func:`epoch` token — capture
        it AFTER the write you care about): i.e. until the verified-fresh
        instant reaches the token. TimeoutError past the budget."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and not self._stop.is_set():
            err = self._error
            if err is not None:
                raise StalenessError(f"subscriber failed: {err}") from err
            _arr, fresh_ns, _ver = self._pub.acquire()
            if fresh_ns >= epoch_ns:
                return
            time.sleep(0.002)
        raise TimeoutError(
            f"state did not reach epoch within {timeout}s "
            f"(behind by {(epoch_ns - self._pub.acquire()[1]) / 1e9:.3f}s)"
        )

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until seeded AND verified fresh at least once (the first
        read can succeed)."""
        if not self._ready.wait(timeout):
            if self._error is not None:
                raise self._error
            raise TimeoutError(f"subscriber not ready after {timeout}s")
        if self._error is not None:
            raise self._error

    def staleness(self) -> float:
        """Seconds since the newest verified-fresh instant (inf before the
        first verification)."""
        _arr, fresh_ns, _ver = self._pub.acquire()
        if fresh_ns <= 0:
            return float("inf")
        return max(0.0, (time.monotonic_ns() - fresh_ns) / 1e9)

    @property
    def version(self) -> int:
        """Monotone snapshot version (bumps per applied batch) — serving
        handles skip rebuilding params when it hasn't moved."""
        return self._pub.acquire()[2]

    @property
    def range_elements(self) -> tuple[int, int]:
        """The buffered element range [lo, hi) (word-aligned; the full
        padded table when no range was configured)."""
        return self._elo, self._elo + self._vals.size

    def serving_handle(self, max_staleness: Optional[float] = None):
        """A :class:`serve.ServingHandle` over this subscription (hot-swap
        weight publication for an inference loop)."""
        from .handle import ServingHandle

        return ServingHandle(self, max_staleness=max_staleness)

    def metrics(self) -> dict:
        """Canonical-schema snapshot (st_read_*/st_sub_* — obs/schema.py)."""
        return self._reg.snapshot()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._hub is not None:
            self._hub.unregister_registry(self._label)
        self.node.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- observability -------------------------------------------------------

    def _collect(self) -> dict:
        _arr, fresh_ns, _ver = self._pub.acquire()
        age = (
            (time.monotonic_ns() - fresh_ns) / 1e9 if fresh_ns > 0 else -1.0
        )
        return {
            "st_sub_freshness_seconds": age,
            "st_sub_range_words": self._wcnt,
        }

    def _event(self, name: str, link: int = 0, arg: int = 0) -> None:
        if self._hub is not None:
            self._hub.emit(name, node=self.node.obs_id, link=link, arg=arg)

    # -- protocol ------------------------------------------------------------

    def _send_ctrl(self, link: int, payload: bytes) -> bool:
        """Small control sends with bounded retry (handshake/digest)."""
        for _ in range(50):
            if self._stop.is_set():
                return False
            try:
                if self.node.send(link, payload, timeout=0.1):
                    return True
            except BrokenPipeError:
                return False
        return False

    def _start_handshake(self, uplink: int, resync: bool) -> None:
        """SYNC (+RANGE) + DONE. The parent answers with WELCOME + ITS
        snapshot of our pages as CHUNKs + DONE + a FRESH mark stamped at
        snapshot time — the seed rides the CONTROL plane, which the chaos
        classes never touch (the r06 rule), so a resync completes
        DETERMINISTICALLY however lossy the data plane is. A codec-stream
        seed would instead need every one of its unledgered messages to
        survive end-to-end: under sustained loss that essentially never
        happens, and the subscriber would resync forever (measured)."""
        self._synced = False
        self._seeding = False
        self._await_welcome = True
        self._handshake_t0 = time.monotonic()
        flags = SYNC_FLAG_READ_ONLY | (SYNC_FLAG_RANGE if self._ranged else 0)
        ok = self._send_ctrl(
            uplink, wire.encode_sync(self.spec, self._wire_version, flags)
        )
        if ok and self._ranged:
            ok = self._send_ctrl(
                uplink, wire.encode_range(self._wlo, self._wcnt)
            )
        if ok:
            ok = self._send_ctrl(uplink, bytes([wire.DONE]))
        if ok and resync:
            self._m_resyncs.inc()
            self._event("sub_resync", uplink)
        if not ok:
            log.warning("subscriber handshake send failed (uplink down?)")

    def _desync(self, why: str, seq: int = 0) -> None:
        if self._synced:
            log.info("subscriber desynced (%s, seq %d): will resync", why, seq)
        self._synced = False

    def _maybe_resync(self) -> None:
        up = self._uplink
        if up is None or self._synced:
            return
        now = time.monotonic()
        if self._await_welcome or self._seeding:
            # a handshake/seed is in flight; but a WELCOME or seed DONE
            # that never arrives (parent died mid-handshake) must not
            # wedge the subscriber forever — re-run after a bounded wait
            if now - self._handshake_t0 < 5.0:
                return
        if now - self._last_resync < self.config.serve.resync_min_interval_sec:
            return
        self._last_resync = now
        self._start_handshake(up, resync=True)

    def _apply_frame(
        self, scales: np.ndarray, words: np.ndarray, word_lo: int
    ) -> bool:
        """Apply one frame's (scales, word slice) to the buffered pages —
        the receive half of the sign codec, restricted to our range
        (bit-compatible with stc_apply_frame over the same elements:
        value += scale[leaf] * (1 - 2*bit), padding untouched, ±SAT
        saturation). Returns False for an all-zero-scale no-op."""
        if not scales.any():
            return False
        if word_lo != self._wlo or words.size != self._wcnt:
            # a full-table frame covers any subscription: slice it; an
            # RDATA for a different range is a protocol error
            if word_lo == 0 and words.size >= self._wlo + self._wcnt:
                words = words[self._wlo : self._wlo + self._wcnt]
            else:
                raise ValueError(
                    f"frame words [{word_lo}, {word_lo + words.size}) do "
                    f"not cover subscription [{self._wlo}, "
                    f"{self._wlo + self._wcnt})"
                )
        bits = np.unpackbits(
            np.ascontiguousarray(words, "<u4").view(np.uint8),
            bitorder="little",
        ).astype(np.float32)
        s_el = scales[self._leaf_of] * self._live
        self._vals += s_el * (1.0 - 2.0 * bits)
        np.clip(self._vals, -_SAT, _SAT, out=self._vals)
        return True

    def _publish(self) -> None:
        self._version += 1
        self._pub.publish(self._vals.copy(), self._fresh_ns, self._version)
        if self._fresh_ns > 0:
            self._ready.set()

    def _on_data(self, payload: bytes) -> bool:
        """One DATA/BURST/RDATA message. Returns True if state changed."""
        seq = wire.data_seq(payload)
        if not self._synced:
            self._m_gaps.inc()
            return False
        if seq != self._expected_seq & 0xFFFFFFFF:
            if seq == (self._expected_seq - 1) & 0xFFFFFFFF:
                return False  # duplicate delivery: drop quietly
            # a message vanished on the unledgered link: nothing will ever
            # re-deliver it — desync and re-seed
            self._m_gaps.inc()
            self._desync("seq gap", seq)
            return False
        kind = payload[0]
        changed = False
        trace = None
        try:
            if kind == wire.RDATA:
                scales, words, wlo, _wcnt, trace = wire.decode_rdata(
                    payload, self.spec
                )
                changed = self._apply_frame(scales, words, wlo)
            elif kind == wire.DATA:
                f = wire.decode_frame(payload, self.spec)
                trace = wire.data_trace(payload, self.spec)
                changed = self._apply_frame(
                    np.asarray(f.scales), np.asarray(f.words), 0
                )
            else:  # BURST
                trace = wire.data_trace(payload, self.spec)
                for f in wire.decode_burst(payload, self.spec):
                    changed |= self._apply_frame(
                        np.asarray(f.scales), np.asarray(f.words), 0
                    )
        except Exception as e:
            # undecodable (sheared/garbled): do NOT consume the seq — on
            # the ledgered writer path that rule lets the retransmission
            # re-deliver the message whole; here nothing retransmits, so
            # the only honest repair is a desync + control-plane re-seed
            # (silently skipping it would lose the frame's mass forever
            # while freshness kept advancing)
            log.warning("undecodable data message (seq %d): %s", seq, e)
            self._m_gaps.inc()
            self._desync("undecodable", seq)
            return False
        self._expected_seq += 1
        if trace is not None:
            _origin, gen, _hops = trace
            if gen > self._fresh_ns:
                # verified freshness: the state now includes an update
                # originated at `gen` — and FIFO + in-order seqs mean it
                # includes everything the parent folded before it
                self._fresh_ns = gen
        return changed

    def _on_message(self, link: int, payload: bytes) -> bool:
        kind = payload[0]
        if kind in (wire.DATA, wire.BURST, wire.RDATA):
            return self._on_data(payload)
        if kind == wire.WELCOME:
            # seed transfer starting: the parent's snapshot of our pages
            # follows as CHUNKs, then DONE arms the stream
            self._await_welcome = False
            self._seeding = True
            self._staging = bytearray(self._vals.size * 4)
            return True
        if kind == wire.CHUNK:
            if self._seeding:
                wire.decode_chunk_into(payload, self._staging)
            return True
        if kind == wire.DONE:
            if self._seeding:
                # seed complete: adopt the parent's snapshot wholesale and
                # arm the gap detector at 1 (codec DATA follows, FIFO);
                # freshness re-establishes from the FRESH mark the parent
                # stamped at snapshot time (next message)
                self._vals[:] = np.frombuffer(self._staging, "<f4")
                self._staging = b""
                self._seeding = False
                self._expected_seq = 1
                self._synced = True
                self._fresh_ns = 0
                self._publish()
            return True
        if kind == wire.FRESH:
            t, last_seq = wire.decode_fresh(payload)
            if not self._synced:
                return True
            applied = (self._expected_seq - 1) & 0xFFFFFFFF
            if last_seq != applied:
                # the mark covers messages we never saw: the stream TAIL
                # was swallowed — undetectable from data alone on an idle
                # tree (no next message ever exposes the gap), which is
                # exactly why FRESH carries the seq. Resync instead of
                # falsely verifying freshness over diverged state.
                self._m_gaps.inc()
                self._desync("fresh-mark seq mismatch", last_seq)
                return True
            if t > self._fresh_ns:
                self._fresh_ns = t
                self._m_fresh.inc()
                self._pub.touch(self._fresh_ns)
                self._ready.set()
            return True
        if kind == wire.REJECT:
            self._error = ConnectionError(
                f"parent rejected subscription: {wire.decode_reject(payload)}"
            )
            self._ready.set()
            return True
        if kind == wire.SYNC:
            # a writer (or another subscriber) tried to join UNDER us: a
            # read-only leaf has nothing to seed it with
            self._send_ctrl(
                link,
                wire.encode_reject(
                    "read-only subscriber accepts no children"
                ),
            )
            self.node.drop_link_flushed(link)
            return True
        return False  # ACK/DIGEST/...: not ours, ignore

    def _publish_digest(self) -> None:
        """r09 in-band aggregation, subscriber edition: our st_read_*/
        st_sub_* registry rides the same DIGEST control message up the
        tree, so the root's cluster view (obs.top) includes the serving
        fleet."""
        up = self._uplink
        if up is None:
            return
        from ..obs import aggregate

        doc = aggregate.from_snapshot(
            self.node.obs_id, self._reg.snapshot(), time.monotonic_ns()
        )
        aggregate.bounded(doc)
        try:
            self.node.send(up, wire.encode_digest(doc), timeout=0.05)
        except BrokenPipeError:
            pass

    def _loop(self) -> None:
        digest_interval = (
            self.config.obs.digest_interval_sec if self._obs_on else 0.0
        )
        while not self._stop.is_set():
            busy = False
            for ev in self.node.poll_events(timeout=0.0):
                busy = True
                if ev.kind == EventKind.LINK_UP:
                    if ev.is_uplink:
                        self._uplink = ev.link_id
                        self._error = None
                        self._start_handshake(ev.link_id, resync=False)
                    # else: a joiner grafted under us — kept up just long
                    # enough to REJECT its SYNC (the _on_message SYNC
                    # branch), so the joiner fails loudly with a reason
                    # instead of retrying a silent drop forever
                elif ev.kind == EventKind.LINK_DOWN and ev.is_uplink:
                    self._uplink = None
                    self._desync("uplink down")
                elif ev.kind == EventKind.BECAME_MASTER:
                    self._error = ConnectionError(
                        "subscriber was elected master (all writers died):"
                        " a read-only replica cannot serve the tree —"
                        " restart a writer and re-create the subscriber"
                    )
                    self._desync("became master")
                    self._ready.set()
                elif ev.kind == EventKind.REJOIN_FAILED:
                    self._desync("rejoin failed")
            up = self._uplink
            changed = False
            if up is not None:
                for _ in range(256):
                    try:
                        payload = self.node.recv(up, timeout=0.0)
                    except BrokenPipeError:
                        break
                    if payload is None:
                        break
                    busy = True
                    try:
                        changed |= self._on_message(up, payload)
                    except Exception as e:
                        log.warning("dropping bad message: %s", e)
                    if changed:
                        # publish PER applied message, not per drain pass:
                        # under sustained write load the drain loop stays
                        # busy for whole seconds, and readers must see
                        # freshness advance with every apply, not when the
                        # backlog finally empties (the copy is the cheap
                        # part — the apply above dwarfs it)
                        self._publish()
                        changed = False
            # also drain/reject stray child links (see _on_message SYNC)
            for link in self.node.links:
                if link == up:
                    continue
                try:
                    payload = self.node.recv(link, timeout=0.0)
                except BrokenPipeError:
                    continue
                if payload is not None:
                    busy = True
                    try:
                        self._on_message(link, payload)
                    except Exception as e:
                        log.warning("dropping bad child message: %s", e)
            if changed:
                self._publish()
            self._maybe_resync()
            if digest_interval > 0:
                now = time.monotonic()
                if now - self._digest_last >= digest_interval:
                    self._digest_last = now
                    try:
                        self._publish_digest()
                    except Exception as e:
                        log.debug("subscriber digest failed: %s", e)
            if self._hub is not None:
                self._hub.poll_native(
                    self.config.obs.native_drain_interval_sec
                )
            if not busy:
                time.sleep(0.002)


def subscribe(
    host: str,
    port: int,
    template: Any,
    config: Config | None = None,
    timeout: float = 30.0,
) -> Subscriber:
    """Create a :class:`Subscriber` and block until its first verified-fresh
    read can succeed — the serving twin of ``create_or_fetch``."""
    sub = Subscriber(host, port, template, config)
    try:
        sub.wait_ready(timeout)
    except BaseException:
        sub.close()
        raise
    return sub
