"""Read-path serving tier (r10): parameter subscription for inference fleets.

Every other workload in the repo WRITES (async-SGD trainers calling
``add()``); the "millions of users" north star is read-dominated — fleets
of inference replicas that need fresh-enough weights, not write access.
This package opens that scenario:

- :class:`Subscriber` — a read-only leaf of the tree. It advertises itself
  in the SYNC handshake (compat.SYNC_FLAG_READ_ONLY, the r09 wire-version
  machinery's r10 extension), so writers attach its link UNLEDGERED: no
  unacked ledger, no ACKs, no go-back-N state — a read-only leaf owes the
  tree nothing and its loss repairs by re-seed, not by carry.
- **Bounded-staleness reads** — ``Subscriber.read(max_staleness=...)``
  VERIFIES the bound against the r09 origin stamps (and the writer's FRESH
  drain marks) and raises :class:`StalenessError` when it cannot: a read is
  never silently stale. ``wait_fresh(epoch)`` blocks until the replica
  provably includes everything up to a monotonic-ns epoch token
  (:func:`epoch`). Same-host CLOCK_MONOTONIC semantics, like the r09
  ``st_staleness_seconds`` telemetry.
- **Range subscription** — subscribe to a sub-range of the table
  (``ServeConfig.range``; embedding/paged-style reads): the wire gains a
  RANGE control message, writers forward only the subscribed words per
  frame (wire.RDATA), and the subscriber buffers ONLY its pages.
- :class:`ServingHandle` — double-buffered hot-swap weight publication
  into an inference loop: ``refresh()`` atomically swaps a verified JAX
  snapshot in; ``params()`` is a lock-free reference read, so serving
  threads never touch the data plane (core.SnapshotPublisher).
"""

from .handle import ServingHandle
from .subscriber import StalenessError, Subscriber, epoch, subscribe

__all__ = [
    "ServingHandle",
    "StalenessError",
    "Subscriber",
    "epoch",
    "subscribe",
]
