"""ShardNode: one member of a cluster-sharded tensor (r16 tentpole).

The core invariant changes here — from "every node converges on the whole
table" (the flood) to "every word has exactly one owner and the cluster
converges on the union of the owned slices". A ShardNode joins the same
transport tree as every other tier, but:

- it holds ONLY its owned shard slices (plus transient outboxes and
  subscriber residuals — :class:`~shared_tensor_tpu.shard.state.ShardState`
  carries the memory contract: O(total / n_shards) per node, never the
  full table);
- a local ``add()`` applies its IN-shard part exactly (local applies never
  quantize) and accumulates the out-of-shard parts into per-target-shard
  outbox residuals, drained as :data:`wire.FWD` frames routed hop-by-hop
  toward each shard's owner — the flood-re-quantize path is gone; a relay
  forwards the frame VERBATIM (re-stamping only the per-link seq), so
  owner-routed forwarding never re-quantizes;
- delivery is the r06 discipline per hop (per-link tx_seq, cumulative
  wire.ACK, byte-identical go-back-N retransmission, black-hole teardown
  into re-route) plus END-TO-END dedup at the owner on the frame's
  (origin, fwd_seq) identity: a re-routed resend of a delivered-but-
  unacked frame is discarded instead of double-applied (the at-least-once
  window the wire.py FWD note documents);
- readers never land here: full/partial views ride the r10 subscription
  machinery against each owner (:mod:`shared_tensor_tpu.shard.gather`),
  and a ShardNode serves ranged read-only subscribers within its owned
  shards exactly like a classic writer does.

Membership / the shard map
--------------------------

The master (the node that created the rendezvous) partitions the word
space into ``ShardConfig.n_shards`` contiguous ranges and is the ONLY
minter of ownership grants (tools/protospec/spec_shard.py model-checks
the exactly-one-owner discipline). A joiner advertises the r16 capability
in its SYNC flags (compat.SYNC_FLAG_SHARD + a 2-byte shard-index claim
tail); a sharded parent answers WELCOME with the same flag and the
current map as a wire.SHARD control message, after which the joiner's
claim rides ``{"t": "claim"}`` up the tree to the master, the grant
floods back down, and the claimer adopts its slice. Tolerant in both
orientations (the compat.py SYNC_FLAG_SHARD note): a sharded joiner
under a pre-r16/unsharded parent detects the absent WELCOME flag and
raises :class:`ShardFallback` (``create_or_fetch_sharded`` then returns
a classic full-replica peer); a classic WRITER joining a sharded parent
is REJECTed with an explicit reason (no node here can seed a full
replica).

Routing is reverse-path: an owner floods ``{"t": "own"}`` announces
(epoch-filtered, so stale floods can't loop) and every node records the
arrival link as its next hop toward that shard; unknown routes default
to the uplink, and a frame with no route at all parks in a bounded
buffer (``ShardConfig.park_cap`` — overflow drops the OLDEST parked
frame and counts it loudly, never unbounded memory).

Drain-handoff: a leaving owner drains its outboxes/ledgers, then
transfers each owned slice to its PARENT over the control plane
(``ho_meta`` / ``ho_state`` chunks / ``ho_done`` / ``ho_ack``) along
with its END-TO-END dedup state — without the dedup transfer, a
retransmission of a frame the old owner applied-but-never-acked would
double-apply at the successor (the exact mutation the spec_shard red
team seeds). The successor mints the next epoch (the map.py handoff
discipline), announces, and the cluster's routes flip.

Host-tier rules apply: pure numpy, no jax backend is ever initialized
here (the core.py 2.7x contention note); one loop thread owns all
protocol state except ShardState (which has its own lock so ``add()``
can run from the caller's thread).
"""

from __future__ import annotations

import base64
import logging
import os
import struct
import threading
import time
from collections import deque
from typing import Any, Optional

import numpy as np

from .. import obs as _obs
from ..obs import schema as _schema
from ..obs.clock import ClockSync
from ..comm import wire
from ..comm.transport import EventKind, TransportNode
from ..compat import (
    SYNC_FLAG_RANGE,
    SYNC_FLAG_READ_ONLY,
    SYNC_FLAG_SHARD,
    wire_protocol_version,
)
from ..config import Config
from ..ops.codec_np import flatten_np
from ..ops.table import TableFrame, make_spec
from .engine_lane import ShardLane, shard_engine_eligible
from .map import OwnerEntry, ShardMap
from .state import ShardState, SliceCodec

log = logging.getLogger("shared_tensor_tpu.shard")


class ShardBackpressure(RuntimeError):
    """add() refused: the per-target-shard outbox allocation would exceed
    ShardConfig.outbox_limit_bytes and the overflow policy is "raise"
    (or the "block" wait timed out). The writer is outrunning the FWD
    plane's drain — back off, or raise the limit."""


class _NullCounter:
    """Stands in for a Registry counter whose value the engine lane serves
    from the C counters ABI instead (the collector would lose to a
    registered instrument of the same name — obs/registry.py snapshot)."""

    __slots__ = ()

    def inc(self, n: int = 1) -> None:
        pass

#: Go-back-N bounds, mirroring comm/peer.py's ledgered discipline: most
#: unacked FWD messages per link (backpressure: a full window leaves mass
#: in the outbox residual, where error feedback keeps it exact), and how
#: many head entries one retransmission round re-sends byte-identical.
SEND_WINDOW = 32
RETX_PREFIX = 4
#: Most FWD messages drained per outbox per loop pass (fairness across
#: shards; the loop comes right back while any outbox is non-idle). Each
#: message carries up to wire.FWD_BURST_FRAMES successive halvings.
OUTBOX_MSGS_PER_PASS = 4
#: End-to-end dedup window per origin: the owner remembers this many
#: recent (origin, fwd_seq) identities. Duplicates only arise inside the
#: re-route race window (a rollback-resend racing a delivered-but-unacked
#: original), which is far narrower than this; the bound keeps dedup
#: state O(origins), and the whole window transfers at handoff.
DEDUP_WINDOW = 1024
#: How often an owner re-floods its ``own`` route announces (heals routes
#: purged by link deaths; late joiners learn the reverse path).
ANNOUNCE_SEC = 2.0
#: Per-link transport send-queue depth this node runs with — MUST equal
#: the native default (sttransport.cpp ``int32_t queue_depth = 8``) and
#: TransportNode's python default; _queue_room's control-traffic headroom
#: math reads it, and a silent drift would either starve the FWD pump or
#: let it fill the very slots the cumulative ACKs need (tools/lint_abi.py
#: pins the three declarations together).
QUEUE_DEPTH = 8
#: ho_state chunk payload (base64 of f32 slices), sized well under the
#: DIGEST_MAX_BYTES control-message cap after JSON framing.
HO_CHUNK_ELEMS = 8192


def shard_enabled() -> bool:
    """ST_SHARD=0 force-disables the r16 capability end to end (the A/B
    escape hatch, like ST_SHM/ST_SIGN2/ST_WIRE_TRACE)."""
    return os.environ.get("ST_SHARD", "1") != "0"


class ShardFallback(Exception):
    """The parent is not sharded (pre-r16 or n_shards=0): the caller must
    fall back to the classic full-replica protocol."""


class ShardRejected(ConnectionError):
    """The cluster refused this node (claim denied, layout mismatch)."""


class _Member:
    """One ledgered member link (uplink or sharded child): the per-hop
    go-back-N state for the FWD plane."""

    __slots__ = (
        "tx_seq", "rx_count", "unacked", "progress_t", "retx_rounds",
        "ack_due",
    )

    def __init__(self):
        self.tx_seq = 0
        self.rx_count = 0
        self.unacked: list[list] = []  # [seq, bytearray, enqueue_t]
        self.progress_t = time.monotonic()
        self.retx_rounds = 0
        self.ack_due = False


class _Sub:
    """One read-only subscriber link served from an owned shard."""

    __slots__ = ("wlo", "wcnt", "tx_seq", "last_fresh_t")

    def __init__(self, wlo: int, wcnt: int):
        self.wlo = wlo
        self.wcnt = wcnt
        self.tx_seq = 0
        self.last_fresh_t = 0.0


class ShardNode:
    """One sharded cluster member (see the module docstring). Construct
    via :func:`shared_tensor_tpu.shard.create_or_fetch_sharded`, which
    handles the classic-protocol fallback."""

    def __init__(
        self,
        host: str,
        port: int,
        template: Any,
        config: Config | None = None,
    ):
        self.config = config or Config()
        scfg = self.config.shard
        if scfg.n_shards <= 0:
            raise ValueError(
                "ShardNode needs ShardConfig.n_shards > 0 "
                "(use create_or_fetch_sharded for the n_shards=0 fallback)"
            )
        if self.config.transport.wire_compat:
            raise ValueError(
                "the sharded tensor needs the native protocol (the "
                "reference compat wire has no capability hello)"
            )
        self.spec = make_spec(template)
        #: the address this node's OwnerEntry advertises (gather legs and
        #: takeover peers dial it): the configured reachable address, or
        #: the rendezvous host when unset (single-host clusters)
        self._adv_host = scfg.advertise_host or host
        if self.spec.total // 32 < scfg.n_shards:
            raise ValueError(
                f"{self.spec.total // 32} words cannot split into "
                f"{scfg.n_shards} shards"
            )
        self.scfg = scfg
        self.state = ShardState(self.spec)
        #: r17 engine lane: when eligible, the FWD hot loop (outbox pump,
        #: verbatim relay, owner dedup+apply, go-back-N) runs in the
        #: native shard plane (shard/engine_lane.py); Python keeps the
        #: control plane. Created once the shard map exists (the plane's
        #: slice geometry is the map's fixed partition). ST_SHARD_ENGINE=0
        #: / ShardConfig.engine_lane=False pin the r16 python-tier plane.
        self._lane_want = shard_engine_eligible(self.config)
        self._lane: Optional[ShardLane] = None
        self._lane_links: set[int] = set()
        #: lane-mode subscriber serving: link -> [SliceCodec of the
        #: subscribed range, conveyed values copy, owning shard]. The
        #: residual is (current slice - conveyed) computed on demand —
        #: error-feedback-equivalent without per-apply feeding, since the
        #: owned slice lives in C
        self._lane_subs: dict[int, list] = {}
        self._host = host
        self._wire_version = wire_protocol_version(self.config)
        self._codecs: dict[int, SliceCodec] = {}
        self.map: Optional[ShardMap] = None
        self._members: dict[int, _Member] = {}
        self._subs: dict[int, _Sub] = {}
        self._pending: dict[int, dict] = {}  # link -> handshake staging
        self._deferred_done: list[int] = []  # children awaiting our map
        self._route: dict[int, int] = {}  # shard -> next-hop link
        self._route_epoch: dict[int, int] = {}
        self._parked: deque = deque()  # (shard, bytearray)
        self._uplink: Optional[int] = None
        self._fwd_seq = 0
        #: origin -> (seen set, fifo of seen) — the end-to-end dedup window.
        #: Mutated by the loop thread (apply, handoff merge); _dedup_mu
        #: makes save_shards' caller-thread capture consistent — a torn
        #: window restores without a just-applied seq and double-applies.
        self._dedup: dict[int, tuple[set, deque]] = {}
        self._dedup_mu = threading.Lock()
        self._retx_total = 0
        self._claim_nonce = f"{os.getpid()}-{time.monotonic_ns()}"
        self._claim_sent_t = 0.0
        self._claim_first_t = 0.0
        self._granted = threading.Event()
        self._fallback = False
        self._error: Optional[Exception] = None
        self._leaving = False
        self._ho_stage: dict[int, dict] = {}  # shard -> incoming handoff
        self._ho_acked: set[int] = set()
        #: shards whose OUTGOING handoff state has shipped (ho_done sent,
        #: ho_ack pending): the slice snapshot already left, so applying
        #: a late FWD here would die with the released slice while the
        #: sender's ledger was ACK-debited — debited-mass conservation
        #: (spec_shard's apply_during_handoff mutation) requires routing
        #: those frames onward instead
        self._ho_sent: set[int] = set()
        self._announce_last = 0.0
        self._digest_last = 0.0
        self._child_digests: dict[int, dict] = {}
        # r18 fleet health plane: per-shard apply counts (the heat-rate
        # numerator — loop thread writes, collector reads; GIL-atomic dict
        # ops), the simulated-skew knob, and the clock-probe beat state.
        self._shard_applies: dict[int, int] = {}
        # r19 writer-side heat twins: raw outbox deposits BEFORE residual
        # coalescing (user threads write under _dep_mu, collector reads) —
        # the post-coalesce st_shard_fwd_msgs_out_total rate saturates at
        # the drain rate, so this is the only honest write-pressure signal
        self._dep_mu = threading.Lock()
        self._shard_deposits: dict[int, int] = {}
        self._shard_deposit_bytes: dict[int, int] = {}
        skew_env = os.environ.get("ST_CLOCK_SKEW_SEC", "")
        self._skew_ns = int(
            float(skew_env if skew_env else self.config.obs.clock_skew_sim_sec)
            * 1e9
        )
        self._clock_interval = self.config.obs.clock_sync_interval_sec
        self._clock_last = 0.0
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._wake = threading.Event()
        self._handoff_wanted: Optional[list[int]] = None

        # restart-restore state, loaded BEFORE joining (slices adopt at
        # grant time). The load-bearing piece is the restored DEDUP
        # WINDOWS: still-alive origins' re-routed resends keep being
        # discarded across our restart. The restored fwd_seq is only
        # forward-compat: obs ids are pid-seeded, so a reborn node mints
        # a NEW origin id and its (origin, seq) identities can't collide
        # with the old ones regardless of the counter.
        self._restored: dict[int, tuple[int, int, np.ndarray]] = {}
        self._restore_outboxes: dict[int, tuple[int, np.ndarray]] = {}
        self._takeover = False
        if scfg.restore_dir:
            self._load_restore(scfg.restore_dir)

        self.node = TransportNode(
            host,
            port,
            self.config.transport,
            frame_bytes=wire.frame_wire_bytes(self.spec),
            queue_depth=QUEUE_DEPTH,
            max_children=scfg.max_children,
            keepalive_sec=min(
                1.0, max(0.05, self.config.transport.peer_timeout_sec / 4)
            ),
        )
        self.is_master = self.node.is_master
        self.obs_id = int(self.node.obs_id)
        # r18: master = tree root = the clock reference (offset pinned
        # 0/0); the root with a health sink runs the analyzer per beat.
        self._clock = ClockSync(self._now_ns, is_root=self.is_master)
        self._health = None
        if self.is_master and self.config.obs.health_json_path:
            from ..obs.health import HealthAnalyzer

            ocfg = self.config.obs
            self._health = HealthAnalyzer(
                path=ocfg.health_json_path,
                history=ocfg.health_history,
                objective_sec=ocfg.staleness_slo_sec,
                budget=ocfg.slo_budget,
                windows=ocfg.slo_windows,
                skew_ratio=ocfg.heat_skew_ratio,
                emit=self._health_event,
            )

        self._obs_on = _obs.obs_enabled() and self.config.obs.enabled
        self._hub = _obs.hub() if self._obs_on else None
        self._reg = _obs.Registry()
        if self._lane_want:
            # engine lane: the FWD counters live in the C plane and reach
            # the registry through _collect (a registered instrument would
            # shadow the collector's value — obs/registry.py snapshot);
            # _ensure_lane re-registers the real instruments if plane
            # creation later fails and the python tier takes over
            self._m_fwd_out = _NullCounter()
            self._m_fwd_in = _NullCounter()
            self._m_relayed = _NullCounter()
            self._m_dedup = _NullCounter()
            self._m_park_drops = _NullCounter()
            self._m_updates = _NullCounter()
        else:
            self._register_py_counters()
        self._m_handoffs = self._reg.counter(
            "st_shard_handoffs_total",
            help="shard ownership handoffs completed (either side)",
        )
        self._reg.register_collector(self._collect)
        self._label = f"shard-{self.obs_id}"
        if self._hub is not None:
            self._hub.register_registry(self._label, self._reg)

        if self.is_master:
            words = self.spec.total // 32
            self.map = ShardMap(words, scfg.n_shards)
            self._ensure_lane()
            if scfg.shard_index >= 0:
                entry = OwnerEntry(
                    1, self.obs_id, self._adv_host, self.node.listen_port
                )
                self.map.merge_entry(scfg.shard_index, entry)
                self._restore_pending_outboxes()
                self._adopt(scfg.shard_index)
            else:
                # shard_index=-1 is documented as "owns no shard" for the
                # master too: it minds the map and routes, holds no slice
                # (shard 0 stays claimable by a later joiner)
                self._restore_pending_outboxes()
            self._ready.set()

        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="st-shard"
        )
        self._thread.start()

    # -- user API ------------------------------------------------------------

    def add(self, delta: Any) -> None:
        """Merge an additive update: the in-shard part applies exactly to
        the owned slices (and feeds subscriber residuals); every
        out-of-shard part accumulates into its target shard's outbox
        residual, to be drained as owner-routed FWD frames."""
        if self._leaving:
            raise RuntimeError("node is leaving (sealed)")
        m = self.map
        if m is None:
            raise RuntimeError("node not ready (no shard map yet)")
        flat = flatten_np(delta, self.spec, copy=False)
        self._admit_add(flat)
        if self._lane is not None:
            # deposit twins ride a python-side scan (the native plane
            # coalesces inside add_flat); the owns() read is racy vs a
            # concurrent adopt, which can only misattribute one beat's
            # worth of deposits — fine for a gauge
            for k in range(m.n_shards):
                elo, ehi = m.element_range(k)
                seg = flat[elo:ehi]
                if np.any(seg) and not self._lane.owns(k):
                    self._track_deposit(k, seg.size * 4)
            # engine lane: ONE native call splits in-shard (exact apply)
            # from out-of-shard (outbox deposit) under the plane's mutex
            self._lane.add_flat(
                np.ascontiguousarray(flat, np.float32)
            )
            self._wake.set()
            return
        for k in range(m.n_shards):
            elo, ehi = m.element_range(k)
            seg = flat[elo:ehi]
            if not np.any(seg):
                continue
            # ONE lock acquisition decides owned-vs-outbox AND writes: a
            # separate owns() check here would race the loop thread's
            # adopt()/release() into a stranded outbox or a spurious raise
            if self.state.add_delta(k, lambda k=k: self._codec(k), elo, seg):
                self._track_deposit(k, seg.size * 4)
        self._m_updates.inc()
        self._wake.set()

    def read_owned(self) -> dict[int, tuple[int, int, np.ndarray]]:
        """{shard: (word_lo, word_cnt, values copy)} of the owned slices —
        a node's whole resident view. Full/partial cluster views ride
        :mod:`shared_tensor_tpu.shard.gather`."""
        if self._lane is not None:
            out = {}
            for s in self.owned_shards():
                vals = self._lane.read_shard(s)
                if vals is not None:
                    wlo, wcnt = self.map.word_range(s)
                    out[s] = (wlo, wcnt, vals)
            return out
        return self.state.snapshot_owned()

    def owned_shards(self) -> list[int]:
        if self._lane is not None:
            return [
                s
                for s in range(self.map.n_shards if self.map else 0)
                if self._lane.owns(s)
            ]
        with self.state._lock:
            return sorted(self.state.owned)

    def owned_words(self) -> int:
        """Words of the table this node currently owns (lane-blind)."""
        if self._lane is not None:
            return self._lane.owned_words()
        return self.state.owned_words()

    def map_doc(self) -> dict:
        """The node's current shard-map document (geometry + owners)."""
        m = self.map
        if m is None:
            raise RuntimeError("no shard map yet")
        return m.as_doc()

    def wait_ready(self, timeout: float = 30.0) -> None:
        # the caller's explicit timeout governs this wait; ShardConfig.
        # claim_timeout_sec bounds the claim round trip itself (in
        # _maybe_claim), so a larger timeout here is never silently capped
        if not self._ready.wait(timeout):
            raise TimeoutError(
                f"shard claim/handshake incomplete after {timeout}s"
            )
        if self._fallback:
            raise ShardFallback(
                "parent is not sharded — fall back to the classic protocol"
            )
        if self._error is not None:
            raise self._error

    def drained(self, tol: float = 0.0) -> bool:
        """True when every outbox residual is idle AND every ledger is
        empty AND nothing is parked — this node owes the cluster nothing."""
        if self._lane is not None:
            return self._lane.idle(tol)
        if not self.state.outboxes_idle(tol):
            return False
        if self._parked:
            return False
        # list() snapshots: the loop thread adds/pops members (welcome,
        # link-down teardown) while this caller-thread poll iterates
        return all(not m.unacked for m in list(self._members.values()))

    def drain(self, timeout: float = 60.0, tol: float = 0.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.drained(tol):
                return True
            self._wake.set()
            time.sleep(0.02)
        return False

    def alloc_bytes(self) -> int:
        """Resident f32 state bytes (the chaos harness's per-node bound)."""
        if self._lane is not None:
            # C-resident slices/outboxes + the python-side conveyed
            # copies backing lane-mode subscriber serving
            extra = sum(
                ent[1].nbytes for ent in list(self._lane_subs.values())
            )
            return self._lane.alloc_bytes() + extra
        return self.state.alloc_bytes()

    def metrics(self) -> dict:
        return self._reg.snapshot()

    def leave(self, timeout: float = 60.0) -> bool:
        """Graceful departure: seal local adds, drain everything owed,
        hand every owned shard to the parent (ownership + slice + dedup
        state), then close. Returns False if any phase timed out (the
        node still closes; un-handed shards need a takeover restore).
        The master cannot leave a cluster that still has members —
        there is no map-authority handoff (documented limitation)."""
        self._leaving = True
        ok = self.drain(timeout=timeout * 0.5)
        shards = self.owned_shards()
        if shards and self._uplink is not None:
            self._ho_acked.clear()
            self._wake.set()
            deadline = time.monotonic() + timeout * 0.5
            # the loop thread runs the handoff (serialized with every
            # other protocol action); we just wait for the acks
            self._handoff_wanted = list(shards)
            while time.monotonic() < deadline:
                if all(s in self._ho_acked for s in shards):
                    break
                self._wake.set()
                time.sleep(0.02)
            ok = ok and all(s in self._ho_acked for s in shards)
            # frames that arrived mid-handoff were relayed/unparked onto
            # the uplink ledger — they are mass we still OWE the
            # successor; closing before their ACKs drops them
            ok = ok and self.drain(
                timeout=max(1.0, deadline - time.monotonic())
            )
        elif shards:
            ok = False  # nowhere to hand off (master / orphan)
        self.close()
        return ok

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5.0)
        if self._hub is not None:
            self._hub.unregister_registry(self._label)
        if self._lane is not None:
            # the plane's threads block inside the node's queues/condvars:
            # stop+destroy strictly BEFORE TransportNode.close (the
            # engine/peer.py teardown ordering)
            self._lane.destroy()
        self.node.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- checkpoint ----------------------------------------------------------

    def save_shards(self, dirpath: str) -> Optional[dict]:
        """Write this node's sharded checkpoint (owned slices + outbox
        residuals + dedup windows + fwd_seq) and return its manifest
        entry, or None when the node owns nothing and owes nothing.
        Quiesce first (``drain()``) for an exact capture."""
        from ..utils import checkpoint as ckpt

        if self._lane is not None:
            # the plane captures slices + outboxes + windows under its
            # ONE mutex (st_shard_snapshot) — same no-torn-pair contract
            lowned, loutboxes, ldedup = self._lane.snapshot()
            owned = {}
            for s, vals in lowned.items():
                wlo, wcnt = self.map.word_range(s)
                owned[s] = (wlo, wcnt, vals)
            outboxes = {
                s: (self.map.word_range(s)[0], r)
                for s, r in loutboxes.items()
            }
            dedup = {str(o): sorted(seqs) for o, seqs in ldedup.items()}
            fwd_seq = self._lane.fwd_seq()
        else:
            with self._dedup_mu:
                # one mutex covers slices AND windows (_apply_fwd commits
                # both under it), so even a live capture can't persist a
                # window seq whose mass missed the slice
                owned = self.state.snapshot_owned()
                outboxes = self.state.snapshot_outboxes()
                dedup = {
                    str(origin): sorted(seen)
                    for origin, (seen, _fifo) in self._dedup.items()
                }
            fwd_seq = self._fwd_seq
        if not owned and not outboxes:
            return None
        return ckpt.save_shard_state(
            dirpath,
            self.node_name,
            self.spec.layout_digest(),
            owned,
            outboxes,
            dedup,
            fwd_seq,
        )

    @property
    def node_name(self) -> str:
        name = self.config.lifecycle.node_name
        return name if name else f"node-{self.obs_id}"

    def _load_restore(self, dirpath: str) -> None:
        from ..utils import checkpoint as ckpt

        name = self.config.lifecycle.node_name
        if not name:
            raise ValueError(
                "restore_dir needs a stable LifecycleConfig.node_name "
                "(obs ids are not stable across restarts)"
            )
        path = os.path.join(dirpath, ckpt.shard_filename(name))
        doc = ckpt.load_shard_state(path)
        if doc["layout"] != self.spec.layout_digest():
            raise ValueError(
                "sharded checkpoint layout does not match this table"
            )
        self._restored = dict(doc["owned"])
        self._restore_outboxes = dict(doc["outboxes"])
        for origin, seqs in doc["dedup"].items():
            fifo = deque(seqs)
            self._dedup[int(origin)] = (set(seqs), fifo)
        self._fwd_seq = int(doc["fwd_seq"])
        self._takeover = True

    # -- observability -------------------------------------------------------

    def _track_deposit(self, shard: int, nbytes: int) -> None:
        with self._dep_mu:
            self._shard_deposits[shard] = (
                self._shard_deposits.get(shard, 0) + 1
            )
            self._shard_deposit_bytes[shard] = (
                self._shard_deposit_bytes.get(shard, 0) + nbytes
            )

    def _collect(self) -> dict:
        if self._lane is not None:
            c = self._lane.counters()
            out = {
                "st_shard_owned_words": self._lane.owned_words(),
                "st_shard_alloc_bytes": self.alloc_bytes(),
                "st_shard_routes": len(self._route),
                "st_shard_parked_msgs": int(c[5]),
                # engine-tier counter twins, served off the C plane's
                # counters ABI under the SAME canonical names the python
                # tier registers — obs.top's shard column and the chaos
                # harness's tallies stay lane-blind
                "st_shard_fwd_msgs_out_total": int(c[0]),
                "st_shard_fwd_msgs_in_total": int(c[1]),
                "st_shard_fwd_relayed_total": int(c[2]),
                "st_shard_fwd_dedup_total": int(c[3]),
                "st_shard_park_drops_total": int(c[4]),
                "st_shard_fwd_frames_in_total": int(c[9]),
                "st_shard_fwd_retx_total": int(c[6]),
                "st_updates_total": int(c[7]),
                "st_shard_outbox_bytes": self._lane.outbox_bytes(),
            }
            # r18 heat numerator, lane mode: the counters ABI keeps one
            # apply total; the lane attributes it across the owned shards
            # (exact in the one-owned-shard topology)
            for s, n in self._lane.heat_applies_by_shard(
                int(c[1]), self.owned_shards()
            ).items():
                out[_schema.shard_key("st_shard_heat_applies", s)] = n
        else:
            out = {
                "st_shard_owned_words": self.state.owned_words(),
                "st_shard_alloc_bytes": self.state.alloc_bytes(),
                "st_shard_routes": len(self._route),
                "st_shard_parked_msgs": len(self._parked),
                "st_shard_fwd_frames_in_total": self.state.applies,
                "st_shard_fwd_retx_total": self._retx_total,
                "st_shard_outbox_bytes": self.state.outbox_bytes(),
            }
            # r18 heat numerators, python tier: exact per-shard apply
            # counts (tracked in _apply_fwd) and the live nonzero outbox
            # backlog destined to each non-owned shard
            for s, n in list(self._shard_applies.items()):
                out[_schema.shard_key("st_shard_heat_applies", s)] = n
            for s, b in self.state.outbox_backlog_by_shard().items():
                out[_schema.shard_key("st_shard_heat_outbox_bytes", s)] = b
        # r19 pre-coalesce deposit twins (lane-blind, writer-side): the
        # raw deposit rate vs the st_shard_fwd_msgs_out_total drain rate
        # is the coalescing ratio — a saturated writer shows deposits
        # racing ahead while msgs_out flatlines at the drain ceiling
        with self._dep_mu:
            deposits = dict(self._shard_deposits)
            deposit_bytes = dict(self._shard_deposit_bytes)
        for s, n in deposits.items():
            out[_schema.shard_key("st_shard_heat_deposit_msgs", s)] = n
        for s, b in deposit_bytes.items():
            out[_schema.shard_key("st_shard_heat_deposit_bytes", s)] = b
        out["st_shard_outbox_limit_bytes"] = self.scfg.outbox_limit_bytes
        if self._clock.known:
            out["st_clock_offset_seconds"] = self._clock.offset_seconds
            out["st_clock_uncertainty_seconds"] = (
                self._clock.uncertainty_seconds
            )
        out["st_clock_probes_total"] = self._clock.probes
        if self._health is not None:
            out.update(self._health.metrics())
        return out

    def _event(self, name: str, link: int = 0, arg: int = 0) -> None:
        if self._hub is not None:
            self._hub.emit(name, node=self.obs_id, link=link, arg=arg)

    def _now_ns(self) -> int:
        """Monotonic ns plus the simulated clock skew (r18; comm/peer.py
        twin) — every cross-node-comparable stamp routes through here."""
        return time.monotonic_ns() + self._skew_ns

    def _health_event(self, name: str, arg: int, detail: str) -> None:
        if self._hub is not None:
            self._hub.emit(name, node=self.obs_id, arg=arg, detail=detail)

    # -- codec / slices ------------------------------------------------------

    def _codec(self, shard: int) -> SliceCodec:
        c = self._codecs.get(shard)
        if c is None:
            wlo, wcnt = self.map.word_range(shard)
            c = self._codecs[shard] = SliceCodec(self.spec, wlo, wcnt)
        return c

    def _owns(self, shard: int) -> bool:
        """Lane-blind ownership check (control-plane call sites)."""
        if self._lane is not None:
            return self._lane.owns(shard)
        return self.state.owns(shard)

    # -- r17 engine lane -----------------------------------------------------

    def _register_py_counters(self) -> None:
        """The python-tier FWD plane's registry instruments — created at
        init when the lane is ineligible, or at _ensure_lane's failure
        fallback (the _NullCounter placeholders would otherwise silence
        park drops and every FWD tally for the python plane's lifetime)."""
        self._m_fwd_out = self._reg.counter(
            "st_shard_fwd_msgs_out_total",
            help="FWD frames this node originated onto the wire",
        )
        self._m_fwd_in = self._reg.counter(
            "st_shard_fwd_msgs_in_total",
            help="FWD frames applied to an owned shard",
        )
        self._m_relayed = self._reg.counter(
            "st_shard_fwd_relayed_total",
            help="FWD frames forwarded verbatim toward their owner",
        )
        self._m_dedup = self._reg.counter(
            "st_shard_fwd_dedup_total",
            help="FWD frames discarded by the owner's (origin, fwd_seq) dedup",
        )
        self._m_park_drops = self._reg.counter(
            "st_shard_park_drops_total",
            help="parked FWD frames dropped at the park-buffer cap",
        )
        self._m_updates = self._reg.counter(
            "st_updates_total", help="local add() calls merged"
        )

    def _ensure_lane(self, newmap: Optional[ShardMap] = None) -> None:
        """Create the native shard plane once the map exists (its slice
        geometry is the map's fixed partition), seed it with any restored
        dedup windows / fwd_seq, and attach every member the handshake
        already admitted. Joiners pass the JUST-DECODED map BEFORE
        publishing self.map: add() gates on `map is not None` from the
        caller's thread, so the lane must exist by the time the map is
        visible or a racing add() would deposit into the python-tier
        outboxes nothing ever pumps. Falls back to the python-tier plane
        (loudly, with its registry instruments restored) if creation
        fails — never silently loses the node."""
        m = newmap if newmap is not None else self.map
        if not self._lane_want or self._lane is not None or m is None:
            return
        from ..comm.engine import _POLICY_CODE

        try:
            lane = ShardLane(
                self.node,
                self.spec,
                [m.word_range(s) for s in range(m.n_shards)],
                _POLICY_CODE[self.config.codec.scale_policy],
                wire.frame_wire_bytes(self.spec),
                self.config.transport.ack_timeout_sec,
                self.config.transport.ack_retry_limit,
                self.scfg.park_cap,
                self.obs_id,
            )
        except Exception as e:
            log.warning(
                "engine shard lane unavailable (%s): running the "
                "python-tier FWD plane", e,
            )
            self._lane_want = False
            self._register_py_counters()
            return
        self._lane = lane
        with self._dedup_mu:
            for origin, (seen, _fifo) in self._dedup.items():
                lane.dedup_merge(origin, seen)
        lane.set_fwd_seq(self._fwd_seq)
        if self._uplink is not None:
            lane.set_uplink(self._uplink)
        for link, m in self._members.items():
            if lane.member_attach(link, m.tx_seq, m.rx_count):
                self._lane_links.add(link)

    def _lane_attach(self, link: int) -> None:
        m = self._members.get(link)
        if self._lane is not None and m is not None:
            if self._lane.member_attach(link, m.tx_seq, m.rx_count):
                self._lane_links.add(link)

    def _admit_add(self, flat: np.ndarray) -> None:
        """Library-side writer admission control (ROADMAP 1(d)): with
        ShardConfig.outbox_limit_bytes set, an add() whose out-of-shard
        deposits would push resident outbox bytes past the limit BLOCKS
        until the FWD plane drains room (or raises, per outbox_overflow).
        The projection is conservative at slice granularity: each target
        shard of this delta counts one full outbox slice, whether or not
        one is already allocated."""
        limit = self.scfg.outbox_limit_bytes
        if limit <= 0:
            return
        m = self.map
        need = 0
        for k in range(m.n_shards):
            elo, ehi = m.element_range(k)
            if not np.any(flat[elo:ehi]):
                continue
            if self._lane is not None:
                owned = self._lane.owns(k)
            else:
                owned = self.state.owns(k)
            if not owned:
                need += (ehi - elo) * 4
        if need == 0:
            return
        outbox_bytes = (
            self._lane.outbox_bytes
            if self._lane is not None
            else self.state.outbox_bytes
        )
        if outbox_bytes() + need <= limit:
            return
        if self.scfg.outbox_overflow == "raise":
            raise ShardBackpressure(
                f"outbox {outbox_bytes()} B + {need} B new > "
                f"limit {limit} B"
            )
        deadline = time.monotonic() + self.scfg.outbox_block_timeout_sec
        while time.monotonic() < deadline:
            if outbox_bytes() + need <= limit:
                return
            self._wake.set()
            time.sleep(0.002)
        raise ShardBackpressure(
            f"outbox stayed over {limit} B for "
            f"{self.scfg.outbox_block_timeout_sec}s (link stalled?)"
        )

    def _restore_pending_outboxes(self) -> None:
        """Re-seat checkpointed outbox residuals once the map exists
        (their geometry needs the shard ranges). Outboxes toward shards
        we end up owning fold at adopt time instead."""
        for s, (_wlo, resid) in list(self._restore_outboxes.items()):
            if self._lane is not None:
                if not self._lane.owns(s):
                    self._lane.restore_outbox(s, resid)
            elif not self.state.owns(s):
                self.state.restore_outbox(s, self._codec(s), resid)
            self._restore_outboxes.pop(s, None)

    def _adopt(self, shard: int) -> None:
        wlo, wcnt = self.map.word_range(shard)
        rest = self._restored.pop(shard, None)
        vals = rest[2] if rest is not None else None
        if self._lane is not None:
            self._lane.adopt(shard, vals)
        else:
            self.state.adopt(shard, wlo, wcnt, vals)
        self._route.pop(shard, None)
        self._event("shard_adopt", arg=shard)

    def _release_owned(self, shard: int):
        """Release ownership of one shard AND close every subscriber link
        served from its range: the slice will never update here again, so
        a surviving sub link would keep receiving FRESH beats over frozen
        values — silently-stale verified reads, the exact failure the
        serving tier refuses. A dropped link makes the subscriber
        resync/redial against the new owner."""
        if self._lane is not None:
            released = self._lane.release(shard)
        else:
            released = self.state.release(shard)
        if released is None or self.map is None:
            return released
        wlo, wcnt = self.map.word_range(shard)
        for l, sub in list(self._subs.items()):
            if wlo <= sub.wlo < wlo + wcnt:
                self._subs.pop(l, None)
                self.state.drop_sub(l)
                self.node.drop_link(l)
        for l, ent in list(self._lane_subs.items()):
            if ent[2] == shard:
                self._lane_subs.pop(l, None)
                self._subs.pop(l, None)
                self.node.drop_link(l)
        return released

    # -- control-plane sends -------------------------------------------------

    def _send_ctrl(self, link: int, payload: bytes) -> bool:
        for _ in range(40):
            if self._stop.is_set():
                return False
            try:
                if self.node.send(link, payload, timeout=0.05):
                    return True
            except BrokenPipeError:
                return False
        return False

    def _all_links(self) -> list[int]:
        out = list(self._members)
        for l in (self._uplink,):
            if l is not None and l not in out:
                out.append(l)
        return out

    def _flood_shard(self, doc: dict, exclude: Optional[int] = None) -> None:
        doc.setdefault("from", self.obs_id)
        payload = wire.encode_shard(doc)
        for link in self._all_links():
            if link != exclude:
                self._send_ctrl(link, payload)

    def _announce_owned(self, only_link: Optional[int] = None) -> None:
        for shard in self.owned_shards():
            e = self.map.owners[shard]
            doc = {
                "t": "own", "shard": shard, "epoch": e.epoch,
                "owner": self.obs_id, "from": self.obs_id,
            }
            payload = wire.encode_shard(doc)
            targets = [only_link] if only_link is not None else self._all_links()
            for link in targets:
                self._send_ctrl(link, payload)

    # -- FWD plane: ledger / routing ----------------------------------------

    def _ledger_send(self, link: int, payload) -> bool:
        """Ledger + send one FWD on a member link. False = window full or
        unknown link (the caller keeps the mass where it was)."""
        m = self._members.get(link)
        if m is None or len(m.unacked) >= SEND_WINDOW:
            return False
        m.tx_seq += 1
        buf = bytearray(payload)
        wire.fwd_restamp(buf, m.tx_seq)
        if not m.unacked:
            m.progress_t = time.monotonic()
        m.unacked.append([m.tx_seq, buf, time.monotonic()])
        self._send_raw(link, buf)
        return True

    def _send_raw(self, link: int, buf: bytearray) -> None:
        """Best-effort wire write: a bounce (backpressure) is fine — the
        entry is already ledgered, and the go-back-N retransmission path
        re-sends the head until ACK progress resumes."""
        try:
            self.node.send(link, memoryview(buf), timeout=0.05)
        except BrokenPipeError:
            pass  # LINK_DOWN will re-route the ledger

    def _fwd_shard_of(self, buf) -> int:
        (word_lo,) = struct.unpack_from("<I", buf, 5)
        return self.map.shard_of_word(word_lo)

    def _next_hop(self, shard: int, exclude: Optional[int] = None):
        link = self._route.get(shard)
        if link is not None and link != exclude and link in self._members:
            return link
        up = self._uplink
        if up is not None and up != exclude and up in self._members:
            return up
        return None

    def _park(self, shard: int, buf: bytearray) -> None:
        self._parked.append((shard, buf))
        while len(self._parked) > self.scfg.park_cap:
            self._parked.popleft()
            # loud bounded loss, never unbounded memory (ShardConfig
            # .park_cap); the origin's mass is gone — count it
            self._m_park_drops.inc()
            self._event("shard_park_drop")

    def _unpark(self, shard: Optional[int] = None) -> None:
        if not self._parked:
            return
        keep: deque = deque()
        for s, buf in self._parked:
            if shard is not None and s != shard:
                keep.append((s, buf))
                continue
            if not self._dispatch_fwd(s, buf, arrival=None):
                keep.append((s, buf))
        self._parked = keep

    def _dispatch_fwd(self, shard: int, buf: bytearray, arrival) -> bool:
        """Apply locally (owner), relay toward the owner, or fail (caller
        parks). Never sends back on the arrival link. A shard mid-
        outgoing-handoff is NOT locally applicable (its snapshot already
        shipped); the frame relays toward the successor — per-link FIFO
        puts it behind the ho_done on the uplink, so the successor owns
        the slice before the frame lands — or parks until the
        successor's announce supplies the route."""
        if self.state.owns(shard) and shard not in self._ho_sent:
            try:
                self._apply_fwd(buf, shard)
            except (ValueError, struct.error) as e:
                # relays forward verbatim without decoding, so a frame a
                # fault corrupted upstream is first DECODED here — at the
                # owner, possibly straight out of the park buffer or a
                # link-down re-dispatch, where no per-message guard wraps
                # us. Drop it loudly instead of killing the loop thread.
                log.warning(
                    "dropping undecodable FWD frame for shard %d: %s",
                    shard, e,
                )
            return True
        link = self._next_hop(shard, exclude=arrival)
        if link is None:
            return False
        if self._ledger_send(link, buf):
            if arrival is not None:
                self._m_relayed.inc()
            return True
        return False

    def _apply_fwd(self, buf, shard: int) -> None:
        """Owner-side apply with end-to-end dedup. Only the loop thread
        calls this (right after _dispatch_fwd's ownership check, with no
        release possible in between — one thread owns the protocol), so
        ownership is a precondition, not a race."""
        frames, word_lo, _seq, origin, fwd_seq = wire.decode_fwd(
            bytes(buf), self.spec
        )
        with self._dedup_mu:
            # the dedup-add and the slice apply commit TOGETHER under
            # this mutex (lock order: _dedup_mu -> state._lock), so
            # save_shards' capture under the same mutex always persists
            # a consistent pair — a window seq whose mass is missing
            # from the slice would make the restored owner discard that
            # frame's re-routed resend: silent cluster mass loss
            seen, fifo = self._dedup.setdefault(origin, (set(), deque()))
            if fwd_seq in seen:
                self._m_dedup.inc()
                return
            seen.add(fwd_seq)
            fifo.append(fwd_seq)
            while len(fifo) > DEDUP_WINDOW:
                seen.discard(fifo.popleft())
            applied = False
            for scales, words in frames:
                # the burst's halvings apply in order — one dedup
                # identity covers the whole message (one ledger entry,
                # one apply-or-discard decision)
                applied |= self.state.apply_owned(scales, words, word_lo)
        if applied:
            self._m_fwd_in.inc()
            # r18: exact per-shard attribution — the heat-rate numerator
            self._shard_applies[shard] = self._shard_applies.get(shard, 0) + 1

    def _queue_room(self, link: int, keep: int = 3) -> bool:
        """True when the transport send queue has at least ``keep`` free
        slots. The data pumps must never fill the queue to the brim: the
        cumulative ACKs and shard control messages share it, and a pump
        that races them for the last slot starves the very ACKs that let
        its own ledger drain (the first drain smoke wedged exactly
        there — both ends idle, ack_due stuck on a full queue)."""
        st = self.node.stats(link)
        if st is None:
            return False
        return st.send_queue <= QUEUE_DEPTH - keep

    def _pump_outboxes(self) -> None:
        for shard in self.state.outbox_shards():
            if self.state.owns(shard):
                continue  # adopt() folds; nothing to send
            link = self._next_hop(shard)
            if link is None:
                continue  # mass stays in the residual until a route heals
            if not self._queue_room(link):
                continue
            m = self._members.get(link)
            for _ in range(OUTBOX_MSGS_PER_PASS):
                if m is None or len(m.unacked) >= SEND_WINDOW:
                    break
                out = self.state.drain_outbox_frames(
                    shard,
                    self.config.codec.scale_policy,
                    wire.fwd_frames_cap(self.spec, self._codec(shard).word_cnt),
                )
                if out is None:
                    break
                frames, wlo = out
                self._fwd_seq += 1
                payload = wire.encode_fwd(
                    frames, wlo, 0, self.obs_id, self._fwd_seq
                )
                self._ledger_send(link, payload)
                self._m_fwd_out.inc()

    def _check_retransmit(self) -> None:
        timeout = self.config.transport.ack_timeout_sec
        if timeout <= 0:
            return
        limit = max(1, self.config.transport.ack_retry_limit)
        now = time.monotonic()
        for link, m in list(self._members.items()):
            if not m.unacked:
                continue
            if now - m.progress_t < timeout * (1 + m.retx_rounds):
                continue
            m.retx_rounds += 1
            if m.retx_rounds > limit:
                log.warning(
                    "link %d: %d retransmission rounds with no ACK "
                    "progress — tearing down for re-route", link,
                    m.retx_rounds - 1,
                )
                self.node.drop_link(link)  # LINK_DOWN re-routes the ledger
                continue
            m.progress_t = now
            self._retx_total += min(len(m.unacked), RETX_PREFIX)
            for seq, buf, _t in m.unacked[:RETX_PREFIX]:
                self._send_raw(link, buf)

    def _flush_acks(self) -> None:
        for link, m in self._members.items():
            if m.ack_due:
                try:
                    # ack_due stays set on a backpressure bounce — a
                    # silently dropped cumulative ACK would strand the
                    # sender's tail until its go-back-N gives up (found
                    # by the first drain smoke: ~10 frames wedged per
                    # link with both ends idle)
                    if self.node.send(
                        link, wire.encode_ack(m.rx_count), timeout=0.05
                    ):
                        m.ack_due = False
                except BrokenPipeError:
                    m.ack_due = False

    # -- serve tier ----------------------------------------------------------

    def _attach_sub(self, link: int, rng: Optional[tuple[int, int]]) -> None:
        words = self.spec.total // 32
        wlo, wcnt = rng if rng is not None else (0, words)
        try:
            if self._lane is not None:
                seed = self._lane_attach_sub(link, wlo, wcnt)
            else:
                seed = self.state.attach_sub(link, wlo, wcnt)
        except ValueError as e:
            self._send_ctrl(link, wire.encode_reject(
                f"{e} (a sharded owner serves subscriptions only within "
                f"its owned shards)"
            ))
            self.node.drop_link_flushed(link)
            return
        self._subs[link] = sub = _Sub(wlo, wcnt)
        self._send_ctrl(link, wire.encode_welcome())
        for chunk in wire.encode_snapshot_chunks(seed):
            self._send_ctrl(link, chunk)
        sub.last_fresh_t = time.monotonic()
        self._send_ctrl(
            link, wire.encode_fresh(self._now_ns(), sub.tx_seq)
        )
        self._event("sub_attach", link, wcnt)

    def _lane_attach_sub(self, link: int, wlo: int, wcnt: int) -> np.ndarray:
        """Lane-mode subscriber attach: the owned slice lives in C, so the
        serve-tier residual is tracked as (current - conveyed) instead of
        per-apply feeding — error-feedback-equivalent and self-correcting
        (the quantize ladder drains the DIFFERENCE, whatever path the
        slice took). Returns the seed snapshot; raises ValueError when no
        owned shard covers the range (the REJECT path)."""
        for s in self.owned_shards():
            swlo, swcnt = self.map.word_range(s)
            if swlo <= wlo and wlo + wcnt <= swlo + swcnt:
                vals = self._lane.read_shard(s)
                if vals is None:
                    break
                i0 = (wlo - swlo) * 32
                seed = vals[i0:i0 + wcnt * 32].copy()
                sc = SliceCodec(self.spec, wlo, wcnt)
                self._lane_subs[link] = [sc, seed.copy(), s]
                return seed
        raise ValueError(
            f"subscription [{wlo}, {wlo + wcnt}) not within any owned shard"
        )

    def _lane_sub_frame(self, link: int):
        """One RDATA frame off a lane-mode subscriber's conveyed-diff
        residual (None = idle/unknown), plus idle bookkeeping."""
        ent = self._lane_subs.get(link)
        if ent is None:
            return None
        sc, conveyed, shard = ent
        vals = self._lane.read_shard(shard)
        if vals is None:
            return None
        swlo, _sw = self.map.word_range(shard)
        i0 = (sc.word_lo - swlo) * 32
        cur = vals[i0:i0 + sc.n_el]
        r = cur - conveyed
        if not np.any(r):
            return None
        scales, words, new_r = sc.quantize(
            r, self.config.codec.scale_policy
        )
        if not scales.any():
            return None
        ent[1] = cur - new_r  # conveyed advances by exactly what shipped
        return scales, words, sc.word_lo, sc.word_cnt

    def _lane_sub_idle(self, link: int) -> bool:
        ent = self._lane_subs.get(link)
        if ent is None:
            return True
        sc, conveyed, shard = ent
        vals = self._lane.read_shard(shard)
        if vals is None:
            return True
        swlo, _sw = self.map.word_range(shard)
        i0 = (sc.word_lo - swlo) * 32
        return bool(np.array_equal(vals[i0:i0 + sc.n_el], conveyed))

    def _pump_subs(self) -> None:
        fresh_iv = self.config.serve.fresh_interval_sec
        now = time.monotonic()
        for link, sub in list(self._subs.items()):
            if not self._queue_room(link):
                # a bounced RDATA is a LOST frame on the unledgered link
                # (the residual was already debited) — don't even
                # quantize until there is room
                continue
            if self._lane is not None:
                out = self._lane_sub_frame(link)
            else:
                out = self.state.sub_frame(
                    link, self.config.codec.scale_policy
                )
            if out is not None:
                scales, words, wlo, wcnt = out
                sub.tx_seq += 1
                payload = wire.encode_rdata(
                    TableFrame(scales, words),
                    0,
                    wcnt,
                    sub.tx_seq,
                    trace=(self.obs_id, self._now_ns(), 0),
                )
                # encode_rdata slices [word_lo:word_lo+cnt] out of the
                # frame's words; our words ARE the slice already, so the
                # wire range header is patched to the true word_lo
                buf = bytearray(payload)
                struct.pack_into("<I", buf, 5, wlo)
                try:
                    self.node.send(link, memoryview(buf), timeout=0.05)
                except BrokenPipeError:
                    continue
            elif (
                (
                    self._lane_sub_idle(link)
                    if self._lane is not None
                    else self.state.sub_idle(link)
                )
                and now - sub.last_fresh_t >= fresh_iv
            ):
                sub.last_fresh_t = now
                try:
                    self.node.send(
                        link,
                        wire.encode_fresh(self._now_ns(), sub.tx_seq),
                        timeout=0.05,
                    )
                except BrokenPipeError:
                    continue

    # -- handoff -------------------------------------------------------------

    def _run_handoffs(self) -> None:
        wanted = getattr(self, "_handoff_wanted", None)
        if not wanted or self._uplink is None:
            return
        up = self._uplink
        send_dedup = True
        for shard in list(wanted):
            if self._lane is not None:
                # conservation across the capture/send window: the C
                # receiver applies CONCURRENTLY with this thread (the
                # python tier is safe by its single loop thread), so the
                # relay-onward flag must be up BEFORE the slice is read —
                # a frame applied after the read would die with the
                # released slice (spec_shard's apply_during_handoff)
                self._lane.set_handoff(shard, True)
                vals = self._lane.read_shard(shard)
                if vals is None:
                    self._lane.set_handoff(shard, False)
                    wanted.remove(shard)
                    continue
                c = self._codec(shard)
            else:
                ent = self.state.owned_entry(shard)
                if ent is None:
                    wanted.remove(shard)
                    continue
                c, vals = ent
            epoch = self.map.owners[shard].epoch + 1
            ok = self._send_ctrl(up, wire.encode_shard({
                "t": "ho_meta", "shard": shard, "word_lo": c.word_lo,
                "word_cnt": c.word_cnt, "epoch": epoch,
                "from": self.obs_id,
            }))
            raw = np.ascontiguousarray(vals, "<f4").tobytes()
            step = HO_CHUNK_ELEMS * 4
            for off in range(0, len(raw), step):
                if not ok:
                    break
                ok = self._send_ctrl(up, wire.encode_shard({
                    "t": "ho_state", "shard": shard, "off": off,
                    "data": base64.b64encode(raw[off:off + step]).decode(),
                    "from": self.obs_id,
                }))
            # the dedup windows ride along: without them, a
            # retransmission of a frame WE applied but never acked
            # would double-apply at the successor (the spec_shard
            # red-team mutation). They are per-ORIGIN node state, not
            # per-shard — ship them once per leave (with the first shard
            # of the batch); the successor merges into its global window
            # at that shard's ho_done, before any adopted slice can see
            # a replayed frame
            if ok and send_dedup:
                if self._lane is not None:
                    # windows alone — the full snapshot would copy every
                    # owned slice under the plane mutex just to discard it
                    windows = {
                        int(o): sorted(seqs)
                        for o, seqs in self._lane.dedup_windows().items()
                    }
                else:
                    with self._dedup_mu:
                        windows = {
                            int(origin): sorted(seen)
                            for origin, (seen, _fifo) in self._dedup.items()
                        }
                for origin, seqs in windows.items():
                    for off in range(0, len(seqs), 4096):
                        if not ok:
                            break
                        ok = self._send_ctrl(up, wire.encode_shard({
                            "t": "ho_dedup", "shard": shard,
                            "origin": origin,
                            "seqs": seqs[off:off + 4096],
                            "from": self.obs_id,
                        }))
            if ok:
                ok = self._send_ctrl(up, wire.encode_shard({
                    "t": "ho_done", "shard": shard, "from": self.obs_id,
                }))
            if not ok:
                # a bounced control send means the staged transfer has a
                # hole — ho_done would let the successor adopt a zero-
                # filled slice and ho_ack would release the true one
                # (silent mass loss). Leave the shard in `wanted` and
                # retry next pass: the fresh ho_meta resets the stage.
                log.warning(
                    "shard %d handoff send bounced; retrying next pass",
                    shard,
                )
                if self._lane is not None:
                    # resume local ownership: frames relayed upstream in
                    # the window self-heal (routes still point here)
                    self._lane.set_handoff(shard, False)
                return
            send_dedup = False
            self._ho_sent.add(shard)
            wanted.remove(shard)

    def _on_ho(self, link: int, doc: dict) -> None:
        t = doc["t"]
        shard = int(doc.get("shard", -1))
        if t == "ho_meta":
            self._ho_stage[shard] = {
                "word_lo": int(doc["word_lo"]),
                "word_cnt": int(doc["word_cnt"]),
                "epoch": int(doc["epoch"]),
                "buf": bytearray(int(doc["word_cnt"]) * 32 * 4),
                "dedup": {},
                "link": link,
            }
        elif t == "ho_state":
            st = self._ho_stage.get(shard)
            if st is not None:
                off = int(doc["off"])
                data = base64.b64decode(doc["data"])
                st["buf"][off:off + len(data)] = data
        elif t == "ho_dedup":
            st = self._ho_stage.get(shard)
            if st is not None:
                st["dedup"].setdefault(
                    int(doc["origin"]), []
                ).extend(int(s) for s in doc.get("seqs", ()))
        elif t == "ho_done":
            st = self._ho_stage.pop(shard, None)
            if st is None:
                return
            vals = np.frombuffer(bytes(st["buf"]), "<f4").copy()
            if self._lane is not None:
                self._lane.adopt(shard, vals)
                for origin, seqs in st["dedup"].items():
                    self._lane.dedup_merge(origin, seqs)
            else:
                self.state.adopt(shard, st["word_lo"], st["word_cnt"], vals)
                for origin, seqs in st["dedup"].items():
                    with self._dedup_mu:
                        seen, fifo = self._dedup.setdefault(
                            origin, (set(), deque())
                        )
                        merged = sorted(set(seqs) | seen)[-DEDUP_WINDOW:]
                        seen.clear()
                        seen.update(merged)
                        fifo.clear()
                        fifo.extend(merged)
            entry = OwnerEntry(
                st["epoch"], self.obs_id, self._adv_host, self.node.listen_port
            )
            self.map.merge_entry(shard, entry)
            self._route.pop(shard, None)
            self._flood_shard({
                "t": "grant", "shard": shard, "e": entry.as_doc(),
                "nonce": "",
            })
            self._announce_owned()
            self._send_ctrl(link, wire.encode_shard({
                "t": "ho_ack", "shard": shard, "from": self.obs_id,
            }))
            self._m_handoffs.inc()
            self._event("shard_handoff", link, shard)
            self._unpark(shard)
        elif t == "ho_ack":
            released = self._release_owned(shard)
            if released is not None:
                self._event("shard_release", link, shard)
                self._m_handoffs.inc()
            self._ho_sent.discard(shard)
            self._ho_acked.add(shard)

    # -- shard control plane -------------------------------------------------

    def _on_shard_msg(self, link: int, doc: dict) -> None:
        t = doc.get("t")
        if t == "map":
            changed = False
            if self.map is None:
                newmap = ShardMap.from_doc(doc["map"])
                # lane BEFORE the map publishes: add() gates on the map
                # from the caller's thread, and a delta deposited into
                # the python-tier outboxes in the gap would be stranded
                # (lane mode never pumps them)
                self._ensure_lane(newmap)
                self.map = newmap
                self._restore_pending_outboxes()
                changed = True
            else:
                changed = self.map.merge_doc(doc["map"])
            self._maybe_claim()
            for child in list(self._deferred_done):
                self._deferred_done.remove(child)
                self._welcome_member(child)
            if changed:
                self._wake.set()
        elif t == "claim":
            if self.is_master:
                self._arbitrate(doc)
            elif self._uplink is not None:
                self._send_ctrl(self._uplink, wire.encode_shard(doc))
            # uplink down mid-claim: drop — the claimer retries every 1 s
        elif t == "grant":
            shard = int(doc["shard"])
            entry = OwnerEntry.from_doc(doc["e"])
            if self.map is not None and self.map.merge_entry(shard, entry):
                self._flood_shard(dict(doc), exclude=link)
            # act on the DIRECTORY's current entry, not the message's: the
            # master's flood and a handoff successor's flood are separate
            # minters with no cross-link ordering, so a stale duplicate
            # grant can arrive AFTER the handoff that moved the shard
            # elsewhere — adopting (or releasing) on its say-so would
            # re-create two-owner split-brain
            cur = (
                self.map.owner_of_shard(shard)
                if self.map is not None
                and 0 <= shard < self.map.n_shards
                else entry
            )
            if cur is not None and cur.owner == self.obs_id:
                if not self._owns(shard):
                    self._adopt(shard)
                    self._announce_owned()
                self._granted.set()
                self._ready.set()
            elif cur is not None and self._owns(shard):
                # a takeover re-granted our shard elsewhere (we were
                # presumed dead): release — exactly-one-owner wins
                self._release_owned(shard)
                self._event("shard_release", link, shard)
        elif t == "deny":
            if doc.get("nonce") == self._claim_nonce:
                self._error = ShardRejected(
                    f"claim denied: {doc.get('reason', '')}"
                )
                self._ready.set()
            else:
                self._flood_shard(dict(doc), exclude=link)
        elif t == "own":
            shard = int(doc["shard"])
            epoch = int(doc["epoch"])
            owner = int(doc["owner"])
            if owner == self.obs_id:
                return
            if self._owns(shard):
                my_e = self.map.owners[shard].epoch if self.map else 0
                if epoch > my_e:
                    self._release_owned(shard)
                    self._event("shard_release", link, shard)
                else:
                    return
            prev = self._route_epoch.get(shard, 0)
            if epoch < prev:
                return
            self._route[shard] = link
            self._route_epoch[shard] = epoch
            if self._lane is not None:
                self._lane.set_route(shard, link)
            # ALWAYS re-flood (tree: flood-except-arrival terminates; no
            # cycles, no storm): an epoch-gated forward would starve any
            # node whose route a link death purged — its neighbors, still
            # holding the same epoch, would never pass the periodic
            # re-announce along
            self._flood_shard(dict(doc), exclude=link)
            self._unpark(shard)
        elif t in ("ho_meta", "ho_state", "ho_dedup", "ho_done", "ho_ack"):
            self._on_ho(link, doc)
        else:
            log.warning("unknown shard control message %r", t)

    def _arbitrate(self, doc: dict) -> None:
        """Root-side claim arbitration (the ONE grant minter)."""
        shard = int(doc["shard"])
        if self.map is None or not 0 <= shard < self.map.n_shards:
            self._flood_shard({
                "t": "deny", "shard": shard, "nonce": doc.get("nonce"),
                "reason": f"no such shard {shard}",
            })
            return
        cur = self.map.owners[shard]
        claimer = int(doc["owner"])
        if cur.epoch == 0 or bool(doc.get("takeover")) or cur.owner == claimer:
            entry = OwnerEntry(
                cur.epoch + 1, claimer, str(doc["host"]), int(doc["port"])
            )
            self.map.merge_entry(shard, entry)
            if self._owns(shard) and claimer != self.obs_id:
                self._release_owned(shard)
            self._flood_shard({
                "t": "grant", "shard": shard, "e": entry.as_doc(),
                "nonce": doc.get("nonce"),
            })
            self._event("shard_grant", arg=shard)
        else:
            self._flood_shard({
                "t": "deny", "shard": shard, "nonce": doc.get("nonce"),
                "reason": (
                    f"shard {shard} is owned (epoch {cur.epoch}); restart "
                    f"with restore_dir for takeover semantics"
                ),
            })

    def _maybe_claim(self) -> None:
        """(Re-)send our claim up the tree until granted/denied — the
        claim is idempotent at the arbiter (a re-grant to the same owner
        just mints the next epoch), so a lost grant heals by retry."""
        if (
            self.is_master
            or self.map is None
            or self._uplink is None
            or self._granted.is_set()
            or self._error is not None
        ):
            return
        idx = self.scfg.shard_index
        if idx < 0:
            self._ready.set()  # member that owns no shard: ready on map
            return
        now = time.monotonic()
        if self._claim_first_t == 0.0:
            self._claim_first_t = now
        elif now - self._claim_first_t > self.scfg.claim_timeout_sec:
            # the documented join budget: unanswered claims fail the
            # creation instead of retrying forever (wait_ready honors
            # the CALLER's timeout; this knob bounds the claim itself)
            self._error = ShardRejected(
                f"no grant for shard {idx} after "
                f"{self.scfg.claim_timeout_sec}s of claims"
            )
            self._ready.set()
            return
        if now - self._claim_sent_t < 1.0:
            return
        self._claim_sent_t = now
        self._send_ctrl(self._uplink, wire.encode_shard({
            "t": "claim", "shard": idx, "owner": self.obs_id,
            "host": self._adv_host, "port": self.node.listen_port,
            "nonce": self._claim_nonce, "takeover": self._takeover,
            "from": self.obs_id,
        }))

    # -- handshake -----------------------------------------------------------

    def _welcome_member(self, link: int) -> None:
        """Accept a sharded child: WELCOME with the r16 flag, then the
        current map (per-link FIFO: the child sees WELCOME -> map before
        any data), then our route announces so its reverse paths exist."""
        self._send_ctrl(link, wire.encode_welcome(SYNC_FLAG_SHARD))
        self._members[link] = _Member()
        self._lane_attach(link)
        self._send_ctrl(
            link,
            wire.encode_shard({
                "t": "map", "map": self.map.as_doc(), "from": self.obs_id,
            }),
        )
        self._announce_owned(only_link=link)
        # routes we LEARNED (owners elsewhere) propagate to the new child,
        # so its reverse paths exist before its first out-of-shard write
        for shard in sorted(self._route):
            if not self._owns(shard):
                e = self.map.owners[shard]
                if e.epoch > 0:
                    self._send_ctrl(link, wire.encode_shard({
                        "t": "own", "shard": shard, "epoch": e.epoch,
                        "owner": e.owner, "from": self.obs_id,
                    }))

    def _start_join(self, uplink: int) -> None:
        claim = self.scfg.shard_index
        self._send_ctrl(
            uplink,
            wire.encode_sync(
                self.spec,
                self._wire_version,
                SYNC_FLAG_SHARD,
                shard=claim,
            ),
        )
        self._send_ctrl(uplink, bytes([wire.DONE]))

    # -- message dispatch ----------------------------------------------------

    def _on_message(self, link: int, payload: bytes) -> None:
        kind = payload[0]
        if kind == wire.FWD:
            if self._lane is not None:
                # a stray consumed in the attach race window: drop
                # unacked — the sender's go-back-N re-delivers into the
                # plane's receiver (its rx_count never saw this seq)
                return
            m = self._members.get(link)
            if m is None:
                return  # not a member link (mid-handshake stray)
            seq = struct.unpack_from("<I", payload, 1)[0]
            if seq != (m.rx_count + 1) & 0xFFFFFFFF:
                # dup or gap: discard unapplied; the sender's go-back-N
                # re-delivers in order (never mis-acked). RE-ANNOUNCE the
                # cumulative ACK either way: a duplicate here usually
                # means our ACK was lost (e.g. bounced on backpressure),
                # and a sender whose retransmissions are silently
                # discarded without a fresh ACK is wedged forever
                m.ack_due = True
                return
            m.rx_count += 1
            m.ack_due = True
            buf = bytearray(payload)
            shard = self._fwd_shard_of(buf)
            if not self._dispatch_fwd(shard, buf, arrival=link):
                self._park(shard, buf)
        elif kind == wire.ACK:
            m = self._members.get(link)
            if m is None:
                return
            count = wire.decode_ack(payload)
            popped = False
            while m.unacked and m.unacked[0][0] <= count:
                m.unacked.pop(0)
                popped = True
            if popped:
                m.progress_t = time.monotonic()
                m.retx_rounds = 0
                self._wake.set()  # window opened: outboxes may drain
        elif kind == wire.SHARD:
            self._on_shard_msg(link, wire.decode_shard(payload))
        elif kind == wire.SYNC:
            self._on_sync(link, payload)
        elif kind == wire.RANGE:
            st = self._pending.get(link)
            if st is not None and st.get("sub"):
                st["range"] = wire.decode_range(payload)
        elif kind == wire.DONE:
            st = self._pending.pop(link, None)
            if st is None:
                return
            if st.get("sub"):
                self._attach_sub(link, st.get("range"))
            elif self.map is None:
                self._deferred_done.append(link)  # answered once map lands
            else:
                claim = st.get("claim")
                if claim is not None and not (
                    -1 <= claim < self.map.n_shards
                ):
                    # the SYNC claim tail fails a misconfigured joiner
                    # (n_shards disagreement) at the hello boundary,
                    # before it spends a join on a claim the master's
                    # arbitration can only deny
                    self._send_ctrl(link, wire.encode_reject(
                        f"shard-index claim {claim} is out of range "
                        f"for this cluster's n_shards="
                        f"{self.map.n_shards}"
                    ))
                    self.node.drop_link_flushed(link)
                else:
                    self._welcome_member(link)
        elif kind == wire.WELCOME:
            if not wire.welcome_flags(payload) & SYNC_FLAG_SHARD:
                # pre-r16 / unsharded parent: the tolerant-fallback arm —
                # the caller tears this node down and joins classic
                self._fallback = True
                self._ready.set()
                return
            self._members[link] = _Member()
            self._lane_attach(link)  # lane exists on a re-grafted member
            # map + claim follow (the parent sends its map right behind);
            # a RE-GRAFTED member re-announces its shards so the new
            # subtree's routes point here again
            self._announce_owned(only_link=link)
        elif kind == wire.REJECT:
            self._error = ShardRejected(wire.decode_reject(payload))
            self._ready.set()
        elif kind == wire.DIGEST:
            self._child_digests[link] = wire.decode_digest(payload)
        elif kind == wire.CLOCK:
            # r18 clock plane (comm/peer.py twin): answer a child's probe
            # down its own link; fold an uplink reply into the estimator
            doc = wire.decode_clock(payload)
            if doc.get("op") == "probe":
                try:
                    self.node.send(
                        link,
                        wire.encode_clock(self._clock.reply_payload(doc)),
                        timeout=0.05,
                    )
                except BrokenPipeError:
                    pass
            elif doc.get("op") == "reply" and link == self._uplink:
                self._clock.on_reply(doc)
        elif kind in (wire.CHUNK,):
            pass  # no snapshot uploads in the sharded handshake
        elif kind in (wire.DATA, wire.BURST, wire.RDATA, wire.FRESH):
            pass  # classic stream from a parent we are abandoning (fallback)
        else:
            log.warning("unknown message kind %d on link %d", kind, link)

    def _on_sync(self, link: int, payload: bytes) -> None:
        k, n, digest = wire.decode_sync(payload)
        if digest != self.spec.layout_digest():
            self._send_ctrl(link, wire.encode_reject(
                f"table layout mismatch: yours ({k} leaves, {n} elems) "
                f"is not byte-compatible with ours "
                f"({self.spec.num_leaves}, {self.spec.total_n})"
            ))
            self.node.drop_link_flushed(link)
            return
        flags = wire.sync_flags(payload)
        if flags & SYNC_FLAG_READ_ONLY:
            self._pending[link] = {"sub": True}
            if not flags & SYNC_FLAG_RANGE:
                self._pending[link]["range"] = None
            return
        if not flags & SYNC_FLAG_SHARD:
            # the r10 detectably-broken-not-silently-wrong rule: no node
            # in a sharded cluster holds the full replica, so a classic
            # writer cannot be seeded — fail it loudly with the remedy
            self._send_ctrl(link, wire.encode_reject(
                "this cluster runs the r16 cluster-sharded tensor; a "
                "full-replica writer cannot join (set ShardConfig."
                "n_shards/shard_index to join sharded, or start the "
                "cluster with n_shards=0 / ST_SHARD=0 for the classic "
                "protocol)"
            ))
            self.node.drop_link_flushed(link)
            return
        self._pending[link] = {"sub": False, "claim": wire.sync_shard(payload)}

    # -- membership events ---------------------------------------------------

    def _on_link_down(self, link: int, is_uplink: bool) -> None:
        m = self._members.pop(link, None)
        if self._lane is not None and link in self._lane_links:
            # the plane re-dispatches every unacked FWD under its
            # unchanged end-to-end identity (apply/relay/park) — the
            # python redispatch below is the non-lane twin
            self._lane.member_detach(link)
            self._lane_links.discard(link)
            m = None
        self._lane_subs.pop(link, None)
        self._subs.pop(link, None)
        self.state.drop_sub(link)
        self._pending.pop(link, None)
        self._child_digests.pop(link, None)
        # abandoned incoming handoffs: a leaver that died mid-transfer
        # never sends ho_done, and the stage holds a slice-sized buffer —
        # purge it or repeated aborted handoffs accumulate ~a full table
        # invisible to alloc_bytes()
        for k in [
            k for k, st in self._ho_stage.items() if st.get("link") == link
        ]:
            del self._ho_stage[k]
        if link in self._deferred_done:
            self._deferred_done.remove(link)
        for shard in [s for s, l in self._route.items() if l == link]:
            del self._route[shard]
        if is_uplink:
            self._uplink = None
            if self._lane is not None:
                self._lane.set_uplink(None)
                for s in list(self._ho_sent):
                    self._lane.set_handoff(s, False)
            # un-acked outgoing handoffs: the successor may never have
            # adopted — we still hold the slice (release only happens on
            # ho_ack), so resume local applies; if the successor DID
            # adopt, its epoch+1 announce releases us when the tree heals
            self._ho_sent.clear()
        if m is not None:
            # every unacked FWD re-routes under its UNCHANGED end-to-end
            # identity (byte-identical past the link-seq field) — a copy
            # that was actually delivered dies in the owner's dedup
            # window instead of double-applying
            for _seq, buf, _t in m.unacked:
                shard = self._fwd_shard_of(buf)
                if not self._dispatch_fwd(shard, buf, arrival=None):
                    self._park(shard, buf)

    def _handle_events(self) -> bool:
        busy = False
        for ev in self.node.poll_events(timeout=0.0):
            busy = True
            if ev.kind == EventKind.LINK_UP:
                if ev.is_uplink:
                    self._uplink = ev.link_id
                    if self._lane is not None:
                        self._lane.set_uplink(ev.link_id)
                    self._start_join(ev.link_id)
                # children speak first (SYNC); nothing to do yet
            elif ev.kind == EventKind.LINK_DOWN:
                self._on_link_down(ev.link_id, ev.is_uplink)
            elif ev.kind == EventKind.BECAME_MASTER:
                # the old root died: we are the tree root now, and with
                # it the map's grant-minting authority (the merged map is
                # the authority state; exactly-one-owner is preserved
                # because only the CURRENT root arbitrates)
                self._uplink = None
                if self._lane is not None:
                    self._lane.set_uplink(None)
                self.is_master = True
            elif ev.kind == EventKind.REJOIN_FAILED:
                self._error = ConnectionError("rejoin failed (tree gone)")
                self._ready.set()
        return busy

    # -- digests -------------------------------------------------------------

    def _publish_digest(self) -> None:
        from ..obs import aggregate

        doc = aggregate.from_snapshot(
            self.obs_id, self._reg.snapshot(), self._now_ns()
        )
        ent = doc["nodes"].get(str(self.obs_id))
        if ent is not None:
            ent["name"] = self.node_name
        for child in list(self._child_digests.values()):
            aggregate.merge(doc, child)
        aggregate.bounded(doc)
        up = self._uplink
        if up is not None:
            try:
                self.node.send(up, wire.encode_digest(doc), timeout=0.05)
            except BrokenPipeError:
                pass
        else:
            if self._health is not None:
                # r18: the root analyzer samples every digest beat
                try:
                    self._health.beat(doc, self._now_ns())
                except Exception as e:
                    log.debug("health beat failed: %s", e)
            if self.config.obs.cluster_json_path:
                import json as _json

                path = self.config.obs.cluster_json_path
                tmp = f"{path}.tmp.{os.getpid()}"
                try:
                    with open(tmp, "w") as f:
                        _json.dump(doc, f)
                        f.write("\n")
                    os.replace(tmp, path)
                except OSError as e:
                    log.debug("cluster digest write failed: %s", e)

    # -- the loop ------------------------------------------------------------

    def _loop(self) -> None:
        digest_iv = (
            self.config.obs.digest_interval_sec if self._obs_on else 0.0
        )
        while not self._stop.is_set():
            busy = self._handle_events()
            for link in list(self.node.links or ()):
                if link in self._lane_links:
                    continue  # the plane's receiver thread consumes these
                for _ in range(256):
                    try:
                        payload = self.node.recv(link, timeout=0.0)
                    except BrokenPipeError:
                        break
                    if payload is None:
                        break
                    busy = True
                    try:
                        self._on_message(link, payload)
                    except Exception as e:
                        log.warning("dropping bad message: %s", e)
                    if link in self._lane_links:
                        # _ensure_lane attached this link mid-drain (the
                        # map just landed): the plane's receiver owns the
                        # stream from here
                        break
            if self._lane is not None:
                # control-plane messages the plane deferred (it owns only
                # FWD/ACK on member links — the engine/peer.py split)
                while True:
                    c = self._lane.poll_ctrl()
                    if c is None:
                        break
                    busy = True
                    try:
                        self._on_message(c[0], c[1])
                    except Exception as e:
                        log.warning("dropping bad ctrl message: %s", e)
            else:
                self._flush_acks()
                self._unpark()  # frames parked on a full window retry here
                self._pump_outboxes()
                self._check_retransmit()
            self._pump_subs()
            self._run_handoffs()
            self._maybe_claim()
            now = time.monotonic()
            if (
                self.owned_shards()
                and now - self._announce_last >= ANNOUNCE_SEC
            ):
                self._announce_last = now
                self._announce_owned()
            if digest_iv > 0 and now - self._digest_last >= digest_iv:
                self._digest_last = now
                try:
                    self._publish_digest()
                except Exception as e:
                    log.debug("digest failed: %s", e)
            if (
                self._clock_interval > 0
                and not self.is_master
                and self._uplink is not None
                and now - self._clock_last >= self._clock_interval
            ):
                # r18 clock-probe beat (comm/peer.py twin): lossy — a
                # bounced send waits for the next interval
                self._clock_last = now
                try:
                    self.node.send(
                        self._uplink,
                        wire.encode_clock(self._clock.probe_payload()),
                        timeout=0.05,
                    )
                except BrokenPipeError:
                    pass
            if self._hub is not None:
                self._hub.poll_native(
                    self.config.obs.native_drain_interval_sec
                )
            if not busy:
                if self._wake.wait(0.002):
                    self._wake.clear()
