"""Shard-local state: owned slices, per-link residual slices, and
per-remote-shard outboxes — with a word-range slice codec.

The memory contract this file carries: a sharded node allocates
O(owned slices) persistent state plus O(active outbox ranges) transient
state, NEVER the full table. Residuals stay shard-local (the r16
discipline): error feedback for a subscriber link lives in a slice the
size of the subscription; error feedback for out-of-shard writes lives in
a per-target-shard outbox slice that drains to zero at quiesce and is
freed once idle.

:class:`SliceCodec` is the 1-bit error-feedback codec restricted to a
word range of the GLOBAL table layout: scales are per GLOBAL leaf (the
full-L scale row RDATA/FWD carry, so serve-tier subscribers and owners
decode with the unmodified r10 machinery), bits cover only the range's
words, and quantize/apply are bit-compatible with codec_np /
serve.Subscriber._apply_frame over the same elements (value +=
scale[leaf] * (1 - 2*bit) on live lanes, ±SAT saturation, padding
untouched).

A node may own SEVERAL shards (a drain-handoff leaves the successor with
two); ``owned`` is keyed by shard index and every receive/serve path
routes by word range.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..config import ScalePolicy
from ..ops.codec import SAT as _SAT
from ..ops.codec_np import _layout, _pow2_floor_np
from ..ops.table import TableSpec


class SliceCodec:
    """1-bit sign codec over ``[word_lo, word_lo + word_cnt)`` of a global
    table spec. Precomputes the range's leaf geometry once; quantize and
    apply are then two-pass numpy over the slice only."""

    def __init__(self, spec: TableSpec, word_lo: int, word_cnt: int):
        words = spec.total // 32
        if not (0 <= word_lo and 0 < word_cnt and word_lo + word_cnt <= words):
            raise ValueError(
                f"slice [{word_lo}, {word_lo + word_cnt}) outside the "
                f"{words}-word table"
            )
        self.spec = spec
        self.word_lo = int(word_lo)
        self.word_cnt = int(word_cnt)
        self.elo = self.word_lo * 32
        self.n_el = self.word_cnt * 32
        offs, ns, padded = _layout(spec)
        bounds = np.cumsum(padded)
        el = np.arange(self.elo, self.elo + self.n_el)
        #: global leaf index per slice element (the RDATA/FWD scale row is
        #: indexed by GLOBAL leaf — serve/subscriber.py's geometry)
        self.leaf_of = np.searchsorted(bounds, el, side="right").astype(
            np.int64
        )
        starts = offs[self.leaf_of]
        #: 1.0 on live (non-padding) elements, 0.0 on padding
        self.live = ((el - starts) < ns[self.leaf_of]).astype(np.float32)
        #: distinct global leaves intersecting the range, with their slice
        #: bounds and live counts — the per-leaf scale segments
        self.segments: list[tuple[int, int, int, float]] = []
        uniq, first = np.unique(self.leaf_of, return_index=True)
        for g, i0 in zip(uniq, first):
            i1 = int(np.searchsorted(self.leaf_of, g, side="right"))
            n_live = float(self.live[int(i0) : i1].sum())
            self.segments.append((int(g), int(i0), i1, n_live))

    def zeros(self) -> np.ndarray:
        return np.zeros(self.n_el, np.float32)

    def measure(
        self,
        resid: np.ndarray,
        policy: ScalePolicy = ScalePolicy.POW2_RMS,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Per-segment scale measurement: (scales f32[L] — zero outside
        the range's leaves, amax f32[L]). Reductions accumulate EXACT f64
        products (f32->f64 squares are exact, so the only inexactness is
        the accumulation order) — the engine-tier twin (stengine.cpp
        slice_measure) sums the same doubles with interleaved
        accumulators, and the f32-cast results agree to the last bit in
        practice (the parity test pins it on shared random state). Like
        the main codec, subnormal rms pow2-floors to 0, so residual dust
        below ~1.2e-38 reads as idle — the documented drain caveat."""
        L = self.spec.num_leaves
        scales = np.zeros(L, np.float32)
        amaxes = np.zeros(L, np.float32)
        for g, i0, i1, n_live in self.segments:
            if n_live <= 0:
                continue
            seg = resid[i0:i1]
            amax = np.float32(np.max(np.abs(seg)))
            if not (amax > 0) or not np.isfinite(amax):
                continue
            amaxes[g] = amax
            seg64 = seg.astype(np.float64)
            if policy == ScalePolicy.ABS_MEAN:
                s = np.float32(
                    np.sum(np.abs(seg64)) / np.float32(n_live)
                )
            else:
                rms = np.float32(
                    np.sqrt(np.sum(seg64 * seg64) / np.float32(n_live))
                )
                s = (
                    _pow2_floor_np(rms)[()]
                    if policy == ScalePolicy.POW2_RMS
                    else rms
                )
            scales[g] = s if np.isfinite(s) else 0.0
        return scales, amaxes

    def quantize_at(
        self, resid: np.ndarray, scales: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray]:
        """Pack + error-feedback one frame at a GIVEN scale row (the
        cascade rung): (words u32[word_cnt], new_resid). The caller owns
        the schedule; an all-zero row is the caller's stop condition.
        Padding lanes pack as 0 bits (r17: the stcodec cascade-kernel
        convention the engine lane rides — receivers mask by ``live``
        either way, so only the parity bytes care)."""
        s_el = scales[self.leaf_of] * self.live
        neg = (resid <= 0) & (self.live > 0)
        words = (
            np.packbits(neg, bitorder="little").view("<u4").astype(np.uint32)
        )
        sent = np.where(neg, -s_el, s_el)
        new_r = np.where(s_el > 0, resid - sent, resid).astype(np.float32)
        new_r *= self.live  # padding stays exactly 0
        return words, new_r

    def cascade_rows(
        self, scales: np.ndarray, amaxes: np.ndarray, k: int
    ) -> list[np.ndarray]:
        """The r11 cascade schedule restricted to this slice: frame 0's
        row anchors each segment at max(policy scale, pow2_floor(amax))
        — the amax anchor is what drains OUTLIERS geometrically (the r11
        engine note: an rms-anchored ladder starves the gaussian tail) —
        and rows 1..k-1 halve, +8 refinement rungs below the measured
        scale (finer lattice for the next message's measured frame to
        terminate on), stopping at the subnormal floor. Exponent math is
        integer (f32 bit fields), so the engine twin is bit-identical."""
        if not scales.any():
            return []
        tops = np.where(scales > 0, _pow2_floor_np(amaxes), 0.0).astype(
            np.float32
        )
        row0 = np.maximum(scales, tops).astype(np.float32)
        # ladder depth: binades from the anchor down to the measured
        # scale (+1), +8 refinement; collapses to 1 when anchor == scale
        exp = lambda x: (  # noqa: E731 — biased f32 exponents, vectorized
            ((np.asarray(x, np.float32).view(np.uint32) >> 23) & 0xFF)
            .astype(np.int64)
        )
        nz = scales > 0
        d = int(np.max(np.where(nz, exp(tops) - exp(scales), 0), initial=0))
        maxd = d + 1 + (8 if d > 0 else 0)
        rows = []
        row = row0
        for j in range(min(max(1, k), maxd)):
            if j > 0:
                row = (row * np.float32(0.5)).astype(np.float32)
                if not row.any():
                    break  # halved into the subnormal floor
            rows.append(row)
        return rows

    def quantize(
        self,
        resid: np.ndarray,
        policy: ScalePolicy = ScalePolicy.POW2_RMS,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One measured sender step (the serve-tier shape): (scales,
        words, new_resid); all-zero scales = idle (residual returned
        unchanged). The FWD outbox drain uses measure + cascade_rows +
        quantize_at instead — one measurement per message."""
        scales, _amax = self.measure(resid, policy)
        if not scales.any():
            return scales, np.zeros(self.word_cnt, np.uint32), resid
        words, new_r = self.quantize_at(resid, scales)
        return scales, words, new_r

    def apply(
        self, target: np.ndarray, scales: np.ndarray, words: np.ndarray
    ) -> bool:
        """Receiver step IN PLACE: target += scale[leaf]*(1-2*bit) on live
        lanes, saturated at ±SAT. Returns False for an all-zero-scale
        no-op. Bit-compatible with serve.Subscriber._apply_frame."""
        if not np.asarray(scales).any():
            return False
        bits = np.unpackbits(
            np.ascontiguousarray(words, "<u4").view(np.uint8),
            bitorder="little",
        ).astype(np.float32)
        s_el = np.asarray(scales, np.float32)[self.leaf_of] * self.live
        target += s_el * (1.0 - 2.0 * bits)
        np.clip(target, -_SAT, _SAT, out=target)
        return True


class ShardState:
    """One sharded node's resident arrays, under one lock:

    - ``owned``: shard index -> (codec, values slice) for every shard
      this node currently owns;
    - ``sub_resid``: per-subscriber-link (codec, residual slice) — error
      feedback for the serve tier, sized to each subscription;
    - ``outbox``: per-target-shard (codec, residual slice) for
      OUT-of-shard writes (error feedback for the FWD plane), allocated
      lazily on the first write toward a shard and freed once drained.

    All mutation happens under ``_lock``; snapshots copy. ``alloc_bytes``
    is the per-node memory bound the chaos harness enforces (the
    acceptance gate's rss/alloc bound)."""

    def __init__(self, spec: TableSpec):
        self.spec = spec
        self._lock = threading.Lock()
        self.owned: dict[int, tuple[SliceCodec, np.ndarray]] = {}
        self.sub_resid: dict[int, tuple[SliceCodec, np.ndarray]] = {}
        self.outbox: dict[int, tuple[SliceCodec, np.ndarray]] = {}
        self.updates = 0
        self.applies = 0

    # -- ownership -----------------------------------------------------------

    def adopt(
        self,
        shard: int,
        word_lo: int,
        word_cnt: int,
        values: Optional[np.ndarray] = None,
    ) -> None:
        with self._lock:
            c = SliceCodec(self.spec, word_lo, word_cnt)
            if values is not None:
                v = np.asarray(values, np.float32)
                if v.shape != (c.n_el,):
                    raise ValueError(
                        f"adopt: values shape {v.shape} != ({c.n_el},)"
                    )
                self.owned[shard] = (c, v.copy())
            else:
                self.owned[shard] = (c, c.zeros())
            # adopting a shard supersedes any outbox we held toward its
            # previous owner: fold the owed mass straight into the slice
            # (we ARE the owner now — exact local apply)
            ob = self.outbox.pop(shard, None)
            if ob is not None:
                _oc, r = ob
                v = self.owned[shard][1]
                np.clip(v + r, -_SAT, _SAT, out=v)

    def release(self, shard: int) -> Optional[np.ndarray]:
        """Drop ownership of one shard (handoff tail): returns the slice
        and drops subscriber residuals inside it (those links resync
        against the new owner)."""
        with self._lock:
            ent = self.owned.pop(shard, None)
            if ent is None:
                return None
            c, vals = ent
            for link in [
                l
                for l, (sc, _r) in self.sub_resid.items()
                if c.word_lo <= sc.word_lo < c.word_lo + c.word_cnt
            ]:
                self.sub_resid.pop(link, None)
            return vals

    def owned_entry(self, shard: int):
        with self._lock:
            return self.owned.get(shard)

    def owns(self, shard: int) -> bool:
        with self._lock:
            return shard in self.owned

    def owned_words(self) -> int:
        with self._lock:
            return sum(c.word_cnt for c, _v in self.owned.values())

    # -- write paths ---------------------------------------------------------

    def add_delta(
        self, shard: int, codec_fn, elo: int, delta: np.ndarray
    ) -> bool:
        """Apply an in-shard delta exactly OR deposit it into the shard's
        outbox — decided and written under ONE lock acquisition, so a
        caller-thread ``add()`` cannot race the loop thread's ``adopt()``/
        ``release()`` into a stranded outbox (adopt folds outboxes under
        this same lock) or a spurious does-not-own raise. ``codec_fn``
        builds the outbox SliceCodec lazily (owned applies never need
        one). Returns True iff the delta landed in the outbox (the
        caller's pre-coalesce deposit twins key on the decision this
        lock made, not on a racy owns() re-check)."""
        with self._lock:
            if shard in self.owned:
                self._add_in_shard_locked(shard, elo, delta)
                return False
            self._add_outbox_locked(shard, codec_fn(), elo, delta)
            return True

    def add_in_shard(self, shard: int, elo: int, delta: np.ndarray) -> None:
        """Apply an in-shard delta slice [elo, elo+len) — exact f32, no
        codec (local applies are exact; only LINKS quantize). Also feeds
        every overlapping subscriber residual."""
        with self._lock:
            self._add_in_shard_locked(shard, elo, delta)

    def _add_in_shard_locked(
        self, shard: int, elo: int, delta: np.ndarray
    ) -> None:
        ent = self.owned.get(shard)
        if ent is None:
            raise RuntimeError(f"node does not own shard {shard}")
        c, vals = ent
        i0 = elo - c.elo
        if i0 < 0 or i0 + delta.size > c.n_el:
            raise ValueError(
                f"delta [{elo}, {elo + delta.size}) outside owned "
                f"slice [{c.elo}, {c.elo + c.n_el})"
            )
        d = np.asarray(delta, np.float32) * c.live[i0 : i0 + delta.size]
        np.clip(
            vals[i0 : i0 + delta.size] + d,
            -_SAT,
            _SAT,
            out=vals[i0 : i0 + delta.size],
        )
        self.updates += 1
        self._feed_subs(elo, d)

    def _feed_subs(self, elo: int, d: np.ndarray) -> None:
        """Accumulate an applied delta into overlapping subscriber
        residuals (caller holds the lock)."""
        for sc, r in self.sub_resid.values():
            j0 = elo - sc.elo
            lo = max(0, j0)
            hi = min(sc.n_el, j0 + d.size)
            if lo < hi:
                r[lo:hi] += d[lo - j0 : hi - j0]

    def add_outbox(
        self, shard: int, codec: SliceCodec, elo: int, delta: np.ndarray
    ) -> None:
        """Accumulate an out-of-shard delta slice into shard's outbox
        (allocating it lazily)."""
        with self._lock:
            self._add_outbox_locked(shard, codec, elo, delta)

    def _add_outbox_locked(
        self, shard: int, codec: SliceCodec, elo: int, delta: np.ndarray
    ) -> None:
        ob = self.outbox.get(shard)
        if ob is None:
            ob = (codec, codec.zeros())
            self.outbox[shard] = ob
        c, r = ob
        i0 = elo - c.elo
        if i0 < 0 or i0 + delta.size > c.n_el:
            raise ValueError(
                f"delta [{elo}, {elo + delta.size}) outside shard "
                f"{shard}'s range [{c.elo}, {c.elo + c.n_el})"
            )
        r[i0 : i0 + delta.size] += (
            np.asarray(delta, np.float32) * c.live[i0 : i0 + delta.size]
        )
        self.updates += 1

    def drain_outbox_frames(
        self, shard: int, policy: ScalePolicy, k: int = 1
    ) -> Optional[tuple[list, int]]:
        """Quantize up to ``k`` cascade frames off a shard's outbox: ONE
        scale measurement per message, then the halving schedule
        (SliceCodec.cascade_rows — frame 0 amax-anchored, +8 refinement
        rungs), error feedback applied per frame. The r11 discipline the
        engine lane rides at native speed — per-frame re-measurement was
        the python plane's measured wall (a division per element per
        frame), and the measured sequence converges to the halving
        schedule anyway. Returns ([(scales, words), ...], word_lo) with
        1..k frames, or None when idle — an idle outbox is FREED (the
        transient-memory contract)."""
        with self._lock:
            ob = self.outbox.get(shard)
            if ob is None:
                return None
            c, r = ob
            scales, amaxes = c.measure(r, policy)
            rows = c.cascade_rows(scales, amaxes, k)
            frames = []
            for row in rows:
                words, r = c.quantize_at(r, row)
                frames.append((row, words))
            if not frames:
                self.outbox.pop(shard, None)  # drained to dust: free it
                return None
            self.outbox[shard] = (c, r)
            return frames, c.word_lo

    def outbox_shards(self) -> list[int]:
        with self._lock:
            return list(self.outbox)

    def restore_outbox(
        self, shard: int, codec: SliceCodec, resid: np.ndarray
    ) -> None:
        """Re-seat a checkpointed outbox residual (restart path): the owed
        out-of-shard mass survives the restart and drains normally once a
        route exists."""
        with self._lock:
            r = np.asarray(resid, np.float32)
            if r.shape != (codec.n_el,):
                raise ValueError(
                    f"outbox residual shape {r.shape} != ({codec.n_el},)"
                )
            prev = self.outbox.get(shard)
            if prev is not None:
                r = r + prev[1]
            self.outbox[shard] = (codec, r.copy())

    # -- receive path --------------------------------------------------------

    def apply_owned(
        self, scales: np.ndarray, words: np.ndarray, word_lo: int
    ) -> bool:
        """Apply a FWD frame addressed to an owned shard: the slice and
        every overlapping subscriber residual move together (the
        split-horizon analog for the serve tier). False when no owned
        shard starts at ``word_lo``."""
        with self._lock:
            for c, vals in self.owned.values():
                if c.word_lo == word_lo:
                    changed = c.apply(vals, scales, words)
                    if changed:
                        self.applies += 1
                        for sc, r in self.sub_resid.values():
                            if (
                                sc.word_lo >= c.word_lo
                                and sc.word_lo + sc.word_cnt
                                <= c.word_lo + c.word_cnt
                            ):
                                i0 = sc.word_lo - c.word_lo
                                sc.apply(
                                    r,
                                    scales,
                                    words[i0 : i0 + sc.word_cnt],
                                )
                    return changed
            return False

    # -- serve tier ----------------------------------------------------------

    def attach_sub(self, link: int, word_lo: int, word_cnt: int) -> np.ndarray:
        """Open (or re-seed) a subscriber link's residual slice; returns
        the CURRENT values for the range (the seed snapshot) — taken and
        attached under ONE lock so no add can fall between them."""
        with self._lock:
            for c, vals in self.owned.values():
                if (
                    c.word_lo <= word_lo
                    and word_lo + word_cnt <= c.word_lo + c.word_cnt
                ):
                    sc = SliceCodec(self.spec, word_lo, word_cnt)
                    self.sub_resid[link] = (sc, sc.zeros())
                    i0 = (word_lo - c.word_lo) * 32
                    return vals[i0 : i0 + word_cnt * 32].copy()
            raise ValueError(
                f"subscription [{word_lo}, {word_lo + word_cnt}) not "
                f"within any owned shard"
            )

    def drop_sub(self, link: int) -> None:
        with self._lock:
            self.sub_resid.pop(link, None)

    def sub_frame(
        self, link: int, policy: ScalePolicy
    ) -> Optional[tuple[np.ndarray, np.ndarray, int, int]]:
        """Quantize one RDATA frame off a subscriber link's residual.
        None = idle or unknown link."""
        with self._lock:
            ob = self.sub_resid.get(link)
            if ob is None:
                return None
            sc, r = ob
            scales, words, new_r = sc.quantize(r, policy)
            if not scales.any():
                return None
            self.sub_resid[link] = (sc, new_r)
            return scales, words, sc.word_lo, sc.word_cnt

    def sub_idle(self, link: int) -> bool:
        """True when the link's residual is exactly drained (safe to
        FRESH-mark — the r10 only-mark-drained discipline)."""
        with self._lock:
            ob = self.sub_resid.get(link)
            if ob is None:
                return True
            return not np.any(ob[1])

    # -- snapshots / accounting ----------------------------------------------

    def snapshot_owned(self) -> dict[int, tuple[int, int, np.ndarray]]:
        """{shard: (word_lo, word_cnt, values copy)} of every owned
        slice."""
        with self._lock:
            return {
                k: (c.word_lo, c.word_cnt, v.copy())
                for k, (c, v) in self.owned.items()
            }

    def snapshot_outboxes(self) -> dict[int, tuple[int, np.ndarray]]:
        """{shard: (word_lo, residual copy)} for every live outbox."""
        with self._lock:
            return {
                k: (c.word_lo, r.copy()) for k, (c, r) in self.outbox.items()
            }

    def outbox_bytes(self) -> int:
        """Resident outbox residual bytes (the r17 admission-control
        gauge — ShardConfig.outbox_limit_bytes bounds it)."""
        with self._lock:
            return sum(r.nbytes for _, r in self.outbox.values())

    def outbox_backlog_by_shard(self) -> dict[int, int]:
        """{shard: nonzero residual bytes} — the LIVE backlog destined to
        each shard (drains to 0 at quiesce, unlike the resident-bytes
        gauge above). The r18 per-shard heat numerator: one nonzero scan
        per outbox per digest beat, off the hot path."""
        with self._lock:
            return {
                k: int(np.count_nonzero(r)) * 4
                for k, (_, r) in self.outbox.items()
            }

    def outboxes_idle(self, tol: float = 0.0) -> bool:
        with self._lock:
            return all(
                float(np.max(np.abs(r), initial=0.0)) <= tol
                for _, r in self.outbox.values()
            )

    def alloc_bytes(self) -> int:
        """Resident f32 state bytes: owned slices + subscriber residuals +
        live outboxes — the number the chaos harness bounds per node."""
        with self._lock:
            total = 0
            for _, v in self.owned.values():
                total += v.nbytes
            for _, r in self.sub_resid.values():
                total += r.nbytes
            for _, r in self.outbox.values():
                total += r.nbytes
            return total
