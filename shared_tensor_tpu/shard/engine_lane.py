"""ctypes wrapper over the native shard data plane (stengine.cpp r17).

:class:`ShardLane` is the engine-tier twin of the ShardNode FWD hot loop:
owned slices, per-target-shard outboxes, the per-link go-back-N ledgers,
the end-to-end (origin, fwd_seq) dedup windows and the park buffer all
live in C, pumped by two native threads riding the same TxSlot ring and
zero-copy transport paths that carry the classic plane (BENCH_r14's
84 GB/s machinery). Python keeps the CONTROL plane — claim/grant/handoff/
arbitration/announces — exactly as before: every non-FWD/ACK message on a
member link defers to :meth:`ShardLane.poll_ctrl`, the engine/peer.py
split applied to the sharded tier.

Capability gating: :func:`shard_engine_eligible` — host tier, the native
lib present, ``ShardConfig.engine_lane`` true, and the ``ST_SHARD_ENGINE=0``
escape hatch unset (the documented A/B pin, like ST_SHM/ST_SIGN2). When
ineligible, ShardNode runs the r16 python-tier plane unchanged — the
fallback and the semantic reference; the two lanes are wire-identical
(byte-equal FWD frames on shared state — tests/test_shard_engine.py), so
mixed trees interop in both orientations.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np

from ..ops.codec_np import _layout
from ..ops.table import TableSpec

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C,ALIGNED")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C,ALIGNED")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C,ALIGNED")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C,ALIGNED")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C,ALIGNED")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C,ALIGNED")

_DECLARED = False


def _declare(lib) -> None:
    """st_shard_* ctypes declarations (tools/lint_abi.py checks every row
    against the native definitions, counter widths included)."""
    global _DECLARED
    if _DECLARED:
        return
    lib.st_slice_quantize.restype = ctypes.c_int32
    lib.st_slice_quantize.argtypes = [
        _i64p, _i64p, _i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, _f32p, _f32p, _u32p,
    ]
    lib.st_slice_apply.restype = ctypes.c_int32
    lib.st_slice_apply.argtypes = [
        _i64p, _i64p, _i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, _f32p, _f32p, _u32p,
    ]
    lib.st_slice_cascade.restype = ctypes.c_int32
    lib.st_slice_cascade.argtypes = [
        _i64p, _i64p, _i64p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32, _f32p, _u8p,
    ]
    lib.st_shard_create.restype = ctypes.c_void_p
    lib.st_shard_create.argtypes = [
        ctypes.c_void_p, _i64p, _i64p, _i64p,
        ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int32, _i64p, _i64p, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_double, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
    ]
    lib.st_shard_start.restype = None
    lib.st_shard_start.argtypes = [ctypes.c_void_p]
    lib.st_shard_stop.restype = None
    lib.st_shard_stop.argtypes = [ctypes.c_void_p]
    lib.st_shard_destroy.restype = None
    lib.st_shard_destroy.argtypes = [ctypes.c_void_p]
    lib.st_shard_member_attach.restype = ctypes.c_int32
    lib.st_shard_member_attach.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_uint64, ctypes.c_uint64,
    ]
    lib.st_shard_member_detach.restype = ctypes.c_int32
    lib.st_shard_member_detach.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.st_shard_set_uplink.restype = None
    lib.st_shard_set_uplink.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.st_shard_set_route.restype = None
    lib.st_shard_set_route.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.st_shard_set_handoff.restype = None
    lib.st_shard_set_handoff.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
    ]
    lib.st_shard_adopt.restype = None
    lib.st_shard_adopt.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
    ]
    lib.st_shard_release.restype = ctypes.c_int32
    lib.st_shard_release.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
    ]
    lib.st_shard_owns.restype = ctypes.c_int32
    lib.st_shard_owns.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.st_shard_read.restype = ctypes.c_int32
    lib.st_shard_read.argtypes = [ctypes.c_void_p, ctypes.c_int32, _f32p]
    lib.st_shard_add.restype = None
    lib.st_shard_add.argtypes = [ctypes.c_void_p, _f32p]
    lib.st_shard_restore_outbox.restype = None
    lib.st_shard_restore_outbox.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, _f32p,
    ]
    lib.st_shard_dedup_merge.restype = ctypes.c_int32
    lib.st_shard_dedup_merge.argtypes = [
        ctypes.c_void_p, ctypes.c_uint32, _u64p, ctypes.c_int64,
    ]
    lib.st_shard_snapshot.restype = ctypes.c_int32
    lib.st_shard_snapshot.argtypes = [
        ctypes.c_void_p, _i32p, _f32p, _i32p, _f32p, _u32p, _u64p,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.st_shard_dedup_size.restype = ctypes.c_int64
    lib.st_shard_dedup_size.argtypes = [ctypes.c_void_p]
    lib.st_shard_dedup_export.restype = ctypes.c_int64
    lib.st_shard_dedup_export.argtypes = [
        ctypes.c_void_p, _u32p, _u64p, ctypes.c_int64,
    ]
    lib.st_shard_fwd_seq.restype = ctypes.c_uint32
    lib.st_shard_fwd_seq.argtypes = [ctypes.c_void_p]
    lib.st_shard_set_fwd_seq.restype = None
    lib.st_shard_set_fwd_seq.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.st_shard_alloc_bytes.restype = ctypes.c_int64
    lib.st_shard_alloc_bytes.argtypes = [ctypes.c_void_p]
    lib.st_shard_outbox_bytes.restype = ctypes.c_int64
    lib.st_shard_outbox_bytes.argtypes = [ctypes.c_void_p]
    lib.st_shard_owned_words.restype = ctypes.c_int64
    lib.st_shard_owned_words.argtypes = [ctypes.c_void_p]
    lib.st_shard_idle.restype = ctypes.c_int32
    lib.st_shard_idle.argtypes = [ctypes.c_void_p, ctypes.c_double]
    lib.st_shard_counters.restype = None
    lib.st_shard_counters.argtypes = [ctypes.c_void_p, _u64p]
    lib.st_shard_poll_ctrl.restype = ctypes.c_int32
    lib.st_shard_poll_ctrl.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
        ctypes.c_char_p, ctypes.c_int32,
    ]
    _DECLARED = True


def load_shard_lib() -> Optional[ctypes.CDLL]:
    """The engine .so with the st_shard_* surface declared, or None."""
    from ..comm.engine import load_engine

    lib = load_engine()
    if lib is None:
        return None
    _declare(lib)
    return lib


def shard_engine_eligible(config) -> bool:
    """Should this ShardNode run the native FWD plane? Host tier,
    ``ShardConfig.engine_lane`` on, the ``ST_SHARD_ENGINE=0`` escape
    hatch unset, and the engine lib loadable. The python-tier plane
    stays the fallback and the semantic reference."""
    from ..core import host_tier_active

    if os.environ.get("ST_SHARD_ENGINE", "1") == "0":
        return False
    if not getattr(config.shard, "engine_lane", True):
        return False
    if not host_tier_active():
        return False
    return load_shard_lib() is not None


class ShardLane:
    """The native shard FWD plane for one ShardNode (see the module
    docstring). All slice/outbox/ledger/dedup state lives in C; methods
    marshal numpy views in and out. Thread-safe (the plane's own mutex)."""

    def __init__(
        self,
        node,  # TransportNode
        spec: TableSpec,
        ranges: list[tuple[int, int]],  # per-shard (word_lo, word_cnt)
        policy_code: int,
        recv_cap: int,
        ack_timeout_sec: float,
        ack_retry_limit: int,
        park_cap: int,
        origin: int,
    ):
        self.spec = spec
        self.ranges = list(ranges)
        self._lib = load_shard_lib()
        if self._lib is None:
            raise RuntimeError("native shard plane unavailable")
        self._offs, self._ns, self._padded = _layout(spec)
        wlo = np.ascontiguousarray([r[0] for r in ranges], np.int64)
        wcnt = np.ascontiguousarray([r[1] for r in ranges], np.int64)
        self._h = self._lib.st_shard_create(
            node._h, self._offs, self._ns, self._padded,
            spec.num_leaves, spec.total, spec.total_n,
            len(ranges), wlo, wcnt, policy_code, recv_cap,
            ack_timeout_sec, ack_retry_limit, park_cap, origin,
        )
        if not self._h:
            raise RuntimeError("st_shard_create failed")
        self._ctrl_buf = ctypes.create_string_buffer(max(recv_cap, 1 << 16))
        self._stopped = False
        self._lib.st_shard_start(self._h)

    def _handle(self):
        h = self._h
        if not h:
            raise RuntimeError("ShardLane used after destroy()")
        return h

    def stop(self) -> None:
        """Stop the plane threads. MUST run before TransportNode.close()
        (they block inside the node's queues/condvars)."""
        if not self._stopped and self._h:
            self._stopped = True
            self._lib.st_shard_stop(self._h)

    def destroy(self) -> None:
        self.stop()
        if self._h:
            self._lib.st_shard_destroy(self._h)
            self._h = None

    # -- membership / routing ------------------------------------------------

    def member_attach(self, link: int, tx: int = 0, rx: int = 0) -> bool:
        return bool(
            self._lib.st_shard_member_attach(self._handle(), link, tx, rx)
        )

    def member_detach(self, link: int) -> bool:
        if not self._h:
            return False
        return bool(self._lib.st_shard_member_detach(self._h, link))

    def set_uplink(self, link: Optional[int]) -> None:
        if self._h:
            self._lib.st_shard_set_uplink(
                self._h, -1 if link is None else link
            )

    def set_route(self, shard: int, link: Optional[int]) -> None:
        if self._h:
            self._lib.st_shard_set_route(
                self._h, shard, -1 if link is None else link
            )

    def set_handoff(self, shard: int, on: bool) -> None:
        if self._h:
            self._lib.st_shard_set_handoff(self._h, shard, 1 if on else 0)

    # -- ownership / data ----------------------------------------------------

    def _n_el(self, shard: int) -> int:
        return self.ranges[shard][1] * 32

    def adopt(self, shard: int, values: Optional[np.ndarray]) -> None:
        ptr = None
        if values is not None:
            v = np.ascontiguousarray(values, np.float32)
            if v.shape != (self._n_el(shard),):
                raise ValueError(
                    f"adopt: values shape {v.shape} != ({self._n_el(shard)},)"
                )
            ptr = v.ctypes.data_as(ctypes.c_void_p)
        self._lib.st_shard_adopt(self._handle(), shard, ptr)

    def release(self, shard: int) -> Optional[np.ndarray]:
        out = np.empty(self._n_el(shard), np.float32)
        if not self._lib.st_shard_release(
            self._handle(), shard, out.ctypes.data_as(ctypes.c_void_p)
        ):
            return None
        return out

    def owns(self, shard: int) -> bool:
        if not self._h:
            return False
        return bool(self._lib.st_shard_owns(self._h, shard))

    def read_shard(self, shard: int) -> Optional[np.ndarray]:
        if not self._h:
            return None
        out = np.empty(self._n_el(shard), np.float32)
        if not self._lib.st_shard_read(self._h, shard, out):
            return None
        return out

    def add_flat(self, flat: np.ndarray) -> None:
        u = np.ascontiguousarray(flat, np.float32)
        self._lib.st_shard_add(self._handle(), u)

    def restore_outbox(self, shard: int, resid: np.ndarray) -> None:
        r = np.ascontiguousarray(resid, np.float32)
        if r.shape != (self._n_el(shard),):
            raise ValueError(
                f"outbox residual shape {r.shape} != ({self._n_el(shard)},)"
            )
        self._lib.st_shard_restore_outbox(self._handle(), shard, r)

    # -- dedup / checkpoint --------------------------------------------------

    def dedup_merge(self, origin: int, seqs) -> None:
        arr = np.ascontiguousarray(sorted(int(s) for s in seqs), np.uint64)
        if arr.size:
            self._lib.st_shard_dedup_merge(
                self._handle(), origin, arr, arr.size
            )

    def dedup_windows(self) -> dict[int, list[int]]:
        """{origin: sorted seqs} of the end-to-end dedup windows alone —
        the handoff ride-along (st_shard_dedup_export: no owned-slice
        copies, unlike the full snapshot). Sized from st_shard_dedup_size
        with a retry, so many-origin clusters never truncate."""
        for _ in range(3):
            cap = int(self._lib.st_shard_dedup_size(self._handle())) + 1024
            origins = np.zeros(cap, np.uint32)
            seqs = np.zeros(cap, np.uint64)
            n = int(
                self._lib.st_shard_dedup_export(
                    self._handle(), origins, seqs, cap
                )
            )
            if n < cap:
                out: dict[int, list[int]] = {}
                for i in range(n):
                    out.setdefault(int(origins[i]), []).append(int(seqs[i]))
                return out
        raise RuntimeError("dedup windows grew faster than the export")

    def fwd_seq(self) -> int:
        if not self._h:
            return 0
        return int(self._lib.st_shard_fwd_seq(self._h))

    def set_fwd_seq(self, seq: int) -> None:
        if self._h:
            self._lib.st_shard_set_fwd_seq(self._h, seq & 0xFFFFFFFF)

    def snapshot(self):
        """Atomic capture under the plane's one mutex: ({shard: values},
        {shard: outbox residual}, {origin: sorted seqs}) — the window/
        slice pair can never tear (the r16 fourth-review invariant)."""
        n_shards = len(self.ranges)
        total_el = sum(c * 32 for _l, c in self.ranges)
        owned_ids = np.zeros(max(1, n_shards), np.int32)
        owned_vals = np.zeros(max(1, total_el), np.float32)
        ob_ids = np.zeros(max(1, n_shards), np.int32)
        ob_vals = np.zeros(max(1, total_el), np.float32)
        # size the window buffer from the plane (+slack for pairs
        # arriving between the size call and the capture; save_shards
        # documents quiesce-first for an exact capture anyway)
        dd_cap = int(self._lib.st_shard_dedup_size(self._handle())) + 4096
        dd_origins = np.zeros(dd_cap, np.uint32)
        dd_seqs = np.zeros(dd_cap, np.uint64)
        dd_n = ctypes.c_int64(0)
        n_ob = ctypes.c_int32(0)
        n_owned = self._lib.st_shard_snapshot(
            self._handle(), owned_ids, owned_vals, ob_ids, ob_vals,
            dd_origins, dd_seqs, dd_cap, ctypes.byref(dd_n),
            ctypes.byref(n_ob),
        )
        owned = {}
        off = 0
        for i in range(n_owned):
            s = int(owned_ids[i])
            n = self._n_el(s)
            owned[s] = owned_vals[off:off + n].copy()
            off += n
        outboxes = {}
        off = 0
        for i in range(int(n_ob.value)):
            s = int(ob_ids[i])
            n = self._n_el(s)
            outboxes[s] = ob_vals[off:off + n].copy()
            off += n
        dedup: dict[int, list[int]] = {}
        for i in range(int(dd_n.value)):
            dedup.setdefault(int(dd_origins[i]), []).append(int(dd_seqs[i]))
        return owned, outboxes, dedup

    # -- accounting / control ------------------------------------------------

    def alloc_bytes(self) -> int:
        if not self._h:
            return 0
        return int(self._lib.st_shard_alloc_bytes(self._h))

    def outbox_bytes(self) -> int:
        if not self._h:
            return 0
        return int(self._lib.st_shard_outbox_bytes(self._h))

    def owned_words(self) -> int:
        if not self._h:
            return 0
        return int(self._lib.st_shard_owned_words(self._h))

    def idle(self, tol: float = 0.0) -> bool:
        if not self._h:
            return True
        return bool(self._lib.st_shard_idle(self._h, tol))

    def counters(self) -> np.ndarray:
        """Counter snapshot; all-zero after destroy(). Layout
        (st_shard_counters): [fwd_msgs_out, fwd_msgs_in, relayed,
        dedup_discards, park_drops, parked, retx_msgs, updates,
        fwd_frames_out, fwd_frames_in, tx_slot_acquires,
        tx_slot_alloc_events, fwd_undecodable, inflight]."""
        out = np.zeros(14, np.uint64)
        if self._h:
            self._lib.st_shard_counters(self._h, out)
        return out

    def heat_applies_by_shard(self, fwd_in: int, owned) -> dict[int, int]:
        """r18 heat numerator: attribute the plane's single fwd_msgs_in
        total (``counters()[1]``) across the owned shards. The C plane
        keeps one apply counter, so this is EXACT in the common
        one-owned-shard topology and an even split otherwise (the python
        tier attributes exactly per shard; the health analyzer's zipf
        detector only needs owner-level resolution when a node owns one
        shard — the bench topology)."""
        owned = sorted(owned)
        if not owned:
            return {}
        share, rem = divmod(int(fwd_in), len(owned))
        return {
            s: share + (1 if i < rem else 0) for i, s in enumerate(owned)
        }

    def poll_ctrl(self) -> Optional[tuple[int, bytes]]:
        """One control-plane message the plane deferred to Python."""
        if not self._h:
            return None
        link = ctypes.c_int32(0)
        buf = self._ctrl_buf
        n = self._lib.st_shard_poll_ctrl(
            self._h, ctypes.byref(link), buf, len(buf)
        )
        if n <= 0:
            return None
        return int(link.value), buf.raw[:n]

    def __repr__(self) -> str:
        if not self._h:
            return "ShardLane(destroyed)"
        c = self.counters()
        return (
            f"ShardLane(shards={len(self.ranges)}, out={int(c[0])}, "
            f"in={int(c[1])}, relayed={int(c[2])})"
        )
