"""Reader-side async all-gather over owner shards (r16).

A sharded cluster has no node holding the full table, so a reader
assembles its view from the owners directly: one r10 ranged read-only
subscription per shard (serve/subscriber.py — unledgered stream, seq-gap
resync, verified freshness), running CONCURRENTLY so the gather is an
async all-gather rather than a sequential walk. ``read()`` stitches the
per-shard pages into one flat array and verifies EVERY shard's staleness
bound — a gather is only as fresh as its stalest shard, and the serving
contract ("fresh-enough or loud", serve.StalenessError) holds per shard
and therefore for the whole view.

Partial views (``ShardGather(..., elements=(lo, hi))``) subscribe only to
the covering shards — embedding/page reads touch exactly the owners they
need.

Capacity caveat: a subscription must land on ONE SPECIFIC owner, but the
transport redirects joiners down the tree once a node's child slots fill
(harmless for classic full-replica subscriptions, fatal here — the
redirect target rejects the out-of-shard range loudly). ShardConfig
.max_children therefore defaults near the transport cap; an owner whose
slots are saturated by writers + subscribers will refuse further gather
legs rather than silently serve the wrong range.

The per-read verified staleness lands in the
``st_shard_gather_staleness_seconds`` histogram (obs/schema.py), the
read-path twin of the writer's FWD counters.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Optional

import numpy as np

from .. import obs as _obs
from ..config import Config, ServeConfig
from ..serve.subscriber import StalenessError, Subscriber
from .map import ShardMap

__all__ = ["ShardGather", "StalenessError"]

#: distinguishes concurrent gathers' registries at the process obs hub
_GATHER_IDS = itertools.count(1)


@dataclasses.dataclass
class _Leg:
    shard: int
    elo: int
    ehi: int
    sub: Subscriber


class ShardGather:
    """One reader's set of per-owner subscriptions (see module docstring).

    ``source`` is a :class:`~shared_tensor_tpu.shard.map.ShardMap`, a map
    document (``ShardNode.map_doc()``), or a ``ShardNode`` (its live map).
    Every targeted shard must have a granted owner — gathering an
    unowned shard raises immediately (there is nothing to subscribe to).
    """

    def __init__(
        self,
        source: Any,
        template: Any,
        config: Config | None = None,
        elements: Optional[tuple[int, int]] = None,
        timeout: float = 30.0,
    ):
        from .node import ShardNode  # local: avoid a cycle at import time

        if isinstance(source, ShardNode):
            m = source.map
            if m is None:
                raise RuntimeError("node has no shard map yet")
        elif isinstance(source, ShardMap):
            m = source
        else:
            m = ShardMap.from_doc(dict(source))
        self.map = m
        self.config = config or Config()
        self._template = template
        total = m.total_words * 32
        if elements is None:
            self._elo, self._ehi = 0, total
        else:
            lo, hi = elements
            if not (0 <= lo < hi <= total):
                raise ValueError(
                    f"gather range [{lo}, {hi}) outside the {total}-element "
                    f"table"
                )
            self._elo, self._ehi = lo, hi
        self._obs_on = _obs.obs_enabled() and self.config.obs.enabled
        self._reg = _obs.Registry()
        self._m_staleness = self._reg.histogram(
            "st_shard_gather_staleness_seconds",
            buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 10.0),
            help="stalest-shard verified staleness per assembled gather",
        )
        # publish to the process hub like ShardNode/Subscriber do — an
        # unregistered registry would make the promised gather-staleness
        # series invisible to obs.top/digests/scrapes
        self._hub = _obs.hub() if self._obs_on else None
        self._label = f"shard-gather-{next(_GATHER_IDS)}"
        if self._hub is not None:
            self._hub.register_registry(self._label, self._reg)
        self.legs: list[_Leg] = []
        try:
            for k in range(m.n_shards):
                s_lo, s_hi = m.element_range(k)
                lo = max(s_lo, self._elo)
                hi = min(s_hi, self._ehi)
                if lo >= hi:
                    continue  # shard outside the requested view
                e = m.owner_of_shard(k)
                if e is None:
                    raise RuntimeError(
                        f"shard {k} has no granted owner — nothing to "
                        f"subscribe to"
                    )
                cfg = dataclasses.replace(
                    self.config,
                    serve=dataclasses.replace(
                        self.config.serve, range=(lo, hi)
                    ),
                )
                self.legs.append(
                    _Leg(k, lo, hi, Subscriber(e.host, e.port, template, cfg))
                )
            deadline = time.monotonic() + timeout
            for leg in self.legs:
                leg.sub.wait_ready(max(0.1, deadline - time.monotonic()))
        except BaseException:
            self.close()
            raise

    def read(
        self, max_staleness: Optional[float] = None
    ) -> tuple[np.ndarray, float]:
        """(flat f32 view of [elo, ehi), worst verified staleness) — every
        shard's bound verified, or :class:`StalenessError` (the gather
        refuses rather than stitch a stale shard in silently)."""
        out = np.zeros(self._ehi - self._elo, np.float32)
        worst = 0.0
        for leg in self.legs:
            flat, staleness, _ver = leg.sub.read_flat(max_staleness)
            worst = max(worst, staleness)
            # the subscription is word-aligned (outward-rounded); slice
            # the requested element window back out of the page
            p_lo, p_hi = leg.sub.range_elements
            i0 = leg.elo - p_lo
            out[leg.elo - self._elo : leg.ehi - self._elo] = flat[
                i0 : i0 + (leg.ehi - leg.elo)
            ]
        if self._obs_on:
            self._m_staleness.observe(worst)
        return out, worst

    def read_tree(self, max_staleness: Optional[float] = None) -> Any:
        """The full table as the caller's pytree structure (full-table
        gathers only)."""
        if (self._elo, self._ehi) != (0, self.map.total_words * 32):
            raise ValueError("read_tree needs a full-table gather")
        from ..ops.codec_np import unflatten_np
        from ..ops.table import make_spec

        flat, _worst = self.read(max_staleness)
        return unflatten_np(flat, make_spec(self._template))

    @property
    def range_elements(self) -> tuple[int, int]:
        return self._elo, self._ehi

    def staleness(self) -> float:
        """Worst staleness across the legs (inf before first verify)."""
        return max(
            (leg.sub.staleness() for leg in self.legs), default=float("inf")
        )

    def metrics(self) -> dict:
        return self._reg.snapshot()

    def close(self) -> None:
        if self._hub is not None:
            self._hub.unregister_registry(self._label)
            self._hub = None
        for leg in self.legs:
            try:
                leg.sub.close()
            except Exception:
                pass
        self.legs = []

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
