"""Cluster-sharded tensor (r16 tentpole): shard ownership, owner-routed
updates, and a cluster-wide ``createOrFetch``.

The classic protocol converges EVERY node on the WHOLE table, so cluster
memory and per-link bytes scale with model size. This package changes the
core invariant: the table's word space is partitioned into
``ShardConfig.n_shards`` contiguous ranges, every word has exactly one
owner node, and the cluster converges on the union of the owned slices —
per-node memory is O(total / n_shards) (the update-exchange decomposition
of "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training": shard-local apply + owner-routed forwarding, i.e.
reduce-scatter / all-gather decomposed over the async tree).

Layers:

- :mod:`.map` — the partition + epoch-merged owner directory;
- :mod:`.state` — shard-local arrays (owned slices, per-subscriber
  residuals, per-target-shard outboxes) + the word-range slice codec;
- :mod:`.node` — the cluster member: capability hello, claim/grant,
  the ledgered FWD plane with end-to-end dedup, relay routing,
  subscriber serving, drain-handoff, restart-restore;
- :mod:`.gather` — the reader's async all-gather over r10 subscriptions.

Entry point: :func:`create_or_fetch_sharded` — the sharded twin of
``create_or_fetch``, with the r14-discipline fallback: joining an
unsharded (or pre-r16) tree returns a CLASSIC full-replica peer, so a
sharded binary interoperates with any existing deployment. ``ST_SHARD=0``
pins the classic protocol end to end.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ..config import Config
from .gather import ShardGather
from .map import OwnerEntry, ShardMap
from .node import (
    ShardBackpressure,
    ShardFallback,
    ShardNode,
    ShardRejected,
    shard_enabled,
)
from .state import ShardState, SliceCodec

__all__ = [
    "OwnerEntry",
    "ShardMap",
    "ShardState",
    "SliceCodec",
    "ShardNode",
    "ShardGather",
    "ShardBackpressure",
    "ShardFallback",
    "ShardRejected",
    "ShardHandle",
    "create_or_fetch_sharded",
    "shard_enabled",
]


class ShardHandle:
    """The user-facing handle ``create_or_fetch_sharded`` returns.

    ``sharded`` is True when the node joined (or created) a sharded
    cluster; False when the tolerant fallback attached a classic
    full-replica peer instead (unsharded/pre-r16 tree, n_shards=0, or
    ST_SHARD=0) — same API either way, so callers don't branch."""

    def __init__(self, node=None, peer=None, template=None, config=None):
        if (node is None) == (peer is None):
            raise ValueError("exactly one of node/peer")
        self._node: Optional[ShardNode] = node
        self._peer = peer
        self._template = template
        self._config = config or Config()

    @property
    def sharded(self) -> bool:
        return self._node is not None

    @property
    def node(self) -> ShardNode:
        if self._node is None:
            raise RuntimeError("classic-fallback handle has no ShardNode")
        return self._node

    @property
    def peer(self):
        if self._peer is None:
            raise RuntimeError("sharded handle has no classic peer")
        return self._peer

    def add(self, delta: Any) -> None:
        (self._node or self._peer).add(delta)

    def drain(self, timeout: float = 60.0, tol: float = 0.0) -> bool:
        return (self._node or self._peer).drain(timeout=timeout, tol=tol)

    def gather(
        self,
        elements: Optional[tuple[int, int]] = None,
        timeout: float = 30.0,
    ) -> ShardGather:
        """An async all-gather view over the cluster (sharded handles
        only — a classic peer already holds the full replica; read it)."""
        return ShardGather(
            self.node, self._template, self._config,
            elements=elements, timeout=timeout,
        )

    def read(self, max_staleness: Optional[float] = None) -> Any:
        """The full table as the caller's pytree. Classic fallback: the
        local replica snapshot (exactly ``peer.read()``). Sharded: a
        verified gather across the owners (staleness bound per shard).

        Each call builds and tears down one subscription per owner — a
        loop that reads repeatedly should hold ONE :meth:`gather` open
        (``with h.gather() as g: ... g.read_tree(...)``) and pay the
        N-leg join once."""
        if self._peer is not None:
            return self._peer.read()
        with self.gather() as g:
            return g.read_tree(max_staleness)

    def jax_view(
        self,
        max_staleness: Optional[float] = None,
        axis_name: str = "cluster",
    ):
        """The table as ONE jax array whose :class:`jax.sharding.
        NamedSharding` mirrors the CLUSTER partition: a 1-D device mesh
        named ``axis_name``, the flat table partitioned along it — the
        "createOrFetch returns an array sharded across the cluster"
        surface (ROADMAP item 1). In a single process this is a local
        projection of the cluster partition (each local device holds the
        shards mapped onto it); under ``jax.distributed`` the same spec
        places each host's addressable slice. Values come from a
        verified gather (sharded) or the local replica (fallback)."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        from ..ops.codec_np import flatten_np
        from ..ops.table import make_spec

        spec = make_spec(self._template)
        if self._peer is not None:
            flat = np.asarray(
                flatten_np(self._peer.read(), spec), np.float32
            )
        else:
            with self.gather() as g:
                flat, _worst = g.read(max_staleness)
        devs = jax.local_devices()
        n = len(devs)
        while n > 1 and spec.total % n:
            n -= 1  # largest local fan-out that divides the padded table
        mesh = Mesh(np.array(devs[:n]), (axis_name,))
        return jax.device_put(
            flat, NamedSharding(mesh, PartitionSpec(axis_name))
        )

    def close(self) -> None:
        (self._node or self._peer).close()

    def leave(self, timeout: float = 60.0) -> bool:
        if self._node is not None:
            return self._node.leave(timeout=timeout)
        return self._peer.leave(timeout=timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def create_or_fetch_sharded(
    host: str,
    port: int,
    template: Any,
    config: Config | None = None,
    timeout: float = 30.0,
) -> ShardHandle:
    """The sharded ``createOrFetch``: create the cluster-sharded tensor at
    ``host:port`` (becoming master and minting the shard map) or join it
    (claiming ``ShardConfig.shard_index``). Falls back to the CLASSIC
    full-replica protocol — returning a working handle either way — when
    sharding is off (``n_shards=0`` / ``ST_SHARD=0``) or the existing
    tree is not sharded (pre-r16 / unsharded parent: the tolerant-hello
    fallback, r14 discipline)."""
    cfg = config or Config()
    if cfg.shard.n_shards <= 0 or not shard_enabled():
        from ..comm.peer import create_or_fetch

        return ShardHandle(
            peer=create_or_fetch(host, port, template, cfg, timeout),
            template=template, config=cfg,
        )
    deadline = time.monotonic() + timeout
    node = ShardNode(host, port, template, cfg)
    try:
        node.wait_ready(timeout)
    except ShardFallback:
        node.close()
        from ..comm.peer import create_or_fetch

        return ShardHandle(
            peer=create_or_fetch(
                host, port, template, cfg,
                max(1.0, deadline - time.monotonic()),
            ),
            template=template, config=cfg,
        )
    except BaseException:
        node.close()
        raise
    return ShardHandle(node=node, template=template, config=cfg)
