"""ShardMap: the cluster's word-space partition and owner directory.

The table's flat word space ([0, total//32) — 32-element packed words,
the r10 RANGE/RDATA unit) is split into ``n_shards`` contiguous ranges at
master creation; the split never changes for the cluster's lifetime.
What DOES change is ownership: shard k's owner entry is
``(epoch, owner_id, host, port)``, minted by the master at claim-grant
time (epoch 1) and re-minted at every handoff/takeover (epoch+1). Nodes
merge entries per shard by epoch — the highest epoch wins — so the map
converges through any flood ordering, and "exactly one owner per shard"
is a property of the mint discipline (only the master grants, only the
current owner hands off) rather than of delivery order.

The map document rides wire.SHARD control messages ({"t": "map"} /
{"t": "grant"}), bounded by DIGEST_MAX_BYTES; ``owner_of_word`` is the
routing primitive the FWD plane keys on.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass
class OwnerEntry:
    epoch: int = 0  # 0 = unowned
    owner: int = 0  # owner's node obs id (informational; identity is epoch)
    host: str = ""
    port: int = 0

    def as_doc(self) -> list:
        return [self.epoch, self.owner, self.host, self.port]

    @staticmethod
    def from_doc(doc) -> "OwnerEntry":
        e, o, h, p = doc
        return OwnerEntry(int(e), int(o), str(h), int(p))


class ShardMap:
    """Partition + owner directory for one sharded cluster."""

    def __init__(self, total_words: int, n_shards: int):
        if n_shards <= 0:
            raise ValueError(f"n_shards must be positive, got {n_shards}")
        if total_words < n_shards:
            raise ValueError(
                f"{total_words} words cannot split into {n_shards} shards"
            )
        self.total_words = int(total_words)
        self.n_shards = int(n_shards)
        # contiguous equal-ish split: the first (total % n) shards get one
        # extra word — deterministic from (total_words, n_shards) alone,
        # so every node derives identical ranges without negotiation
        base, extra = divmod(self.total_words, self.n_shards)
        self.ranges: list[tuple[int, int]] = []
        lo = 0
        for k in range(self.n_shards):
            cnt = base + (1 if k < extra else 0)
            self.ranges.append((lo, cnt))
            lo += cnt
        self.owners: list[OwnerEntry] = [
            OwnerEntry() for _ in range(self.n_shards)
        ]

    # -- geometry ------------------------------------------------------------

    def shard_of_word(self, word: int) -> int:
        if not 0 <= word < self.total_words:
            raise ValueError(
                f"word {word} outside [0, {self.total_words})"
            )
        base, extra = divmod(self.total_words, self.n_shards)
        # first `extra` shards are (base+1) words wide
        wide = extra * (base + 1)
        if word < wide:
            return word // (base + 1)
        return extra + (word - wide) // base if base else self.n_shards - 1

    def word_range(self, shard: int) -> tuple[int, int]:
        """(word_lo, word_cnt) of a shard."""
        return self.ranges[shard]

    def element_range(self, shard: int) -> tuple[int, int]:
        """[elo, ehi) element bounds of a shard (words * 32)."""
        lo, cnt = self.ranges[shard]
        return lo * 32, (lo + cnt) * 32

    # -- ownership -----------------------------------------------------------

    def merge_entry(self, shard: int, entry: OwnerEntry) -> bool:
        """Adopt ``entry`` iff its epoch is newer. Returns True on change."""
        if not 0 <= shard < self.n_shards:
            return False
        if entry.epoch > self.owners[shard].epoch:
            self.owners[shard] = entry
            return True
        return False

    def owner_of_shard(self, shard: int) -> Optional[OwnerEntry]:
        e = self.owners[shard]
        return e if e.epoch > 0 else None

    def owned_shards(self, owner_id: int) -> list[int]:
        return [
            k
            for k, e in enumerate(self.owners)
            if e.epoch > 0 and e.owner == owner_id
        ]

    def fully_owned(self) -> bool:
        return all(e.epoch > 0 for e in self.owners)

    def validate(self) -> list[str]:
        """Structural invariants ([] = clean): ranges form a contiguous
        exact cover of the word space, and no two shards share a live
        owner ENTRY epoch... ownership uniqueness per shard is structural
        (one entry per shard); what can go wrong is the cover."""
        bad = []
        lo = 0
        for k, (wlo, wcnt) in enumerate(self.ranges):
            if wlo != lo or wcnt <= 0:
                bad.append(
                    f"shard {k}: range [{wlo}, {wlo + wcnt}) breaks the "
                    f"contiguous cover at {lo}"
                )
            lo = wlo + wcnt
        if lo != self.total_words:
            bad.append(
                f"ranges cover [0, {lo}), table has {self.total_words} words"
            )
        return bad

    # -- wire ----------------------------------------------------------------

    def as_doc(self) -> dict:
        return {
            "words": self.total_words,
            "n": self.n_shards,
            "owners": [e.as_doc() for e in self.owners],
        }

    @staticmethod
    def from_doc(doc: dict) -> "ShardMap":
        m = ShardMap(int(doc["words"]), int(doc["n"]))
        for k, od in enumerate(doc.get("owners", [])):
            if k < m.n_shards:
                m.owners[k] = OwnerEntry.from_doc(od)
        return m

    def merge_doc(self, doc: dict) -> bool:
        """Merge a peer's map document entry-by-epoch. Returns True if
        anything changed. Geometry mismatches raise — two maps with
        different splits mean the cluster was misconfigured, which must
        be loud (a silently half-merged map would route FWDs into the
        wrong shard forever)."""
        if int(doc["words"]) != self.total_words or int(doc["n"]) != self.n_shards:
            raise ValueError(
                f"shard-map geometry mismatch: theirs "
                f"({doc.get('words')}w/{doc.get('n')}s) vs ours "
                f"({self.total_words}w/{self.n_shards}s)"
            )
        changed = False
        for k, od in enumerate(doc.get("owners", [])):
            if k < self.n_shards:
                changed |= self.merge_entry(k, OwnerEntry.from_doc(od))
        return changed

    def __repr__(self) -> str:  # pragma: no cover
        own = {
            k: f"e{e.epoch}@{e.host}:{e.port}"
            for k, e in enumerate(self.owners)
            if e.epoch > 0
        }
        return (
            f"ShardMap(words={self.total_words}, n={self.n_shards}, "
            f"owners={own})"
        )
