"""``python -m shared_tensor_tpu.ctl`` — the cluster operator surface (r12).

A stdlib-only control CLI over the two file channels the tree ROOT already
serves: the live cluster digest JSON (``ObsConfig.cluster_json_path`` — the
same file ``obs.top`` tails) for read-only views, and the lifecycle command
directory (``LifecycleConfig.ctl_dir``) for operations. Like ``obs.top`` it
never opens a socket into the cluster: it can run anywhere that shares the
files (same host, NFS, a kubectl-cp loop).

Commands::

    python -m shared_tensor_tpu.ctl --file /tmp/st_cluster.json status
    python -m shared_tensor_tpu.ctl --file /tmp/st_cluster.json versions
    python -m shared_tensor_tpu.ctl --ctl-dir /tmp/st_ctl snapshot --dir D
    python -m shared_tensor_tpu.ctl --ctl-dir /tmp/st_ctl restore  --dir D
    python -m shared_tensor_tpu.ctl --ctl-dir /tmp/st_ctl drain NODE
    python -m shared_tensor_tpu.ctl verify --dir D        # offline audit
    python -m shared_tensor_tpu.ctl health --health-file /tmp/st_health.json

``status``/``versions`` read the digest; ``snapshot``/``restore``/``drain``
write ``<ctl_dir>/cmd.json`` (atomically) and poll ``<ctl_dir>/result.json``
for the root's verdict; ``verify`` audits a snapshot directory against its
manifest (shards present, sha256 digests match) with no cluster at all.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid

from .obs import top as _top


def _read_digest(path: str) -> dict:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise SystemExit(f"cannot read cluster digest {path}: {e}")


def _node_val(m: dict, base: str) -> float:
    return _top._node_val(m, base)


def cmd_status(args) -> int:
    doc = _read_digest(args.file)
    print(_top.render(doc, None, 0.0))
    return 0


def cmd_versions(args) -> int:
    """Per-node wire-version audit — the rolling-upgrade view. A healthy
    steady-state cluster shows one version; two versions mid-upgrade is
    expected (decoders accept both framings — compat.py); anything the
    digest has not seen yet shows as '?'."""
    doc = _read_digest(args.file)
    nodes = doc.get("nodes", {})
    versions: dict[int, list[str]] = {}
    for nid in sorted(nodes, key=int):
        v = int(_node_val(nodes[nid].get("m", {}), "st_wire_version"))
        label = nodes[nid].get("name") or nid
        versions.setdefault(v, []).append(str(label))
    for v in sorted(versions):
        label = f"v{v}" if v else "?"
        print(f"wire {label}: {len(versions[v])} node(s) — "
              f"{', '.join(versions[v])}")
    if len([v for v in versions if v]) > 1:
        print("MIXED versions: rolling upgrade in progress (interop is "
              "version-gated — see MIGRATION.md's runbook)")
    return 0


def _send_cmd(ctl_dir: str, cmd: dict, timeout: float) -> dict:
    from .utils.checkpoint import atomic_write_json

    cmd = dict(cmd, req_id=uuid.uuid4().hex)
    atomic_write_json(os.path.join(ctl_dir, "cmd.json"), cmd)
    res_path = os.path.join(ctl_dir, "result.json")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with open(res_path) as f:
                res = json.load(f)
            if res.get("req_id") == cmd["req_id"]:
                return res
        except (OSError, ValueError):
            pass
        time.sleep(0.25)
    raise SystemExit(
        f"no result from the root within {timeout}s — is a root peer "
        f"polling LifecycleConfig.ctl_dir={ctl_dir}?"
    )


def _print_result(res: dict) -> int:
    print(json.dumps(res, indent=2))
    return 0 if res.get("ok") else 1


def cmd_snapshot(args) -> int:
    return _print_result(
        _send_cmd(
            args.ctl_dir,
            {"op": "snapshot", "dir": os.path.abspath(args.dir)},
            args.timeout,
        )
    )


def cmd_restore(args) -> int:
    return _print_result(
        _send_cmd(
            args.ctl_dir,
            {"op": "restore", "dir": os.path.abspath(args.dir)},
            args.timeout,
        )
    )


def cmd_drain(args) -> int:
    return _print_result(
        _send_cmd(
            args.ctl_dir,
            {"op": "drain", "target": args.node},
            args.timeout,
        )
    )


def cmd_health(args) -> int:
    """Fleet health verdict from the root's health.json (r18): exit 0 when
    no SLO alert is firing, 1 while one is (severity printed), 2 when the
    file is unreadable — scriptable as a readiness/paging probe."""
    try:
        with open(args.health_file) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        print(f"cannot read health file {args.health_file}: {e}",
              file=sys.stderr)
        return 2
    slo = doc.get("slo") or {}
    alert = int(slo.get("alert", 0))
    badge = {0: "ok", 1: "TICKET", 2: "PAGE"}.get(alert, str(alert))
    partial = " (PARTIAL: digest breakdowns truncated)" if doc.get(
        "partial") else ""
    print(f"health [{badge}] — beat {doc.get('beats', 0)}, "
          f"{doc.get('nodes', 0)} node(s){partial}")
    worst = (doc.get("staleness") or {}).get("worst")
    if worst:
        unc = worst.get("unc_sec")
        bound = f" ±{unc:.4f}s" if unc is not None else " (uncorrected)"
        print(f"  staleness worst {worst['corrected_sec']:.4f}s{bound} "
              f"@ node {worst.get('node', '?')} "
              f"(objective {(doc.get('staleness') or {}).get('objective_sec', 0):g}s)")
    for name, w in sorted((slo.get("windows") or {}).items()):
        state = "FIRING" if w.get("firing") else "ok"
        print(f"  slo/{name}: {state} — burn {w.get('burn_long', 0.0):.1f}x "
              f"long / {w.get('burn_short', 0.0):.1f}x short "
              f"(threshold {w.get('threshold', 0.0):g}x)")
    heat = doc.get("heat") or {}
    hot = int(heat.get("hot_shard", -1))
    shards = heat.get("shards") or {}
    if shards:
        hottest = max(shards.items(), key=lambda kv: kv[1].get("score", 0.0))
        print(f"  heat: {len(shards)} shard(s), top s{hottest[0]} "
              f"score {hottest[1].get('score', 0.0):.2f}"
              + (f" — HOT shard {hot} (zipf skew)" if hot >= 0 else ""))
    return 1 if alert else 0


def cmd_verify(args) -> int:
    from .utils import checkpoint as ckpt

    problems = ckpt.verify_manifest(args.dir)
    if problems:
        for p in problems:
            print(f"FAIL: {p}")
        return 1
    doc = ckpt.load_manifest(args.dir)
    print(
        f"OK: snapshot {doc.get('snap_id')} — {len(doc.get('nodes', []))} "
        f"shard(s), digests match"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shared_tensor_tpu.ctl",
        description="cluster lifecycle operator CLI (r12)",
    )
    ap.add_argument(
        "--file",
        default="/tmp/st_cluster.json",
        help="cluster digest JSON the root writes "
        "(ObsConfig.cluster_json_path)",
    )
    ap.add_argument(
        "--ctl-dir",
        default="/tmp/st_ctl",
        help="lifecycle command directory the root polls "
        "(LifecycleConfig.ctl_dir)",
    )
    ap.add_argument(
        "--timeout", type=float, default=120.0,
        help="seconds to wait for the root's verdict on an operation",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status", help="render the live cluster digest once")
    sub.add_parser("versions", help="per-node wire-version audit")
    p = sub.add_parser("snapshot", help="consistent-cut snapshot of the tree")
    p.add_argument("--dir", required=True, help="snapshot output directory")
    p = sub.add_parser("restore", help="in-place restore of a live tree")
    p.add_argument("--dir", required=True, help="snapshot directory")
    p = sub.add_parser("drain", help="gracefully drain one node out")
    p.add_argument("node", help="target node name (LifecycleConfig.node_name)")
    p = sub.add_parser("verify", help="offline snapshot-manifest audit")
    p.add_argument("--dir", required=True, help="snapshot directory")
    p = sub.add_parser(
        "health", help="fleet health verdict from the root's health.json"
    )
    p.add_argument(
        "--health-file", default="/tmp/st_health.json",
        help="health JSON the root writes (ObsConfig.health_json_path)",
    )
    args = ap.parse_args(argv)
    return {
        "status": cmd_status,
        "versions": cmd_versions,
        "snapshot": cmd_snapshot,
        "restore": cmd_restore,
        "drain": cmd_drain,
        "verify": cmd_verify,
        "health": cmd_health,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
