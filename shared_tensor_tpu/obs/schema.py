"""Canonical metric-key schema (r08 satellite): ONE name per number.

Before r08 the same quantity had a different name at every layer —
``frames_out`` in ``peer.metrics()``, slot 0 of the ``st_engine_counters``
ABI, ``frames_out`` again (but meaning wire MESSAGES) in the transport's
``LinkStats`` — and the r07 pool stats added two more ad-hoc dicts. This
module is the single source of truth: every telemetry surface (registry
snapshots, the Prometheus exposition, the flight recorder's postmortem
header) speaks these names. The r08 legacy nested ``peer.metrics()``
aliases were carried "for one release", overstayed to r12, and are REMOVED
as of r13 — ``peer.metrics()`` serves only this schema, and
tools/lint_metrics.py fails the suite if a non-schema metric name (or a
legacy alias key) reappears anywhere in the package.

Naming rules (Prometheus conventions):

- ``st_`` prefix; ``_total`` suffix on monotone counters; unit suffixes
  (``_seconds``, ``_bytes``) on measured quantities;
- per-link series carry a ``{link="N"}`` label rendered into the key
  (snapshots are flat dicts; the exposition format parses it natively);
- histograms export as ``{"sum":..,"count":..,"buckets":{le: cum}}`` dicts
  in snapshots and the standard ``_bucket/_sum/_count`` series in
  Prometheus text.
"""

from __future__ import annotations

#: name -> (kind, help). The contract: anything a peer exports uses a name
#: from this table (per-link names via :func:`link_key`).
SCHEMA: dict[str, tuple[str, str]] = {
    # codec-frame taxonomy (peer.metrics() docstring, unchanged semantics)
    "st_frames_out_total": ("counter", "non-idle codec frames handed toward the wire"),
    "st_frames_in_total": ("counter", "codec frames applied from the wire"),
    "st_updates_total": ("counter", "local add() calls merged into the replica"),
    # delivery / go-back-N ledger
    "st_msgs_out_total": ("counter", "wire DATA/BURST messages sent (ACK-ledgered)"),
    "st_msgs_in_total": ("counter", "wire DATA/BURST messages accepted in order"),
    "st_inflight_msgs": ("gauge", "sent-but-unacked messages (0 after drain)"),
    "st_retransmit_msgs_total": ("counter", "go-back-N messages re-sent byte-identical"),
    "st_dedup_discards_total": ("counter", "duplicate/out-of-order data messages discarded unapplied"),
    "st_corrupt_scales_zeroed_total": ("counter", "non-finite scales zeroed at the decode trust boundary"),
    # latency (python tier: true histograms; engine tier: sum/count from the
    # counters ABI — mean-only, the C hot path keeps no buckets)
    "st_ack_rtt_seconds": ("histogram", "ledger-append to cumulative-ACK-pop round trip"),
    "st_ack_rtt_seconds_sum": ("counter", "engine-tier ACK RTT aggregate (seconds)"),
    "st_ack_rtt_seconds_count": ("counter", "engine-tier ACK RTT sample count"),
    "st_encode_seconds": ("histogram", "wire-encode latency per DATA/BURST message"),
    "st_apply_seconds": ("histogram", "decode+apply latency per received batch"),
    # r07 pool occupancy (zero-allocation steady-state assertion)
    "st_tx_slot_acquires_total": ("counter", "frame-slot ring acquires (engine tx ring or wire.FramePool)"),
    "st_tx_slot_alloc_events_total": ("counter", "frame-slot ring fresh allocations (flat in steady state)"),
    "st_tx_slots_allocated": ("gauge", "frame slots currently allocated (engine) / free (python pool)"),
    "st_transport_tx_acquires_total": ("counter", "transport tx buffer acquires"),
    "st_transport_tx_misses_total": ("counter", "transport tx buffer pool misses"),
    "st_transport_rx_acquires_total": ("counter", "transport rx buffer acquires"),
    "st_transport_rx_misses_total": ("counter", "transport rx buffer pool misses"),
    "st_transport_zc_msgs_total": ("counter", "zero-copy (borrowed-slot) sends enqueued"),
    # native event ring health
    "st_obs_events_dropped_total": ("counter", "native ring events lost to overflow (undrained)"),
    # r09 convergence/staleness telemetry (trace context at apply)
    "st_staleness_seconds": ("gauge", "live age of the link's freshest traced update (per-link; raw CLOCK_MONOTONIC delta — the r18 health plane widens it to offset-corrected +/- uncertainty via st_clock_*)"),
    "st_staleness_origin": ("gauge", "origin node id of the link's freshest traced update (per-link; feeds the r18 offset correction)"),
    "st_residual_norm": ("gauge", "L2 norm over every link's error-feedback residual (0 = quiesced)"),
    "st_update_hops": ("histogram", "tree hops traversed by applied traced updates (python tier buckets)"),
    "st_update_hops_sum": ("counter", "engine-tier hop-count aggregate (sum over applied traced msgs)"),
    "st_update_hops_count": ("counter", "engine-tier hop-count sample count"),
    "st_update_hops_last": ("gauge", "hop distance of the latest traced update applied on the link (per-link)"),
    "st_traced_msgs_in_total": ("counter", "applied data messages that carried a v2 trace stamp"),
    # r09 in-band cluster digest aggregation
    "st_digest_sends_total": ("counter", "cluster metrics digests sent up the tree"),
    "st_digest_msgs_in_total": ("counter", "cluster metrics digests received from subtree links"),
    "st_cluster_nodes": ("gauge", "nodes represented in this peer's latest merged cluster digest"),
    # r10 read-path serving tier. st_read_* live on the SUBSCRIBER
    # (serve/subscriber.py registry); st_sub_* split: resyncs/gap/fresh-in/
    # freshness/range on the subscriber, links/msgs-out/fresh-out on the
    # WRITER (peer collector; engine tier serves the counts over the
    # widened counters ABI). Staleness semantics follow the r09 caveat:
    # same-host CLOCK_MONOTONIC deltas.
    "st_read_total": ("counter", "serving reads served (staleness bound verified)"),
    "st_read_stale_total": ("counter", "serving reads REFUSED: staleness bound not verifiable (raised, never silently stale)"),
    "st_read_staleness_seconds": ("histogram", "verified staleness observed at read time"),
    "st_sub_resyncs_total": ("counter", "subscriber re-seed handshakes (seq gap or re-join)"),
    "st_sub_gap_discards_total": ("counter", "data messages discarded while desynced (gap -> resync window)"),
    "st_sub_fresh_marks_total": ("counter", "FRESH drain marks applied by the subscriber"),
    "st_sub_freshness_seconds": ("gauge", "age of the subscriber's newest verified-fresh instant (stamp or FRESH mark)"),
    "st_sub_range_words": ("gauge", "subscribed word count (full table when it equals total/32)"),
    "st_sub_links": ("gauge", "writer: attached read-only subscriber links"),
    "st_sub_msgs_out_total": ("counter", "writer: unledgered data messages sent to subscriber links"),
    "st_sub_fresh_out_total": ("counter", "writer: FRESH drain marks delivered to subscriber links"),
    # r11 data plane: multi-socket link striping + telemetry-adaptive
    # precision. st_stripe_count/live are per-link gauges (negotiated vs
    # surviving sockets); deaths/reroutes count stripe teardowns and the
    # messages re-routed off a dying stripe. st_link_precision is the
    # governor's current wire precision for the link (1 = sign-bit,
    # 2 = sign2); upshifts/downshifts count its flips (ring event
    # precision_shift carries each one); st_frames2_* are the sign2
    # subsets of st_frames_*_total.
    "st_stripe_count": ("gauge", "negotiated sockets striping the link (per-link)"),
    "st_stripe_live": ("gauge", "surviving stripe sockets on the link (per-link)"),
    "st_stripe_deaths_total": ("counter", "stripe sockets torn down (link degraded to survivors)"),
    "st_stripe_reroutes_total": ("counter", "messages re-routed off a dying stripe to survivors"),
    "st_link_precision": ("gauge", "wire precision the governor chose for the link (1=sign, 2=sign2)"),
    "st_precision_upshifts_total": ("counter", "governor upshifts to the sign2 2-bit codec"),
    "st_precision_downshifts_total": ("counter", "governor downshifts back to 1-bit"),
    "st_frames2_out_total": ("counter", "sign2 (2-bit) frames sent (subset of st_frames_out_total)"),
    "st_frames2_in_total": ("counter", "sign2 (2-bit) frames applied (subset of st_frames_in_total)"),
    # r14 same-host shm transport lane: st_shm_active is a per-link gauge
    # (1 = segment mapped, 2 = the link's data plane is live on the shm
    # rings); the *_total counters isolate the lane's share of the link
    # wire traffic (also counted in st_link_wire_* — the lane slots in
    # below the wire-seq layer, like striping). The ring events
    # shm_lane_up / shm_fallback carry each lane switch and each
    # negotiation failure reason.
    "st_shm_active": ("gauge", "shm lane state for the link (1=mapped, 2=data plane live)"),
    "st_shm_msgs_out_total": ("counter", "wire messages sent over shm rings (subset of st_link_wire_msgs_out_total)"),
    "st_shm_msgs_in_total": ("counter", "wire messages received over shm rings (subset of st_link_wire_msgs_in_total)"),
    "st_shm_bytes_out_total": ("counter", "bytes written into shm tx rings (record headers included)"),
    "st_shm_bytes_in_total": ("counter", "bytes drained from shm rx rings (record headers included)"),
    # r12 cluster lifecycle (consistent-cut snapshot/restore, drain-node,
    # rolling upgrade). Gauges ride the per-node digest breakdown, which
    # is what obs.top's lifecycle rows and ``ctl versions`` read at the
    # root: st_wire_version audits a mid-upgrade version skew per node,
    # st_lifecycle_paused / st_snapshot_in_progress / st_drain_in_progress
    # show who is inside a barrier or leaving, and
    # st_snapshot_shards_acked shows barrier progress (subtree shard acks
    # folded at each node so far).
    "st_wire_version": ("gauge", "DATA/BURST framing version this node emits (compat.WIRE_VERSION; the ctl versions / rolling-upgrade audit)"),
    "st_lifecycle_paused": ("gauge", "1 while the node's data production is quiesced by a lifecycle barrier"),
    "st_snapshot_in_progress": ("gauge", "1 while a consistent-cut snapshot barrier is active at this node"),
    "st_snapshot_shards_acked": ("gauge", "subtree shard acks folded into this node's barriers so far"),
    "st_snapshot_total": ("counter", "consistent-cut shards this node captured"),
    "st_snapshot_last_duration_seconds": ("gauge", "root: wall time of the last snapshot/restore barrier"),
    "st_restore_total": ("counter", "shard restores applied (in-place barrier or restart load)"),
    "st_drain_in_progress": ("gauge", "1 while this node is executing a routed drain (seal+drain+close)"),
    "st_drain_total": ("counter", "routed drain commands this node accepted"),
    "st_lifecycle_errors_total": ("counter", "lifecycle barrier/ctl failures (overlap, timeout, lost RESUME, shard I/O)"),
    # r16 cluster-sharded tensor (shared_tensor_tpu/shard). The write
    # plane: fwd_out counts frames a node ORIGINATED (its outbox drains),
    # fwd_in frames applied to an owned shard, relayed frames forwarded
    # verbatim toward their owner, dedup the end-to-end (origin, fwd_seq)
    # discards that close the re-route at-least-once window. park_drops is
    # the bounded-park overflow (loud bounded loss — ShardConfig.park_cap).
    # The read plane: the gather histogram records each assembled view's
    # WORST per-shard verified staleness. owned_words/alloc_bytes ride the
    # per-node digest breakdown (obs.top's shard column, and the chaos
    # harness's per-node memory bound).
    "st_shard_owned_words": ("gauge", "words of the table this node currently owns (0 = pure writer/relay)"),
    "st_shard_alloc_bytes": ("gauge", "resident shard-state bytes: owned slices + subscriber residuals + live outboxes"),
    "st_shard_routes": ("gauge", "shards with a learned next-hop route at this node"),
    "st_shard_parked_msgs": ("gauge", "FWD frames parked awaiting a route (bounded by ShardConfig.park_cap)"),
    "st_shard_fwd_msgs_out_total": ("counter", "FWD frames this node originated (outbox drains)"),
    "st_shard_fwd_msgs_in_total": ("counter", "FWD frames applied to an owned shard"),
    "st_shard_fwd_relayed_total": ("counter", "FWD frames relayed verbatim toward their owner (no re-quantization)"),
    "st_shard_fwd_dedup_total": ("counter", "FWD frames discarded by the owner's (origin, fwd_seq) dedup window"),
    "st_shard_park_drops_total": ("counter", "parked FWD frames dropped at the park-buffer cap (bounded loud loss)"),
    # r17 engine-tier shard plane twins: the same write-plane numbers,
    # served off the native st_shard_counters ABI for engine-lane nodes
    # (the python tier reports them from its own registry — obs.top and
    # the chaos harness stay lane-blind). frames_in is the codec-frame
    # subtotal behind fwd_msgs_in (one FWD message bursts many halving
    # frames — the shard-perf bench's GB/s-equiv numerator); retx counts
    # go-back-N re-sends on the FWD ledger.
    "st_shard_fwd_frames_in_total": ("counter", "codec frames applied from FWD messages (burst subtotal of st_shard_fwd_msgs_in_total)"),
    "st_shard_fwd_retx_total": ("counter", "FWD messages re-sent byte-identical by the shard plane's go-back-N"),
    "st_shard_handoffs_total": ("counter", "shard ownership handoffs completed (counted at both endpoints)"),
    "st_shard_gather_staleness_seconds": ("histogram", "worst per-shard verified staleness per assembled gather view"),
    # r18 fleet health plane. Clock gauges are per-NODE estimates against
    # the tree root's CLOCK_MONOTONIC (obs/clock.py: NTP-style four-stamp
    # exchange over wire.CLOCK, min-RTT selected; the root pins 0/0).
    # Heat numerators are per-SHARD labeled gauges (shard_key) so they
    # ride the digest's per-node breakdown — heat_applies is a monotone
    # cumulative count served as a gauge (the health store derives the
    # rate), heat_outbox is the node's pending backlog toward the shard.
    # st_heat_*/st_slo_* are the ROOT's analyzer verdicts (obs/health.py).
    "st_clock_offset_seconds": ("gauge", "estimated clock offset of this node vs the tree root (C_node - C_root; 0 at the root)"),
    "st_clock_uncertainty_seconds": ("gauge", "error bound on st_clock_offset_seconds (accumulated min-RTT/2 down the tree)"),
    "st_clock_probes_total": ("counter", "clock-offset probes sent up the uplink (wire.CLOCK round trips)"),
    "st_shard_heat_applies": ("gauge", "cumulative FWD applies attributed to the shard at this node (per-shard; rate = shard heat numerator)"),
    "st_shard_heat_outbox_bytes": ("gauge", "pending outbox bytes at this node destined to the shard (per-shard backlog)"),
    "st_shard_heat_deposit_msgs": ("gauge", "cumulative pre-coalesce outbox deposits destined to the shard at this node (writer-side; its rate vs the st_shard_fwd_msgs_out_total drain rate is the coalescing ratio — diverging deposits with flat msgs_out = saturated writer)"),
    "st_shard_heat_deposit_bytes": ("gauge", "cumulative pre-coalesce payload bytes deposited toward the shard at this node (writer-side byte twin of st_shard_heat_deposit_msgs)"),
    "st_shard_outbox_bytes": ("gauge", "total pending outbox bytes across all shards at this node"),
    "st_shard_outbox_limit_bytes": ("gauge", "configured outbox byte cap (ShardConfig.outbox_limit_bytes; 0 = unlimited)"),
    "st_heat_score": ("gauge", "root analyzer: hottest shard's heat score (0.6*rate + 0.3*outbox + 0.1*alloc, each max-normalized)"),
    "st_heat_hot_shard": ("gauge", "root analyzer: zipf-skew hot shard id (-1 = no shard dominates)"),
    "st_slo_burn_rate": ("gauge", "root analyzer: staleness SLO burn rate over the severity's long window (per-window label)"),
    "st_slo_alert": ("gauge", "root analyzer: staleness SLO alert severity (0=ok, 1=ticket, 2=page)"),
    "st_slo_bad_beats_total": ("counter", "root analyzer: digest beats whose worst corrected staleness broke the objective"),
    # per-link series (rendered via link_key)
    "st_link_bytes_out_total": ("counter", "wire bytes sent on the link (incl. framing/keepalives)"),
    "st_link_bytes_in_total": ("counter", "wire bytes received on the link"),
    "st_link_wire_msgs_out_total": ("counter", "transport messages sent (data AND control, no keepalives)"),
    "st_link_wire_msgs_in_total": ("counter", "transport messages received"),
    "st_link_send_queue": ("gauge", "transport send-queue depth"),
    "st_link_recv_queue": ("gauge", "transport recv-queue depth"),
    "st_link_residual_rms": ("gauge", "outgoing residual RMS (0 = quiesced)"),
}

#: Names whose value is PROCESS-scoped, not peer-scoped: every peer in a
#: process reports the same module/ring-global number. The cluster digest
#: (obs/aggregate.py) must deduplicate these by pid before summing, or a
#: 7-peer single-process tree would report them 7x.
PROCESS_GLOBAL = frozenset(
    {
        "st_corrupt_scales_zeroed_total",
        "st_obs_events_dropped_total",
    }
)

def label_key(name: str, label: str, value) -> str:
    """Canonical single-label series key: ``name{label="value"}``. The
    ONLY sanctioned way to build a labeled variant of a schema name —
    tools/lint_metrics.py bans ad-hoc dynamic construction of st_ names,
    so every label site routes through here (or the typed wrappers).
    Numeric values render as integers (link/shard ids); strings (the SLO
    window names) pass through verbatim."""
    if isinstance(value, (int, float)):
        value = int(value)
    return f'{name}{{{label}="{value}"}}'


def link_key(name: str, link: int) -> str:
    """Canonical per-link series key: ``st_link_..._total{link="3"}``."""
    return label_key(name, "link", link)


def shard_key(name: str, shard: int) -> str:
    """Canonical per-shard series key: ``st_shard_...{shard="2"}``."""
    return label_key(name, "shard", shard)
