"""NTP-style clock-offset estimation over the tree control plane (r18).

Since r09 the staleness gauge carried a documented lie across hosts:
``st_staleness_seconds`` differences CLOCK_MONOTONIC stamps from two
machines, which share no epoch — honest same-host, garbage cross-host.
This module closes that debt with the classic four-timestamp exchange
(RFC 5905's origin/receive/transmit/destination, scoped down to a tree):

- every non-root node periodically probes its UPLINK with a
  ``wire.CLOCK`` message carrying ``t1`` (child's clock at send);
- the parent replies with ``t2``/``t3`` (its clock at receive/transmit —
  one read, the handler is synchronous) plus its OWN current offset to
  the root and that offset's uncertainty;
- the child stamps ``t4`` at reply arrival and forms one sample::

      theta = ((t2 - t1) + (t3 - t4)) / 2     # parent_clock - child_clock
      rtt   = (t4 - t1) - (t3 - t2)           # pure network round trip

Writing ``off_X`` for ``C_X - C_root`` (what you add to root time to get
X's clock), ``theta = off_parent - off_child``, so::

      off_child = off_parent - theta
      unc_child = unc_parent + rtt / 2

The root pins ``off = unc = 0`` and never probes; parents only answer
with an offset once they know their own, so convergence flows down the
tree one probe-interval per level. Sample selection is min-RTT over a
bounded window (NTP's clock-filter insight: the shortest round trip has
the least asymmetric queueing, hence the tightest ``rtt/2`` error
bound). No clock is ever *adjusted* — the estimate only corrects
cross-node comparisons (staleness, Perfetto timestamps).

CLOCK messages are control-plane (not in ``wire.is_data``), so chaos
fault injection never drops them — the r06 rule that keeps the control
plane exempt so observed failures are always *data* failures.
"""

from __future__ import annotations

from collections import deque
from typing import Optional

#: Bounded sample window for min-RTT selection.
SAMPLE_WINDOW = 16


class ClockSync:
    """Per-node offset estimator; one instance per peer/shard node.

    Thread-safety: mutated only from the owner's receive/housekeeping
    thread (same discipline as the digest state), read by collectors —
    plain attribute reads of immutable tuples, no lock needed.
    """

    def __init__(self, now_ns, is_root: bool = False) -> None:
        self._now_ns = now_ns
        self._samples: deque = deque(maxlen=SAMPLE_WINDOW)
        self.probes = 0          # probes sent (root never probes)
        self.replies = 0         # usable replies folded in
        self._is_root = bool(is_root)
        # (offset_ns, uncertainty_ns) relative to the root, or None until
        # the first usable reply; the root is its own reference.
        self._est: Optional[tuple] = (0, 0) if is_root else None

    # -- state -----------------------------------------------------------

    @property
    def known(self) -> bool:
        return self._est is not None

    @property
    def offset_ns(self) -> int:
        return self._est[0] if self._est is not None else 0

    @property
    def uncertainty_ns(self) -> int:
        return self._est[1] if self._est is not None else 0

    @property
    def offset_seconds(self) -> float:
        return self.offset_ns / 1e9

    @property
    def uncertainty_seconds(self) -> float:
        return self.uncertainty_ns / 1e9

    # -- wire payloads (bounded JSON dicts, wire.encode_clock) -----------

    def probe_payload(self) -> dict:
        """Child -> parent probe."""
        self.probes += 1
        return {"op": "probe", "t1": int(self._now_ns())}

    def reply_payload(self, probe: dict) -> dict:
        """Parent's synchronous answer to a child's probe. ``t2 == t3``
        because the handler turns the reply around inline — the serve
        time is already inside the child's measured RTT either way."""
        now = int(self._now_ns())
        out = {
            "op": "reply",
            "t1": int(probe.get("t1", 0)),
            "t2": now,
            "t3": now,
        }
        if self._est is not None:
            out["off_ns"] = int(self._est[0])
            out["unc_ns"] = int(self._est[1])
        return out

    def on_reply(self, reply: dict) -> bool:
        """Fold a parent reply into the estimate; returns True if the
        sample was usable (parent knew its own offset)."""
        if self._is_root or "off_ns" not in reply:
            return False  # parent not yet converged: skip, try again
        t4 = int(self._now_ns())
        t1 = int(reply.get("t1", 0))
        t2 = int(reply.get("t2", 0))
        t3 = int(reply.get("t3", 0))
        rtt = (t4 - t1) - (t3 - t2)
        if rtt < 0:
            return False  # nonsensical (reordered stamps): drop
        theta = ((t2 - t1) + (t3 - t4)) // 2
        self._samples.append(
            (rtt, theta, int(reply["off_ns"]), int(reply.get("unc_ns", 0)))
        )
        rtt, theta, p_off, p_unc = min(self._samples)
        self._est = (p_off - theta, p_unc + rtt // 2)
        self.replies += 1
        return True
