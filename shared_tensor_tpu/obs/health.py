"""Fleet health analyzer over the digest time-series (r18 tentpole).

Sits at the tree ROOT, fed one cluster digest per DIGEST beat, and turns
the raw series into the three signals ROADMAP's rebalancing loop needs:

- **Per-shard heat.** Each shard's score combines its FWD apply rate
  (owner-side work), the fleet-wide outbox backlog destined to it
  (writer-side pressure), and the owner's allocation share::

      heat_k = 0.6 * rate_k/max_rate + 0.3 * outbox_k/max_outbox
             + 0.1 * alloc_k/max_alloc

  Rates come from the reset-tolerant store (`timeseries.TimeSeriesStore`)
  over a short trailing window, so a freshly re-grafted node never makes
  a shard look cold or molten. **Zipf-skew detection** names the hot
  shard only when its rate dominates the mean of the others by
  ``skew_ratio`` (default 3x) — a uniformly busy fleet has no hot shard.

- **Honest cross-host staleness.** Raw ``st_staleness_seconds`` compares
  the applier's CLOCK_MONOTONIC to the origin's. With the r18 clock
  plane each node exports its estimated offset to the root
  (``st_clock_offset_seconds`` ± ``st_clock_uncertainty_seconds``) and
  the origin node of each link's freshest update
  (``st_staleness_origin{link=}``), so the analyzer widens every value
  to offset-corrected-with-error-bound::

      corrected = raw - off_applier + off_origin
      unc       = unc_applier + unc_origin

  Nodes without clock estimates (engine-tier lanes, pre-r18 peers) keep
  their raw value with ``unc = null`` — flagged, never silently trusted.

- **Staleness SLO with multi-window burn-rate alerts.** Per beat the SLI
  is "worst corrected staleness <= objective". Burn rate over a window
  is ``bad_fraction / error_budget``; an alert severity fires when BOTH
  its long and short windows exceed the threshold (the SRE-workbook
  pairing: the long window means the budget is really burning, the short
  window means it is burning NOW — and makes the alert self-clearing
  when the short window recovers). Defaults: page = 14.4x over
  (60s, 5s), ticket = 6x over (300s, 30s), budget 1%.

Everything lands in a machine-readable ``health.json`` (atomic tmp +
``os.replace``, same discipline as the cluster digest) that the future
split/merge rebalancer consumes directly, plus ``metrics()`` gauges that
ride the root's normal registry export. ``partial`` mirrors the digest's
``truncated`` count: totals are exact, but per-node detail (and thus
heat/staleness attribution) may be missing nodes.
"""

from __future__ import annotations

import json
import os
import re
from collections import deque

from .timeseries import TimeSeriesStore
from . import schema as _schema

HEALTH_VERSION = 1

#: Trailing window for heat rates: long enough to smooth beat jitter,
#: short enough that a hot shard is named within ~3 digest beats.
HEAT_WINDOW_SEC = 10.0

#: Default multi-window burn-rate severities: (name, long_s, short_s,
#: threshold). Thresholds follow the SRE-workbook sizing for a 1% budget.
DEFAULT_WINDOWS = (
    ("page", 60.0, 5.0, 14.4),
    ("ticket", 300.0, 30.0, 6.0),
)

_SHARD_RE = re.compile(r'\{shard="(\d+)"\}$')
_LINK_RE = re.compile(r'\{link="(\d+)"\}')


class HealthAnalyzer:
    """Digest-beat health analytics at the root; see module docstring.

    Thread-safety: ``beat`` runs on the root's housekeeping thread (the
    same one that publishes digests); ``metrics``/``doc`` read a single
    attribute holding an immutable-by-convention dict, so collector
    threads see either the previous or the new beat, never a torn one.
    """

    def __init__(
        self,
        path: str = "",
        history: int = 256,
        objective_sec: float = 1.0,
        budget: float = 0.01,
        windows=DEFAULT_WINDOWS,
        skew_ratio: float = 3.0,
        heat_window_sec: float = HEAT_WINDOW_SEC,
        emit=None,
    ) -> None:
        self.path = path
        self.store = TimeSeriesStore(max_points=history)
        self.objective_sec = float(objective_sec)
        self.budget = max(1e-9, float(budget))
        self.windows = tuple(
            (str(n), float(l), float(s), float(t)) for n, l, s, t in windows
        )
        self.skew_ratio = max(1.0, float(skew_ratio))
        self.heat_window_sec = float(heat_window_sec)
        self._emit = emit
        longest = max((w[1] for w in self.windows), default=60.0)
        # SLI ring sized by time, not beats: prune past the longest window
        self._sli: deque = deque()
        self._sli_horizon_ns = int(longest * 1e9) + int(1e9)
        self._firing: dict = {}      # severity name -> bool
        self._hot_named = -1         # last hot shard announced via event
        self.bad_beats = 0
        self._doc: dict = {}

    # -- per-beat pipeline ----------------------------------------------

    def beat(self, doc: dict, t_ns: int) -> dict:
        """Ingest one cluster digest and recompute the health document."""
        t_ns = int(t_ns)
        self.store.ingest(doc, t_ns)
        clock = self._clock_table(doc)
        stale = self._staleness(doc, clock)
        slo = self._slo(stale, t_ns)
        heat = self._heat(doc)
        out = {
            "v": HEALTH_VERSION,
            "t_ns": t_ns,
            "beats": self.store.beats,
            "nodes": len(doc.get("nodes", {})),
            "truncated": int(doc.get("truncated", 0)),
            "partial": int(doc.get("truncated", 0)) > 0,
            "store": {"series": len(self.store), "evicted": self.store.evicted},
            "clock": clock,
            "staleness": stale,
            "slo": slo,
            "heat": heat,
            "trends": {
                "frames_in_per_sec": self.store.cluster_rate(
                    "st_frames_in_total", self.heat_window_sec
                ),
                "updates_per_sec": self.store.cluster_rate(
                    "st_updates_total", self.heat_window_sec
                ),
            },
        }
        self._doc = out
        if self.path:
            self._write(out)
        return out

    def doc(self) -> dict:
        return self._doc

    def metrics(self) -> dict:
        """Analyzer gauges folded into the root's registry collector so
        they ride the normal export (and the next digest)."""
        d = self._doc
        if not d:
            return {}
        out = {
            "st_heat_score": max(
                (s["score"] for s in d["heat"]["shards"].values()), default=0.0
            ),
            "st_heat_hot_shard": float(d["heat"]["hot_shard"]),
            "st_slo_alert": float(d["slo"]["alert"]),
            "st_slo_bad_beats_total": self.bad_beats,
        }
        for name, w in d["slo"]["windows"].items():
            out[_schema.label_key("st_slo_burn_rate", "window", name)] = w[
                "burn_long"
            ]
        return out

    # -- clock -----------------------------------------------------------

    @staticmethod
    def _clock_table(doc: dict) -> dict:
        """node id (str) -> {"off_sec","unc_sec"} for nodes that export
        clock estimates; absent nodes have no usable offset."""
        table = {}
        for nid, entry in doc.get("nodes", {}).items():
            m = entry.get("m", {})
            off = m.get("st_clock_offset_seconds")
            if off is None:
                continue
            table[str(int(nid))] = {
                "off_sec": float(off),
                "unc_sec": float(m.get("st_clock_uncertainty_seconds", 0.0)),
            }
        return table

    # -- staleness --------------------------------------------------------

    def _staleness(self, doc: dict, clock: dict) -> dict:
        nodes_out = {}
        worst = None
        for nid, entry in doc.get("nodes", {}).items():
            m = entry.get("m", {})
            applier = clock.get(str(int(nid)))
            for name, raw in m.items():
                if not (
                    name == "st_staleness_seconds"
                    or name.startswith("st_staleness_seconds{")
                ):
                    continue
                raw = float(raw)
                lm = _LINK_RE.search(name)
                origin = None
                if lm is not None:
                    ov = m.get(
                        _schema.label_key(
                            "st_staleness_origin", "link", lm.group(1)
                        )
                    )
                    if ov is not None:
                        origin = int(ov)
                oc = clock.get(str(origin)) if origin is not None else None
                if applier is not None and oc is not None:
                    corrected = raw - applier["off_sec"] + oc["off_sec"]
                    unc = applier["unc_sec"] + oc["unc_sec"]
                else:
                    corrected, unc = raw, None
                corrected = max(0.0, corrected)
                rec = {
                    "raw_sec": raw,
                    "corrected_sec": corrected,
                    "unc_sec": unc,
                    "origin": origin,
                }
                prev = nodes_out.get(str(int(nid)))
                if prev is None or corrected > prev["corrected_sec"]:
                    nodes_out[str(int(nid))] = rec
                if worst is None or corrected > worst["corrected_sec"]:
                    worst = dict(rec, node=int(nid))
        return {
            "objective_sec": self.objective_sec,
            "worst": worst,
            "nodes": nodes_out,
        }

    # -- SLO --------------------------------------------------------------

    def _burn(self, window_sec: float, now_ns: int) -> float:
        since = now_ns - int(window_sec * 1e9)
        total = bad = 0
        for t, b in self._sli:
            if t >= since:
                total += 1
                bad += b
        if total == 0:
            return 0.0
        return (bad / total) / self.budget

    def _slo(self, stale: dict, t_ns: int) -> dict:
        worst = stale.get("worst")
        bad = 1 if worst and worst["corrected_sec"] > self.objective_sec else 0
        self.bad_beats += bad
        self._sli.append((t_ns, bad))
        horizon = t_ns - self._sli_horizon_ns
        while self._sli and self._sli[0][0] < horizon:
            self._sli.popleft()
        windows_out = {}
        alert = 0
        for i, (name, long_s, short_s, thr) in enumerate(self.windows):
            burn_long = self._burn(long_s, t_ns)
            burn_short = self._burn(short_s, t_ns)
            was = self._firing.get(name, False)
            if not was and burn_long >= thr and burn_short >= thr:
                self._firing[name] = True
                self._event(
                    "slo_alert_fire",
                    arg=i,
                    detail=f"{name}: burn {burn_long:.1f}x/{burn_short:.1f}x"
                    f" over {long_s:g}s/{short_s:g}s (thr {thr:g}x)",
                )
            elif was and burn_short < thr:
                self._firing[name] = False
                self._event(
                    "slo_alert_clear",
                    arg=i,
                    detail=f"{name}: short-window burn {burn_short:.1f}x"
                    f" back under {thr:g}x",
                )
            if self._firing.get(name, False):
                alert = max(alert, 2 if name == "page" else 1)
            windows_out[name] = {
                "long_sec": long_s,
                "short_sec": short_s,
                "threshold": thr,
                "burn_long": burn_long,
                "burn_short": burn_short,
                "firing": self._firing.get(name, False),
            }
        return {"budget": self.budget, "alert": alert, "windows": windows_out}

    # -- heat --------------------------------------------------------------

    def _heat(self, doc: dict) -> dict:
        rates: dict = {}       # shard -> summed apply rate
        outbox: dict = {}      # shard -> summed outbox backlog bytes
        alloc: dict = {}       # shard -> owner alloc bytes (max-rate node)
        owner_rate: dict = {}
        for nid, entry in doc.get("nodes", {}).items():
            m = entry.get("m", {})
            node_alloc = float(m.get("st_shard_alloc_bytes", 0.0))
            for name, v in m.items():
                sm = _SHARD_RE.search(name)
                if sm is None:
                    continue
                shard = int(sm.group(1))
                if name.startswith("st_shard_heat_applies{"):
                    r = self.store.node_rate(
                        int(nid), name, self.heat_window_sec
                    )
                    rates[shard] = rates.get(shard, 0.0) + r
                    # the node applying this shard's FWDs is its owner:
                    # its allocation share feeds the headroom term
                    if r >= owner_rate.get(shard, 0.0):
                        owner_rate[shard] = r
                        alloc[shard] = node_alloc
                elif name.startswith("st_shard_heat_outbox_bytes{"):
                    outbox[shard] = outbox.get(shard, 0.0) + float(v)
        shards = sorted(set(rates) | set(outbox))
        max_rate = max(rates.values(), default=0.0)
        max_out = max(outbox.values(), default=0.0)
        max_alloc = max(alloc.values(), default=0.0)
        out_shards = {}
        for k in shards:
            rn = rates.get(k, 0.0) / max_rate if max_rate > 0 else 0.0
            on = outbox.get(k, 0.0) / max_out if max_out > 0 else 0.0
            an = alloc.get(k, 0.0) / max_alloc if max_alloc > 0 else 0.0
            out_shards[str(k)] = {
                "apply_rate": rates.get(k, 0.0),
                "outbox_bytes": outbox.get(k, 0.0),
                "alloc_frac": an,
                "score": 0.6 * rn + 0.3 * on + 0.1 * an,
            }
        hot, ratio = -1, 0.0
        if len(shards) >= 2 and max_rate > 0:
            top = max(shards, key=lambda k: rates.get(k, 0.0))
            others = [rates.get(k, 0.0) for k in shards if k != top]
            mean_rest = sum(others) / len(others) if others else 0.0
            ratio = (
                rates.get(top, 0.0) / mean_rest if mean_rest > 0 else float("inf")
            )
            if ratio >= self.skew_ratio:
                hot = top
        if hot >= 0 and hot != self._hot_named:
            self._event(
                "hot_shard",
                arg=hot,
                detail=f"shard {hot} rate {rates.get(hot, 0.0):.1f}/s, "
                f"{'inf' if ratio == float('inf') else f'{ratio:.1f}'}x the rest",
            )
        self._hot_named = hot
        return {
            "window_sec": self.heat_window_sec,
            "shards": out_shards,
            "hot_shard": hot,
            "skew_ratio": ratio if ratio != float("inf") else -1.0,
        }

    # -- plumbing ----------------------------------------------------------

    def _event(self, name: str, arg: int = 0, detail: str = "") -> None:
        if self._emit is not None:
            try:
                self._emit(name, arg, detail)
            except Exception:
                pass  # health events must never take down the beat

    def _write(self, out: dict) -> None:
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(out, f, separators=(",", ":"))
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
