"""In-band cluster metrics aggregation (r09 tentpole, part 2).

r08 gave every process a registry and one merged timeline — but a 7-node
tree was still seven disjoint stories: answering "how many frames has the
CLUSTER applied?" meant scraping seven endpoints and hoping the instants
lined up. This module defines the bounded **metrics digest** that peers
piggyback up the tree on their existing links (wire.DIGEST control
messages, one per ``ObsConfig.digest_interval_sec``): each node folds its
own registry snapshot together with its children's latest digests and
forwards the merge, so the ROOT's ``peer.metrics(cluster=True)`` and
Prometheus exposition serve a live whole-tree view — the TF-paper /
Podracer lesson that cluster-level accounting, not per-process logs, is
what makes distributed training debuggable (PAPERS.md).

Merge semantics (the digest is a CRDT-ish bounded summary, not a log):

- **counters** merge by SUM, with per-link labels stripped first — link
  ids are node-local, so the cluster view wants "bytes the tree sent",
  not "bytes link 3 of node 5 sent" (the per-node breakdown keeps the
  labeled values);
- **histograms** merge by BUCKET-ADD (same fixed bounds everywhere —
  registry.LATENCY_BUCKETS — so cumulative bucket counts, sums and counts
  add losslessly);
- **gauges** merge by LABELED MAX/MIN: a gauge has no meaningful sum, but
  "worst staleness anywhere, and WHO" is exactly the operator question —
  each extremum carries the node id that owns it.

Every digest also carries a bounded per-node breakdown (``nodes``): each
node's gauges plus a whitelisted counter subset, stamped with the node's
snapshot time. Bound discipline: at most :data:`MAX_NODES` breakdown
entries and ``wire.DIGEST_MAX_BYTES`` encoded bytes — past either, the
OLDEST nodes' breakdowns are dropped (merged totals keep every node's
contribution; ``truncated`` counts the dropped breakdowns so the view
never silently narrows).

Subtree disjointness makes the sums exact: a node merges only its own
snapshot plus digests from CHILD links, and the tree has no cycles, so
every registry contributes exactly once to the root's totals — the
equality ``root totals == Σ per-node registries`` is asserted at a
quiesced instant by tests/test_obs_cluster.py and the CHAOS_r09 run.
"""

from __future__ import annotations

import json
from typing import Optional

from . import schema as _schema

#: Digest document version (the JSON carries it as "v").
DIGEST_VERSION = 1

#: Per-node breakdown entries kept before truncation (merged totals are
#: never truncated — only the per-node detail).
MAX_NODES = 256

#: Counters included in each node's per-node breakdown (the whole-tree
#: totals cover every counter; the breakdown is the operator's "which node
#: is the outlier" view and stays small by listing only the load-bearing
#: ones).
NODE_COUNTERS = (
    "st_frames_out_total",
    "st_frames_in_total",
    "st_updates_total",
    "st_msgs_out_total",
    "st_msgs_in_total",
    "st_retransmit_msgs_total",
    "st_dedup_discards_total",
    "st_traced_msgs_in_total",
    # r17: obs.top's shard columns read these off the per-node breakdown
    # (they rendered 0 for every node before — the cluster SUM carried
    # them but the breakdown didn't); engine-lane nodes serve them off
    # the native counters ABI through the same collector names
    "st_shard_fwd_msgs_in_total",
    "st_shard_fwd_msgs_out_total",
)


def base_name(name: str) -> str:
    """Strip a rendered ``{label=...}`` suffix: the schema keys per-link
    series as ``st_link_..._total{link="3"}``."""
    return name.split("{", 1)[0]


def empty() -> dict:
    return {
        "v": DIGEST_VERSION,
        "nodes": {},
        "counters": {},
        "hists": {},
        "gmax": {},
        "gmin": {},
        # PROCESS-scoped counters (schema.PROCESS_GLOBAL), keyed by pid:
        # every peer in a process reports the same ring/module-global
        # value, so merging by pid-keyed assignment (not sum) dedups
        # within a process while still summing across processes.
        "proc": {},
        "truncated": 0,
    }


def _kind(name: str, value) -> str:
    if isinstance(value, dict):
        return "histogram" if "buckets" in value else "skip"
    k = _schema.SCHEMA.get(base_name(name))
    if k is not None:
        return k[0]
    # unknown name (forward compat): counters are self-describing by suffix
    return "counter" if base_name(name).endswith("_total") else "gauge"


def _merge_hist(dst: dict, name: str, snap: dict) -> None:
    h = dst.setdefault(name, {"sum": 0.0, "count": 0, "buckets": {}})
    h["sum"] += float(snap.get("sum", 0.0))
    h["count"] += int(snap.get("count", 0))
    hb = h["buckets"]
    for bound, cum in snap.get("buckets", {}).items():
        key = str(float(bound))  # JSON round trips turn float keys to str
        hb[key] = hb.get(key, 0) + int(cum)


def from_snapshot(node_id: int, snap: dict, t_ns: int) -> dict:
    """One node's registry snapshot -> a single-node digest document."""
    import os

    doc = empty()
    mine: dict = {}
    pid = str(os.getpid())
    for name, v in snap.items():
        kind = _kind(name, v)
        if kind == "histogram":
            _merge_hist(doc["hists"], base_name(name), v)
            continue
        if kind == "skip" or not isinstance(v, (int, float)):
            continue
        if kind == "counter":
            b = base_name(name)
            if b in _schema.PROCESS_GLOBAL:
                doc["proc"].setdefault(pid, {})[b] = v
                continue
            doc["counters"][b] = doc["counters"].get(b, 0) + v
            if b in NODE_COUNTERS and "{" not in name:
                mine[name] = v
            continue
        # gauge: per-node breakdown keeps the labeled value; the cluster
        # extrema aggregate on the base name, tagged with the owner
        mine[name] = v
        b = base_name(name)
        cur = doc["gmax"].get(b)
        if cur is None or v > cur[0]:
            doc["gmax"][b] = [v, node_id]
        cur = doc["gmin"].get(b)
        if cur is None or v < cur[0]:
            doc["gmin"][b] = [v, node_id]
    doc["nodes"][str(int(node_id))] = {"t_ns": int(t_ns), "m": mine}
    return doc


def merge(into: dict, other: Optional[dict]) -> dict:
    """Fold ``other`` (a child subtree's digest) into ``into`` in place and
    return it. Node breakdowns are keyed by process-unique node id, so a
    re-sent child digest REPLACES at the caller (peers keep only each
    child's latest) — this merge itself assumes disjoint inputs."""
    if not other:
        return into
    for name, v in other.get("counters", {}).items():
        into["counters"][name] = into["counters"].get(name, 0) + v
    for name, h in other.get("hists", {}).items():
        _merge_hist(into["hists"], name, h)
    for name, pair in other.get("gmax", {}).items():
        cur = into["gmax"].get(name)
        if cur is None or pair[0] > cur[0]:
            into["gmax"][name] = list(pair)
    for name, pair in other.get("gmin", {}).items():
        cur = into["gmin"].get(name)
        if cur is None or pair[0] < cur[0]:
            into["gmin"][name] = list(pair)
    into["nodes"].update(other.get("nodes", {}))
    for pid, vals in other.get("proc", {}).items():
        # pid-keyed assignment: same-process peers overwrite with the same
        # (or fresher) value instead of double-counting
        into["proc"].setdefault(pid, {}).update(vals)
    into["truncated"] += int(other.get("truncated", 0))
    return into


def process_global_totals(doc: dict) -> dict:
    """The cluster-wide PROCESS_GLOBAL counter totals: summed across the
    distinct processes the digest has seen (each counted once)."""
    out: dict = {}
    for vals in doc.get("proc", {}).values():
        for name, v in vals.items():
            out[name] = out.get(name, 0) + v
    return out


def bounded(doc: dict) -> dict:
    """Enforce the digest bounds before encoding: at most MAX_NODES
    per-node breakdowns and wire.DIGEST_MAX_BYTES encoded bytes. Oldest
    breakdowns (stalest t_ns) drop first; merged totals are untouched and
    ``truncated`` counts what the per-node view lost. Over-budget
    shrinking estimates each drop's size from the entry's own encoding
    (additive to within framing commas) and re-measures once per batch —
    never one full-document re-encode per evicted node."""
    from ..comm import wire as _wire

    nodes = doc["nodes"]
    by_age = sorted(nodes, key=lambda k: nodes[k].get("t_ns", 0))
    drop = len(by_age) - MAX_NODES
    for k in by_age[:max(0, drop)]:
        del nodes[k]
        doc["truncated"] += 1
    by_age = by_age[max(0, drop):]
    cap = _wire.DIGEST_MAX_BYTES
    while by_age:
        size = len(json.dumps(doc, separators=(",", ":")).encode())
        if size <= cap:
            break
        over = size - cap
        freed = 0
        while by_age and freed < over:
            k = by_age.pop(0)
            entry = nodes.pop(k)
            doc["truncated"] += 1
            # this entry's encoded footprint: key + entry + framing slack
            freed += len(
                json.dumps({k: entry}, separators=(",", ":")).encode()
            )
    return doc


def cluster_nodes(doc: dict) -> int:
    return len(doc.get("nodes", {}))


def _num(v) -> str:
    """Full-precision sample rendering: %g's 6 significant digits would
    round any counter past ~1e6 (a soak's frame totals within minutes),
    silently breaking the cluster view's ``totals == sum of registries``
    exactness for scrapers. Integers render as integers; floats via repr
    (shortest round-trip)."""
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


def prometheus_text(doc: dict) -> str:
    """Render a cluster digest as Prometheus text exposition: merged
    counters/histograms as plain series, per-node GAUGES with a ``node``
    label, extrema as ``_max``/``_min`` series labeled with the owning
    node. Per-node COUNTER breakdowns stay in the JSON digest / obs.top
    only — emitting them as labeled twins of the merged series would make
    ``sum()`` double-count and interleave metric families (strict
    OpenMetrics parsers reject that); per-node gauges group by family so
    the exposition stays contiguous."""
    lines: list[str] = []
    for name in sorted(doc.get("counters", {})):
        lines.append(f"# TYPE {name} counter")
        lines.append(f'{name} {_num(doc["counters"][name])}')
    for name, v in sorted(process_global_totals(doc).items()):
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_num(v)}")
    for name in sorted(doc.get("hists", {})):
        h = doc["hists"][name]
        lines.append(f"# TYPE {name} histogram")
        for bound in sorted(h["buckets"], key=float):
            lines.append(
                f'{name}_bucket{{le="{float(bound):g}"}} {h["buckets"][bound]}'
            )
        lines.append(f'{name}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f'{name}_sum {_num(h["sum"])}')
        lines.append(f'{name}_count {h["count"]}')
    for kind, suffix in (("gmax", "_max"), ("gmin", "_min")):
        for name in sorted(doc.get(kind, {})):
            v, node = doc[kind][name]
            lines.append(
                f'{name}{suffix}{{node="{int(node)}"}} {_num(v)}'
            )
    # per-node gauges, pivoted name-major so each family is one
    # contiguous block of {node=...}-labeled samples
    families: dict[str, list[str]] = {}
    for node in sorted(doc.get("nodes", {}), key=int):
        for name, v in doc["nodes"][node].get("m", {}).items():
            base = base_name(name)
            if _kind(name, v) != "gauge":
                continue
            if "{" in name:  # fold the node label into the existing set
                head, rest = name.split("{", 1)
                families.setdefault(base, []).append(
                    f'{head}{{node="{int(node)}",{rest} {_num(v)}'
                )
            else:
                families.setdefault(base, []).append(
                    f'{name}{{node="{int(node)}"}} {_num(v)}'
                )
    for base in sorted(families):
        lines.extend(families[base])
    return "\n".join(lines) + "\n"
