"""Unified cross-tier telemetry (r08 tentpole).

Three pieces, one timeline:

- :mod:`~shared_tensor_tpu.obs.registry` — metrics registry (counters /
  gauges / fixed-bucket histograms) with dict snapshots, Prometheus text
  exposition and a background JSONL sink; canonical key names come from
  :mod:`~shared_tensor_tpu.obs.schema` (the old per-layer dicts survive as
  deprecated aliases in ``peer.metrics()``).
- :mod:`~shared_tensor_tpu.obs.events` — the native event ring drain
  (``st_obs_drain`` over lock-free per-thread rings in sttransport.cpp)
  merged with Python-tier events on the shared CLOCK_MONOTONIC timebase.
- :mod:`~shared_tensor_tpu.obs.recorder` — the process flight recorder:
  last-N merged events + registry snapshots dumped to a postmortem file on
  crash-point fires, recv-thread exceptions and go-back-N teardowns.

The r09 distributed tier adds:

- :mod:`~shared_tensor_tpu.obs.aggregate` — the bounded cluster metrics
  digest peers piggyback up the tree (counters by sum, histograms by
  bucket-add, gauges by labeled max/min); the root's
  ``peer.metrics(cluster=True)`` serves the whole-tree view;
- :mod:`~shared_tensor_tpu.obs.trace_export` — causal-path reconstruction
  over the wire trace context + Perfetto/Chrome ``trace_event`` export;
- :mod:`~shared_tensor_tpu.obs.top` — ``python -m shared_tensor_tpu.obs.top``,
  a live terminal view of the root's cluster digest.

``ST_OBS=0`` disables the whole subsystem (native ring emission included);
the production default is ON — the native events are rare (link churn,
recovery, injected faults) and the OBS_r08 gate proves the hot-path cost
is <2% (benchmarks/obs_overhead.py).
"""

from __future__ import annotations

import os

from .recorder import FlightRecorder, ObsHub, hub  # noqa: F401
from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS,
    Registry,
)

_ENABLED: bool | None = None


def obs_enabled() -> bool:
    """Process-wide obs switch (env ``ST_OBS``, default on). Cached: the
    peers' hot paths gate on this via a bound attribute, and flipping it
    mid-process is a bench-only move (:func:`set_enabled`)."""
    global _ENABLED
    if _ENABLED is None:
        _ENABLED = os.environ.get("ST_OBS", "1") != "0"
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Flip obs at runtime — for A/B overhead measurement
    (benchmarks/obs_overhead.py), not production use. Also flips the native
    ring's emission flag when the transport library is loaded. Peers
    created before the flip keep their construction-time wiring."""
    global _ENABLED
    _ENABLED = bool(on)
    try:
        from ..comm import transport

        lib = transport._lib
        if lib is not None:
            lib.st_obs_set_enabled(1 if on else 0)
    except Exception:
        pass
