"""``python -m shared_tensor_tpu.obs.top`` — live cluster digest viewer.

A `top`-style terminal view over the r09 in-band cluster digest: point the
tree ROOT at a file (``ObsConfig.cluster_json_path="/tmp/st_cluster.json"``)
and this tool tails it, rendering whole-tree totals, throughput rates
(derived by differencing counters between refreshes) and the per-node
breakdown — staleness, residual norm, frames, retransmits — one row per
node. Stdlib-only and read-only: it never touches the peers, so it can run
on a box that merely shares the file (NFS, kubectl cp loop, scp cron).

Usage:
    python -m shared_tensor_tpu.obs.top --file /tmp/st_cluster.json
    python -m shared_tensor_tpu.obs.top --file ... --once   # one frame (CI)
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _fmt(v, width=10) -> str:
    if isinstance(v, float):
        if v != 0 and (abs(v) < 1e-3 or abs(v) >= 1e6):
            return f"{v:>{width}.2e}"
        return f"{v:>{width}.3f}"
    return f"{v:>{width}}"


def _node_val(m: dict, base: str) -> float:
    """A node's value for a base metric name, max over labeled variants
    (per-link gauges render as ``name{link="N"}``)."""
    best = 0.0
    for k, v in m.items():
        if k == base or k.startswith(base + "{"):
            best = max(best, float(v))
    return best


def render(doc: dict, prev: dict | None, dt: float) -> str:
    nodes = doc.get("nodes", {})
    counters = doc.get("counters", {})
    pc = (prev or {}).get("counters", {})

    def rate(name: str) -> float:
        if dt <= 0:
            return 0.0
        return max(0.0, (counters.get(name, 0) - pc.get(name, 0)) / dt)

    lines = [
        f"shared-tensor cluster digest — {len(nodes)} node(s), "
        f"{doc.get('truncated', 0)} breakdown(s) truncated",
        (
            f"  frames in {counters.get('st_frames_in_total', 0):.0f}"
            f" ({rate('st_frames_in_total'):.0f}/s)"
            f"   msgs in {counters.get('st_msgs_in_total', 0):.0f}"
            f" ({rate('st_msgs_in_total'):.0f}/s)"
            f"   retx {counters.get('st_retransmit_msgs_total', 0):.0f}"
            f"   dedup {counters.get('st_dedup_discards_total', 0):.0f}"
        ),
    ]
    gmax = doc.get("gmax", {})
    stale = gmax.get("st_staleness_seconds")
    resid = gmax.get("st_residual_norm")
    if stale or resid:
        parts = []
        if stale:
            parts.append(
                f"worst staleness {stale[0]:.4f}s @ node {int(stale[1])}"
            )
        if resid:
            parts.append(
                f"worst residual L2 {resid[0]:.4g} @ node {int(resid[1])}"
            )
        lines.append("  " + "   ".join(parts))
    # r12 lifecycle rows: only rendered while something is happening —
    # a snapshot barrier in progress (per-node paused/acked state), a
    # drain underway, or a version skew worth knowing about mid-upgrade
    lc_rows = []
    versions = set()
    for nid in sorted(nodes, key=int):
        m = nodes[nid].get("m", {})
        v = int(_node_val(m, "st_wire_version"))
        if v:
            versions.add(v)
        state = []
        if _node_val(m, "st_snapshot_in_progress") > 0:
            state.append(
                f"snapshotting (acks {int(_node_val(m, 'st_snapshot_shards_acked'))})"
            )
        elif _node_val(m, "st_lifecycle_paused") > 0:
            state.append("paused (barrier)")
        if _node_val(m, "st_drain_in_progress") > 0:
            state.append("draining")
        if state:
            lc_rows.append(f"  node {nid}: " + ", ".join(state))
    if lc_rows:
        lines.append("  lifecycle:")
        lines.extend(lc_rows)
    if len(versions) > 1:
        lines.append(
            f"  lifecycle: MIXED wire versions {sorted(versions)} "
            f"(rolling upgrade in progress?)"
        )
    lines.append("")
    # r16: the shard column renders only when any node reports shard
    # telemetry — a classic full-replica tree keeps the r12 layout
    sharded = any(
        _node_val(nodes[nid].get("m", {}), "st_shard_owned_words") > 0
        or _node_val(nodes[nid].get("m", {}), "st_shard_routes") > 0
        for nid in nodes
    )
    hdr = (
        f"{'node':>6} {'stale_s':>10} {'resid_L2':>10} {'hops':>5} "
        f"{'frames_out':>11} {'frames_in':>10} {'updates':>8} "
        f"{'retx':>6} {'inflight':>9}"
    )
    if sharded:
        hdr += f" {'owned_w':>9} {'fwd_in':>8} {'fwd_out':>8}"
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for nid in sorted(nodes, key=int):
        m = nodes[nid].get("m", {})
        row = (
            f"{nid:>6} "
            f"{_fmt(_node_val(m, 'st_staleness_seconds'))} "
            f"{_fmt(_node_val(m, 'st_residual_norm'))} "
            f"{int(_node_val(m, 'st_update_hops_last')):>5} "
            f"{_fmt(m.get('st_frames_out_total', 0), 11)} "
            f"{_fmt(m.get('st_frames_in_total', 0))} "
            f"{_fmt(m.get('st_updates_total', 0), 8)} "
            f"{_fmt(m.get('st_retransmit_msgs_total', 0), 6)} "
            f"{_fmt(_node_val(m, 'st_inflight_msgs'), 9)}"
        )
        if sharded:
            row += (
                f" {int(_node_val(m, 'st_shard_owned_words')):>9}"
                f" {int(m.get('st_shard_fwd_msgs_in_total', 0)):>8}"
                f" {int(m.get('st_shard_fwd_msgs_out_total', 0)):>8}"
            )
        lines.append(row)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live terminal view of the r09 cluster metrics digest"
    )
    ap.add_argument(
        "--file", required=True,
        help="digest JSON the tree root writes (ObsConfig.cluster_json_path)",
    )
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    args = ap.parse_args(argv)
    prev, prev_t = None, 0.0
    while True:
        try:
            with open(args.file) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            if args.once:
                print(f"cannot read digest {args.file}: {e}", file=sys.stderr)
                return 1
            time.sleep(args.interval)
            continue
        now = time.monotonic()
        frame = render(doc, prev, now - prev_t if prev is not None else 0.0)
        if args.once:
            print(frame)
            return 0
        # ANSI clear + home keeps it flicker-light without curses
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        prev, prev_t = doc, now
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
