"""``python -m shared_tensor_tpu.obs.top`` — live cluster digest viewer.

A `top`-style terminal view over the r09 in-band cluster digest: point the
tree ROOT at a file (``ObsConfig.cluster_json_path="/tmp/st_cluster.json"``)
and this tool tails it, rendering whole-tree totals, throughput rates
(derived by differencing counters between refreshes) and the per-node
breakdown — staleness, residual norm, frames, retransmits — one row per
node. Stdlib-only and read-only: it never touches the peers, so it can run
on a box that merely shares the file (NFS, kubectl cp loop, scp cron).

v2 (r18): the viewer keeps a bounded :class:`~.timeseries.TimeSeriesStore`
across refreshes, so the header grows throughput/staleness sparklines; when
the root also publishes ``health.json`` (``ObsConfig.health_json_path``),
``--health`` adds the SLO burn-rate row, a per-shard heat table naming the
hot shard, and a per-node heat column. Truncation is honest: a truncated
digest says how many node breakdowns were dropped and flags every total as
exact-but-partial-breakdown rather than letting partial rows read as whole.

Usage:
    python -m shared_tensor_tpu.obs.top --file /tmp/st_cluster.json
    python -m shared_tensor_tpu.obs.top --file ... --health /tmp/st_health.json
    python -m shared_tensor_tpu.obs.top --file ... --once   # one frame (CI)
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
import time

_SPARK_CHARS = "▁▂▃▄▅▆▇█"
_SHARD_LABEL_RE = re.compile(r'\{shard="(\d+)"\}$')


def _fmt(v, width=10) -> str:
    if isinstance(v, float):
        if v != 0 and (abs(v) < 1e-3 or abs(v) >= 1e6):
            return f"{v:>{width}.2e}"
        return f"{v:>{width}.3f}"
    return f"{v:>{width}}"


def _spark(vals, width: int = 16) -> str:
    """Unicode sparkline over the last ``width`` values (min..max scaled;
    a flat series renders as all-low so spikes stay visually loud)."""
    vals = [float(v) for v in vals][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_CHARS[0] * len(vals)
    return "".join(
        _SPARK_CHARS[
            min(len(_SPARK_CHARS) - 1, int((v - lo) / span * len(_SPARK_CHARS)))
        ]
        for v in vals
    )


def _deltas(vals) -> list[float]:
    """Positive first-differences of a counter series (reset -> 0 step)."""
    out = []
    for a, b in zip(vals, vals[1:]):
        out.append(max(0.0, float(b) - float(a)))
    return out


def _node_heat(m: dict, health: dict) -> float:
    """A node's heat: max health score over the shards it reports apply
    telemetry for (the applier of a shard's FWDs is its owner)."""
    shards = (health.get("heat") or {}).get("shards") or {}
    best = 0.0
    for k in m:
        sm = _SHARD_LABEL_RE.search(k)
        if sm is not None and k.startswith("st_shard_heat_applies{"):
            best = max(best, float(shards.get(sm.group(1), {}).get("score", 0.0)))
    return best


def _node_val(m: dict, base: str) -> float:
    """A node's value for a base metric name, max over labeled variants
    (per-link gauges render as ``name{link="N"}``)."""
    best = 0.0
    for k, v in m.items():
        if k == base or k.startswith(base + "{"):
            best = max(best, float(v))
    return best


def render(
    doc: dict,
    prev: dict | None,
    dt: float,
    health: dict | None = None,
    store=None,
) -> str:
    nodes = doc.get("nodes", {})
    counters = doc.get("counters", {})
    pc = (prev or {}).get("counters", {})
    truncated = int(doc.get("truncated", 0))

    def rate(name: str) -> float:
        if dt <= 0:
            return 0.0
        return max(0.0, (counters.get(name, 0) - pc.get(name, 0)) / dt)

    # truncation honesty (r18): a bounded digest drops whole NODE
    # breakdowns oldest-first but keeps exact totals — say both, loudly,
    # instead of letting a partial node table read as the whole fleet.
    if truncated:
        trunc_note = (
            f"{truncated} node breakdown(s) TRUNCATED — totals exact, "
            f"per-node rows partial"
        )
    else:
        trunc_note = "breakdown complete"
    lines = [
        f"shared-tensor cluster digest — {len(nodes)} node(s), {trunc_note}",
        (
            f"  frames in {counters.get('st_frames_in_total', 0):.0f}"
            f" ({rate('st_frames_in_total'):.0f}/s)"
            f"   msgs in {counters.get('st_msgs_in_total', 0):.0f}"
            f" ({rate('st_msgs_in_total'):.0f}/s)"
            f"   retx {counters.get('st_retransmit_msgs_total', 0):.0f}"
            f"   dedup {counters.get('st_dedup_discards_total', 0):.0f}"
        ),
    ]
    if store is not None and len(store):
        spark_rows = []
        fr = _deltas(store.values(("cluster", "st_frames_in_total")))
        if fr:
            spark_rows.append(f"frames/beat {_spark(fr)}")
        st = store.values(("gmax", "st_staleness_seconds"))
        if st:
            spark_rows.append(f"worst stale {_spark(st)}")
        if spark_rows:
            lines.append("  " + "   ".join(spark_rows))
    gmax = doc.get("gmax", {})
    stale = gmax.get("st_staleness_seconds")
    resid = gmax.get("st_residual_norm")
    if stale or resid:
        parts = []
        if stale:
            parts.append(
                f"worst staleness {stale[0]:.4f}s @ node {int(stale[1])}"
            )
        if resid:
            parts.append(
                f"worst residual L2 {resid[0]:.4g} @ node {int(resid[1])}"
            )
        lines.append("  " + "   ".join(parts))
    # r18 fleet health: SLO burn-rate row + per-shard heat table, fed by
    # the root's health.json (absent -> layout falls back to pre-r18)
    if health:
        slo = health.get("slo") or {}
        worst = (health.get("staleness") or {}).get("worst")
        alert = int(slo.get("alert", 0))
        badge = {0: "ok", 1: "TICKET", 2: "PAGE"}.get(alert, str(alert))
        parts = [f"slo [{badge}]"]
        if worst:
            unc = worst.get("unc_sec")
            parts.append(
                f"worst corrected {worst['corrected_sec']:.4f}s"
                + (f" ±{unc:.4f}s" if unc is not None else " (uncorrected)")
                + f" @ node {worst.get('node', '?')}"
            )
        for name, w in sorted((slo.get("windows") or {}).items()):
            flame = "*" if w.get("firing") else ""
            parts.append(
                f"{name}{flame} {w.get('burn_long', 0.0):.1f}x/"
                f"{w.get('burn_short', 0.0):.1f}x"
            )
        lines.append("  " + "   ".join(parts))
        heat = health.get("heat") or {}
        shards = heat.get("shards") or {}
        if shards:
            hot = int(heat.get("hot_shard", -1))
            cells = []
            for k in sorted(shards, key=int):
                s = shards[k]
                mark = "!" if int(k) == hot else ""
                cells.append(
                    f"s{k}{mark}={s.get('score', 0.0):.2f}"
                    f"({s.get('apply_rate', 0.0):.0f}/s)"
                )
            tail = f"   HOT shard {hot}" if hot >= 0 else ""
            lines.append("  heat: " + " ".join(cells) + tail)
    # r12 lifecycle rows: only rendered while something is happening —
    # a snapshot barrier in progress (per-node paused/acked state), a
    # drain underway, or a version skew worth knowing about mid-upgrade
    lc_rows = []
    versions = set()
    for nid in sorted(nodes, key=int):
        m = nodes[nid].get("m", {})
        v = int(_node_val(m, "st_wire_version"))
        if v:
            versions.add(v)
        state = []
        if _node_val(m, "st_snapshot_in_progress") > 0:
            state.append(
                f"snapshotting (acks {int(_node_val(m, 'st_snapshot_shards_acked'))})"
            )
        elif _node_val(m, "st_lifecycle_paused") > 0:
            state.append("paused (barrier)")
        if _node_val(m, "st_drain_in_progress") > 0:
            state.append("draining")
        if state:
            lc_rows.append(f"  node {nid}: " + ", ".join(state))
    if lc_rows:
        lines.append("  lifecycle:")
        lines.extend(lc_rows)
    if len(versions) > 1:
        lines.append(
            f"  lifecycle: MIXED wire versions {sorted(versions)} "
            f"(rolling upgrade in progress?)"
        )
    lines.append("")
    # r16: the shard column renders only when any node reports shard
    # telemetry — a classic full-replica tree keeps the r12 layout
    sharded = any(
        _node_val(nodes[nid].get("m", {}), "st_shard_owned_words") > 0
        or _node_val(nodes[nid].get("m", {}), "st_shard_routes") > 0
        for nid in nodes
    )
    hdr = (
        f"{'node':>6} {'stale_s':>10} {'resid_L2':>10} {'hops':>5} "
        f"{'frames_out':>11} {'frames_in':>10} {'updates':>8} "
        f"{'retx':>6} {'inflight':>9}"
    )
    if sharded:
        hdr += f" {'owned_w':>9} {'fwd_in':>8} {'fwd_out':>8}"
    heatcol = bool(health and (health.get("heat") or {}).get("shards"))
    if heatcol:
        hdr += f" {'heat':>6}"
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for nid in sorted(nodes, key=int):
        m = nodes[nid].get("m", {})
        row = (
            f"{nid:>6} "
            f"{_fmt(_node_val(m, 'st_staleness_seconds'))} "
            f"{_fmt(_node_val(m, 'st_residual_norm'))} "
            f"{int(_node_val(m, 'st_update_hops_last')):>5} "
            f"{_fmt(m.get('st_frames_out_total', 0), 11)} "
            f"{_fmt(m.get('st_frames_in_total', 0))} "
            f"{_fmt(m.get('st_updates_total', 0), 8)} "
            f"{_fmt(m.get('st_retransmit_msgs_total', 0), 6)} "
            f"{_fmt(_node_val(m, 'st_inflight_msgs'), 9)}"
        )
        if sharded:
            row += (
                f" {int(_node_val(m, 'st_shard_owned_words')):>9}"
                f" {int(m.get('st_shard_fwd_msgs_in_total', 0)):>8}"
                f" {int(m.get('st_shard_fwd_msgs_out_total', 0)):>8}"
            )
        if heatcol:
            row += f" {_node_heat(m, health):>6.2f}"
        lines.append(row)
    if truncated:
        lines.append(
            f"({truncated} more node(s) in totals but not shown: "
            f"breakdown truncated at the digest bound)"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="live terminal view of the r09 cluster metrics digest"
    )
    ap.add_argument(
        "--file", required=True,
        help="digest JSON the tree root writes (ObsConfig.cluster_json_path)",
    )
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument(
        "--once", action="store_true", help="render one frame and exit"
    )
    ap.add_argument(
        "--health", default="",
        help="root health.json (ObsConfig.health_json_path) for the SLO "
        "row, heat table and per-node heat column",
    )
    args = ap.parse_args(argv)
    from .timeseries import TimeSeriesStore

    store = TimeSeriesStore()
    prev, prev_t, last_t = None, 0.0, -1
    while True:
        try:
            with open(args.file) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            if args.once:
                print(f"cannot read digest {args.file}: {e}", file=sys.stderr)
                return 1
            time.sleep(args.interval)
            continue
        health = None
        if args.health:
            try:
                with open(args.health) as f:
                    health = json.load(f)
            except (OSError, ValueError):
                health = None  # stale/missing health is not fatal to top
        now = time.monotonic()
        # the viewer keeps its own series (sparklines): ingest each NEW
        # digest once, keyed by its freshest node stamp (the digest has
        # no top-level stamp of its own)
        t_ns = max(
            (int(n.get("t_ns", 0)) for n in doc.get("nodes", {}).values()),
            default=time.monotonic_ns(),
        )
        if t_ns != last_t:
            store.ingest(doc, t_ns)
            last_t = t_ns
        frame = render(
            doc, prev, now - prev_t if prev is not None else 0.0,
            health=health, store=store,
        )
        if args.once:
            print(frame)
            return 0
        # ANSI clear + home keeps it flicker-light without curses
        sys.stdout.write("\x1b[2J\x1b[H" + frame + "\n")
        sys.stdout.flush()
        prev, prev_t = doc, now
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.exit(main())
