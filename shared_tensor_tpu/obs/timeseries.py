"""Bounded ring-buffered time-series over the cluster digest (r18).

The r09 digest is an instantaneous snapshot: the root knows "frames in =
1.2M" but not whether that is 10/s or 100k/s, and ROADMAP's rebalancing
loop needs *rates and trends* — which shard is hot NOW, is staleness
growing or shrinking — not point values. This module keeps a bounded
in-memory history of digest beats at the root and derives rates from it.

Design constraints (deliberately boring):

- **Bounded everything.** Each series is a ring of at most ``max_points``
  samples; the store holds at most ``max_series`` series, evicting the
  least-recently-updated series first (``evicted`` counts them — the
  store never silently narrows, same honesty rule as the digest's
  ``truncated``).
- **Reset-tolerant rates.** Counter rates are computed as the sum of
  POSITIVE deltas over the window divided by the window span: a counter
  reset (node re-graft, restore from checkpoint) shows up as a negative
  delta and contributes zero instead of an enormous negative spike.
  Rates are therefore never negative.
- **Stdlib-only, no threads.** The store is fed synchronously from the
  digest beat (one ``ingest`` per DIGEST interval) and read by the
  health analyzer / ``obs.top`` in the same thread or under the caller's
  lock.

Series are keyed by tuples so callers never string-parse:

- ``("cluster", name)`` — whole-tree counter totals and gauge extrema
  (extrema keys are ``("gmax", name)`` / ``("gmin", name)``);
- ``("hist", name, "p50"|"p99")`` — quantile tracks over the merged
  histograms;
- ``("node", node_id, name)`` — per-node breakdown entries, including
  labeled gauges (the rendered name, e.g. ``st_shard_heat_applies{shard="2"}``,
  is kept verbatim as the key's last element).
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, Optional

from . import aggregate as _agg

#: Default ring depth per series: 256 beats at the default 0.5s digest
#: interval is ~2 minutes of history — enough for the SLO long windows.
DEFAULT_MAX_POINTS = 256

#: Default series cap: a 256-node fleet with ~16 breakdown entries each
#: fits with headroom; past it the least-recently-updated series evict.
DEFAULT_MAX_SERIES = 4096

#: Histogram quantile tracks sampled per beat.
QUANTILES = (0.5, 0.99)


def hist_quantile(hist: dict, q: float) -> float:
    """Linear-interpolated quantile from a merged digest histogram
    (``{"sum","count","buckets":{bound_str: cumulative_count}}``).
    Returns 0.0 for an empty histogram; values past the last finite
    bucket clamp to that bucket's bound (the +Inf tail has no width to
    interpolate over)."""
    count = int(hist.get("count", 0))
    if count <= 0:
        return 0.0
    target = q * count
    bounds = sorted(hist.get("buckets", {}), key=float)
    prev_bound, prev_cum = 0.0, 0
    for b in bounds:
        cum = int(hist["buckets"][b])
        bound = float(b)
        if cum >= target:
            span = cum - prev_cum
            if span <= 0:
                return bound
            frac = (target - prev_cum) / span
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    return prev_bound  # target lives in the +Inf bucket: clamp


class RingSeries:
    """One bounded series: (t_ns, value) pairs, oldest evicted first."""

    __slots__ = ("_ring", "last_t_ns")

    def __init__(self, max_points: int) -> None:
        self._ring: deque = deque(maxlen=max_points)
        self.last_t_ns = 0

    def append(self, t_ns: int, value: float) -> None:
        self._ring.append((int(t_ns), float(value)))
        self.last_t_ns = int(t_ns)

    def __len__(self) -> int:
        return len(self._ring)

    def points(self) -> list:
        return list(self._ring)

    def latest(self) -> Optional[float]:
        return self._ring[-1][1] if self._ring else None

    def window(self, since_ns: int) -> list:
        """Samples with t_ns >= since_ns, plus one anchor sample at or
        before the edge when available (rate interpolation needs it)."""
        pts = list(self._ring)
        lo = 0
        for i, (t, _) in enumerate(pts):
            if t >= since_ns:
                lo = i
                break
        else:
            return pts[-1:] if pts else []
        return pts[max(0, lo - 1):]


class TimeSeriesStore:
    """Bounded store of digest-beat series; see module docstring."""

    def __init__(
        self,
        max_points: int = DEFAULT_MAX_POINTS,
        max_series: int = DEFAULT_MAX_SERIES,
    ) -> None:
        self._max_points = max(2, int(max_points))
        self._max_series = max(1, int(max_series))
        self._series: dict = {}
        self.evicted = 0
        self.beats = 0

    # -- feeding ---------------------------------------------------------

    def _put(self, key: tuple, t_ns: int, value) -> None:
        if not isinstance(value, (int, float)):
            return
        s = self._series.get(key)
        if s is None:
            s = self._series[key] = RingSeries(self._max_points)
        s.append(t_ns, value)

    def ingest(self, doc: dict, t_ns: int) -> None:
        """Sample one cluster digest document at time ``t_ns``."""
        self.beats += 1
        for name, v in doc.get("counters", {}).items():
            self._put(("cluster", name), t_ns, v)
        for name, v in _agg.process_global_totals(doc).items():
            self._put(("cluster", name), t_ns, v)
        for name, pair in doc.get("gmax", {}).items():
            self._put(("gmax", name), t_ns, pair[0])
        for name, pair in doc.get("gmin", {}).items():
            self._put(("gmin", name), t_ns, pair[0])
        for name, h in doc.get("hists", {}).items():
            for q in QUANTILES:
                self._put(
                    ("hist", name, f"p{int(q * 100)}"),
                    t_ns,
                    hist_quantile(h, q),
                )
        for nid, entry in doc.get("nodes", {}).items():
            node = int(nid)
            for name, v in entry.get("m", {}).items():
                self._put(("node", node, name), t_ns, v)
        self._evict()

    def _evict(self) -> None:
        over = len(self._series) - self._max_series
        if over <= 0:
            return
        by_age = sorted(self._series, key=lambda k: self._series[k].last_t_ns)
        for k in by_age[:over]:
            del self._series[k]
            self.evicted += 1

    # -- reading ---------------------------------------------------------

    def __len__(self) -> int:
        return len(self._series)

    def keys(self) -> Iterable[tuple]:
        return self._series.keys()

    def series(self, key: tuple) -> Optional[RingSeries]:
        return self._series.get(key)

    def latest(self, key: tuple) -> Optional[float]:
        s = self._series.get(key)
        return s.latest() if s is not None else None

    def values(self, key: tuple, n: int = 0) -> list:
        """The series' values (optionally the last ``n``), oldest first."""
        s = self._series.get(key)
        if s is None:
            return []
        vals = [v for _, v in s.points()]
        return vals[-n:] if n > 0 else vals

    def rate(self, key: tuple, window_sec: float, now_ns: Optional[int] = None) -> float:
        """Reset-tolerant counter rate over the trailing window: sum of
        positive inter-sample deltas divided by the covered span. Counter
        resets (negative deltas) contribute zero; the result is >= 0."""
        s = self._series.get(key)
        if s is None or len(s) < 2:
            return 0.0
        if now_ns is None:
            now_ns = s.last_t_ns
        pts = s.window(int(now_ns - window_sec * 1e9))
        if len(pts) < 2:
            return 0.0
        gained = 0.0
        for (_, a), (_, b) in zip(pts, pts[1:]):
            if b > a:
                gained += b - a
        span = (pts[-1][0] - pts[0][0]) / 1e9
        if span <= 0:
            return 0.0
        return gained / span

    def node_rate(self, node: int, name: str, window_sec: float) -> float:
        return self.rate(("node", int(node), name), window_sec)

    def cluster_rate(self, name: str, window_sec: float) -> float:
        return self.rate(("cluster", name), window_sec)
