"""Causal-path reconstruction + Perfetto/Chrome trace export (r09).

The r09 wire trace context gives every applied DATA/BURST message a
``trace_apply`` event — ``(node, link)`` say who applied it, ``arg``
carries the update generation (the origin's monotonic ns at add() time)
and ``extra`` packs ``origin_node << 8 | hop``. This module turns a flight
recorder timeline into:

- :func:`trace_paths` — ``{(origin, gen): [hop records]}``, the full
  causal path of each update generation across the tree, plus
  :func:`contiguous` to verify a path has no hop gaps (a generation whose
  mass coalesced into a newer one simply STOPS — hops 1..k — but can
  never skip a hop: a node only re-stamps hop k+1 after applying hop k,
  so a gap means lost telemetry, and the CHAOS_r09 gate bounds it);
- :func:`chrome_trace` — a Chrome ``trace_event`` JSON document
  (Perfetto/chrome://tracing loadable): every event becomes an instant on
  its node's track, and each multi-hop update generation becomes a flow
  (``s``/``t`` arrows) hopping across node tracks — the visual "which hop
  delayed this update" answer.

Timestamps: each node's CLOCK_MONOTONIC, converted to the trace format's
microseconds. Same-process nodes share a timebase; across hosts (or the
r18 skew simulator) they do NOT — pass ``offsets_ns`` (node obs id ->
estimated offset from the root clock, i.e. ``st_clock_offset_seconds`` *
1e9) and every event is re-timestamped onto the ROOT's clock, so
cross-node flow arrows land in causal order instead of clock order.
``pid`` is the node obs id (process-unique), with metadata records naming
them; ``tid`` separates the native ("c") and Python ("py") tiers.
"""

from __future__ import annotations

import json
from typing import Iterable, Optional

from . import events as ev


def unpack_trace(event: ev.Event) -> Optional[tuple[int, int, int]]:
    """(origin, gen, hop) of a trace_apply event, else None."""
    if event.name != "trace_apply":
        return None
    return (event.extra >> 8) & 0xFFFFFF, event.arg, event.extra & 0xFF


def trace_paths(
    events: Iterable[ev.Event],
) -> dict[tuple[int, int], list[dict]]:
    """Group trace_apply events by update generation. Each value is the
    generation's hop list sorted by hop then time:
    ``{"hop": h, "node": applier, "link": l, "t_ns": t, "tier": tier}``.
    Retransmissions of the same message are deduplicated upstream by the
    wire's go-back-N acceptance (a discarded duplicate never emits
    trace_apply), so one (generation, node) pair appears at most once per
    delivery."""
    out: dict[tuple[int, int], list[dict]] = {}
    for e in events:
        tr = unpack_trace(e)
        if tr is None:
            continue
        origin, gen, hop = tr
        out.setdefault((origin, gen), []).append(
            {
                "hop": hop,
                "node": e.node,
                "link": e.link,
                "t_ns": e.t_ns,
                "tier": e.tier,
            }
        )
    for path in out.values():
        path.sort(key=lambda r: (r["hop"], r["t_ns"]))
    return out


def contiguous(path: list[dict]) -> bool:
    """True when the path's hop set is exactly 1..max (no gaps). A short
    path (coalesced into a newer generation mid-tree) is contiguous; a
    HOLE means a hop's telemetry was lost."""
    hops = sorted({r["hop"] for r in path})
    return bool(hops) and hops[0] == 1 and hops == list(range(1, hops[-1] + 1))


def path_stats(paths: dict) -> dict:
    """Aggregate verdict over reconstructed paths (the CHAOS_r09 gate
    reads ``contiguous_frac``)."""
    total = len(paths)
    ok = sum(1 for p in paths.values() if contiguous(p))
    max_hops = max((p[-1]["hop"] for p in paths.values() if p), default=0)
    return {
        "paths": total,
        "contiguous": ok,
        "contiguous_frac": (ok / total) if total else 1.0,
        "max_hops": max_hops,
    }


_TIER_TID = {"c": 1, "py": 2}


def chrome_trace(
    events: Iterable[ev.Event],
    flows: bool = True,
    offsets_ns: Optional[dict] = None,
) -> dict:
    """Chrome ``trace_event`` JSON document from a merged timeline.

    ``offsets_ns`` maps node obs id -> that node's clock offset from the
    root in ns (``off = C_node - C_root``, the r18 clock plane's sign
    convention); each event's ``ts`` becomes ``t_ns - off`` so every
    track shares the root's timebase. Unlisted nodes keep raw stamps.
    """
    offs = offsets_ns or {}

    def _ts(node: int, t_ns: int) -> float:
        return (t_ns - int(offs.get(node, 0))) / 1000.0

    events = sorted(events, key=lambda e: e.t_ns)
    out: list[dict] = []
    nodes = sorted({e.node for e in events})
    for n in nodes:
        out.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": n,
                "args": {"name": f"node-{n}" if n else "process"},
            }
        )
        for tier, tid in _TIER_TID.items():
            out.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": n,
                    "tid": tid,
                    "args": {"name": f"{tier}-tier"},
                }
            )
    for e in events:
        args: dict = {"link": e.link, "arg": e.arg}
        tr = unpack_trace(e)
        if tr is not None:
            args.update(origin=tr[0], gen=tr[1], hop=tr[2])
        if e.detail:
            args["detail"] = e.detail
        out.append(
            {
                "name": e.name,
                "cat": "st",
                "ph": "i",
                "s": "t",  # thread-scoped instant
                "ts": _ts(e.node, e.t_ns),
                "pid": e.node,
                "tid": _TIER_TID.get(e.tier, 3),
                "args": args,
            }
        )
    if flows:
        # one flow per multi-hop generation: arrows from each hop's track
        # to the next — the cross-node causal chain made visual
        for flow_id, ((origin, gen), path) in enumerate(
            sorted(trace_paths(events).items()), start=1
        ):
            if len(path) < 2:
                continue
            for i, rec in enumerate(path):
                out.append(
                    {
                        "name": f"update-{origin}-{gen}",
                        "cat": "st_trace",
                        "ph": "s" if i == 0 else "t",
                        "id": flow_id,
                        "ts": _ts(rec["node"], rec["t_ns"]),
                        "pid": rec["node"],
                        "tid": _TIER_TID.get(rec["tier"], 3),
                        "args": {"hop": rec["hop"], "origin": origin},
                    }
                )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def export_file(
    path: str,
    events: Iterable[ev.Event],
    flows: bool = True,
    offsets_ns: Optional[dict] = None,
) -> str:
    doc = chrome_trace(events, flows=flows, offsets_ns=offsets_ns)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return path
