"""Metrics registry: counters, gauges, fixed-bucket histograms (r08).

One registry unifies the four ad-hoc metric surfaces that accreted through
r06/r07 — ``st_engine_counters`` (12-wide ABI), ``st_node_pool_stats``,
``peer.metrics()`` and ``utils/profiling.RateMeter`` — under the canonical
naming schema in :mod:`~shared_tensor_tpu.obs.schema`. Three instrument
kinds (the Podracer/TF lesson: low-overhead first-class telemetry wired
through every layer, arXiv:2104.06272 §4 / arXiv:1605.08695 §9):

- :class:`Counter` — monotone cumulative count (``*_total`` names);
- :class:`Gauge` — point-in-time level (queue depth, residual RMS);
- :class:`Histogram` — fixed upper-bound buckets, cumulative counts +
  sum/count (Prometheus histogram semantics). Fixed buckets keep
  ``observe()`` to one lock + one linear scan over ~14 bounds — cheap
  enough for the Python tier's per-message path (the native tier never
  calls into Python at all; its aggregates ride the counters ABI).

Collectors bridge the pull side: a registered zero-arg callable returning
``{canonical_name: value}`` is invoked at snapshot time, so counters that
already live elsewhere (engine atomics, transport pool stats) are sampled
once per scrape instead of being double-maintained.

Exports: :meth:`Registry.snapshot` (plain dict, JSON-safe),
:meth:`Registry.prometheus_text` (text exposition format v0.0.4), and a
background JSONL sink thread (:meth:`Registry.start_jsonl_sink`) appending
one ``{"t_ns": ..., "metrics": {...}}`` line per interval.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Optional, Sequence

#: Default histogram bounds (seconds): wire/codec latencies span ~10 us
#: (engine-tier ACK turnarounds) to seconds (retransmission timers), log-ish
#: spaced so each bucket is meaningful at some table size.
LATENCY_BUCKETS = (
    1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0, 3.0, 10.0,
)


class Counter:
    """Monotone cumulative counter. ``inc`` only; never decreases (a reset
    — e.g. a re-created peer — is a NEW counter; RateMeter tolerates the
    discontinuity downstream)."""

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._v = 0.0
        self._mu = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError("counters only go up")
        with self._mu:
            self._v += n

    @property
    def value(self) -> float:
        with self._mu:
            return self._v


class Gauge:
    """Point-in-time level; set() or a pull callback (``fn``) — a callback
    gauge samples at snapshot time and ignores set()."""

    def __init__(
        self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None
    ):
        self.name = name
        self.help = help
        self._fn = fn
        self._v = 0.0
        self._mu = threading.Lock()

    def set(self, v: float) -> None:
        with self._mu:
            self._v = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            return float(self._fn())
        with self._mu:
            return self._v


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics): ``buckets`` are the
    finite upper bounds; counts are CUMULATIVE per bound, with an implicit
    +Inf bucket == total count. ``observe`` is one lock + a linear scan —
    fine for the Python tier's per-message cadence (the native data plane
    never routes through here)."""

    def __init__(
        self,
        name: str,
        buckets: Sequence[float] = LATENCY_BUCKETS,
        help: str = "",
    ):
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.name = name
        self.help = help
        self.bounds = tuple(b)
        self._counts = [0] * len(b)  # per-bound, NON-cumulative internally
        self._sum = 0.0
        self._count = 0
        self._mu = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._mu:
            self._sum += v
            self._count += 1
            for i, bound in enumerate(self.bounds):
                if v <= bound:
                    self._counts[i] += 1
                    break

    def snapshot(self) -> dict:
        """{"sum": s, "count": n, "buckets": {bound: cumulative_count}}."""
        with self._mu:
            out, cum = {}, 0
            for bound, c in zip(self.bounds, self._counts):
                cum += c
                out[bound] = cum
            return {"sum": self._sum, "count": self._count, "buckets": out}


class Registry:
    """A namespace of instruments + pull collectors, snapshot-able to a
    plain dict and renderable as Prometheus text exposition. Thread-safe:
    instrument creation takes the registry lock; the instruments themselves
    carry their own locks so the hot path never touches the registry's."""

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._collectors: list[Callable[[], dict]] = []
        self._sink_stop: Optional[threading.Event] = None
        self._sink_thread: Optional[threading.Thread] = None

    # -- instrument constructors (idempotent by name) -----------------------

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_make(name, lambda: Counter(name, help), Counter)

    def gauge(
        self, name: str, help: str = "", fn: Optional[Callable[[], float]] = None
    ) -> Gauge:
        return self._get_or_make(name, lambda: Gauge(name, help, fn), Gauge)

    def histogram(
        self,
        name: str,
        buckets: Sequence[float] = LATENCY_BUCKETS,
        help: str = "",
    ) -> Histogram:
        return self._get_or_make(
            name, lambda: Histogram(name, buckets, help), Histogram
        )

    def _get_or_make(self, name, make, want_type):
        with self._mu:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = make()
            elif not isinstance(m, want_type):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {want_type.__name__}"
                )
            return m

    def register_collector(self, fn: Callable[[], dict]) -> None:
        """``fn() -> {name: value}`` sampled at every snapshot — the bridge
        for counters that already live in C (engine/transport ABIs)."""
        with self._mu:
            self._collectors.append(fn)

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        """Flat JSON-safe dict: scalars for counters/gauges, the
        sum/count/buckets dict for histograms, collector outputs merged in
        (collectors never override a registered instrument's name)."""
        with self._mu:
            metrics = dict(self._metrics)
            collectors = list(self._collectors)
        out: dict = {}
        for fn in collectors:
            try:
                out.update(fn())
            except Exception:
                # a dying peer's collector (closed engine handle) must not
                # take the scrape down with it
                pass
        for name, m in metrics.items():
            if isinstance(m, Histogram):
                out[name] = m.snapshot()
            else:
                out[name] = m.value
        return out

    def prometheus_text(self) -> str:
        """Text exposition format v0.0.4 (one scrape body). Histogram
        buckets render with the standard ``_bucket{le=...}`` /
        ``_sum`` / ``_count`` series; collector scalars render as untyped
        samples. Dict-valued collector entries shaped like
        ``Histogram.snapshot()`` render as histograms too."""
        lines: list[str] = []

        def render_hist(name: str, snap: dict, help: str = "") -> None:
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} histogram")
            for bound, cum in snap["buckets"].items():
                lines.append(f'{name}_bucket{{le="{float(bound):g}"}} {cum}')
            lines.append(f'{name}_bucket{{le="+Inf"}} {snap["count"]}')
            lines.append(f"{name}_sum {snap['sum']:g}")
            lines.append(f"{name}_count {snap['count']}")

        with self._mu:
            metrics = dict(self._metrics)
            collectors = list(self._collectors)
        seen = set()
        for name, m in sorted(metrics.items()):
            seen.add(name)
            if isinstance(m, Histogram):
                render_hist(name, m.snapshot(), m.help)
            else:
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                kind = "counter" if isinstance(m, Counter) else "gauge"
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name} {m.value:g}")
        collected: dict = {}
        for fn in collectors:
            try:
                collected.update(fn())
            except Exception:
                pass
        for name, v in sorted(collected.items()):
            if name in seen:
                continue
            if isinstance(v, dict) and "buckets" in v:
                render_hist(name, v)
            else:
                lines.append(f"{name} {float(v):g}")
        return "\n".join(lines) + "\n"

    # -- background JSONL sink ----------------------------------------------

    def start_jsonl_sink(self, path: str, interval_sec: float = 5.0) -> None:
        """Append one ``{"t_ns": monotonic_ns, "metrics": snapshot()}`` line
        every ``interval_sec`` until :meth:`stop_jsonl_sink` (daemon thread;
        one final line is written at stop so short runs still record)."""
        self.stop_jsonl_sink()
        stop = threading.Event()

        def _run():
            while True:
                fired = stop.wait(interval_sec)
                try:
                    with open(path, "a") as f:
                        f.write(
                            json.dumps(
                                {
                                    "t_ns": time.monotonic_ns(),
                                    "metrics": self.snapshot(),
                                }
                            )
                            + "\n"
                        )
                except OSError:
                    pass  # sink target vanished; keep the process alive
                if fired:
                    return

        self._sink_stop = stop
        self._sink_thread = threading.Thread(
            target=_run, daemon=True, name="st-obs-jsonl"
        )
        self._sink_thread.start()

    def stop_jsonl_sink(self) -> None:
        if self._sink_stop is not None:
            self._sink_stop.set()
            if self._sink_thread is not None:
                self._sink_thread.join(timeout=5.0)
            self._sink_stop = None
            self._sink_thread = None
