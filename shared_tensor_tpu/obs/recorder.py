"""Flight recorder + process obs hub (r08 tentpole, part 3).

The chaos layer (comm/faults.py, r06) turned recovery claims into pass/fail
runs; this module turns a FAILED (or merely surprising) run into an
explainable trace: a bounded deque of the last N merged native+Python
events, dumped — together with per-name event totals and a snapshot of
every registered metrics registry — to a postmortem JSON file when
something terminal happens:

- a fault-plan crash point fires (the dump happens BEFORE ``os._exit``;
  native-tier crash points ``_exit(17)`` inside C and cannot dump — the
  partner peers' recorders are the evidence there);
- a peer's recv thread takes an unhandled exception (the wedged-peer
  failure class r06 hardened against — now it leaves a trace);
- a go-back-N black-hole teardown fires on either tier (the Python tier
  dumps directly; a native teardown is noticed as an EV blackhole event at
  drain time).

One hub per process: peers share the native ring (events carry per-node
obs ids), so a single merged timeline spans every peer in the process —
exactly what a multi-peer chaos test wants to read. Draining the native
ring happens on peers' recv loops (and on demand), never on a background
thread touching ctypes handles, so there is no drain-after-close race.
"""

from __future__ import annotations

import collections
import json
import os
import tempfile
import threading
import time
from typing import Iterable, Optional

from . import events as ev


class FlightRecorder:
    """Last-N merged event store + per-name totals. ``record`` is the only
    writer API; ``timeline`` returns a time-sorted copy (events arrive
    batched per tier, so insertion order is NOT global time order)."""

    def __init__(self, capacity: int = 4096):
        self._mu = threading.Lock()
        self._events: collections.deque[ev.Event] = collections.deque(
            maxlen=max(16, int(capacity))
        )
        #: name -> total ever recorded (NOT bounded by the deque): timeline
        #: accounting survives even when the window has rolled past an event
        self.counts: collections.Counter = collections.Counter()

    def record(self, batch: Iterable[ev.Event]) -> None:
        with self._mu:
            for e in batch:
                self._events.append(e)
                self.counts[e.name] += 1

    def set_capacity(self, capacity: int) -> None:
        """Re-bound the window, preserving the newest events. The r09
        cluster tests raise this before a chaos run so every trace_apply
        record survives until path reconstruction (the default window is
        sized for postmortems, not full-run captures)."""
        with self._mu:
            self._events = collections.deque(
                self._events, maxlen=max(16, int(capacity))
            )

    def timeline(self) -> list[ev.Event]:
        with self._mu:
            out = list(self._events)
        out.sort(key=lambda e: e.t_ns)
        return out

    def clear(self) -> None:
        with self._mu:
            self._events.clear()
            self.counts.clear()


class ObsHub:
    """Process-wide observability hub: the flight recorder, the Python-tier
    event entry point, the native-ring drain, and registered registries
    (snapshotted into postmortems). Use the module-level :func:`hub`."""

    def __init__(self, capacity: int = 4096):
        self.recorder = FlightRecorder(capacity)
        self._mu = threading.Lock()
        self._registries: dict[str, object] = {}  # label -> Registry
        self._last_drain = 0.0
        self._last_dump: dict[str, float] = {}  # reason -> monotonic time
        self.dump_paths: list[str] = []
        # r18 taps: callables fed every drained native batch (peers use
        # one to read engine-tier trace_apply origins without a second
        # drain of the ring — draining is destructive, so the recorder is
        # the single drain point and taps fan the batch out).
        self._taps: list = []

    # -- event ingestion ----------------------------------------------------

    def emit(
        self, name: str, node: int = 0, link: int = 0, arg: int = 0,
        detail: str = "", extra: int = 0,
    ) -> None:
        """Record one Python-tier event (no-op when obs is disabled — the
        callers gate on their own cached flag; this is the backstop)."""
        from . import obs_enabled

        if not obs_enabled():
            return
        self.recorder.record([ev.py_event(name, node, link, arg, detail, extra)])

    def poll_native(self, min_interval_sec: float = 0.0, lib=None) -> int:
        """Drain the native ring into the recorder (rate-limited when
        ``min_interval_sec`` > 0 — peers call this from their recv loops
        every pass). A drained black-hole teardown event triggers a
        postmortem dump, so a NATIVE go-back-N teardown leaves a trace even
        though the teardown itself ran in C. Returns events drained."""
        now = time.monotonic()
        with self._mu:
            if min_interval_sec > 0 and now - self._last_drain < min_interval_sec:
                return 0
            self._last_drain = now
        batch = ev.drain_native(lib=lib)
        if not batch:
            return 0
        self.recorder.record(batch)
        for tap in list(self._taps):
            try:
                tap(batch)
            except Exception:
                pass  # a broken tap must not stop the drain
        if any(e.name == "blackhole_teardown" for e in batch):
            self.dump("native_blackhole_teardown")
        return len(batch)

    def add_tap(self, fn) -> None:
        """Register a callable fed every drained native event batch."""
        with self._mu:
            if fn not in self._taps:
                self._taps.append(fn)

    def remove_tap(self, fn) -> None:
        with self._mu:
            try:
                self._taps.remove(fn)
            except ValueError:
                pass

    # -- registries ----------------------------------------------------------

    def register_registry(self, label: str, registry) -> None:
        with self._mu:
            self._registries[label] = registry

    def unregister_registry(self, label: str) -> None:
        with self._mu:
            self._registries.pop(label, None)

    # -- postmortem ----------------------------------------------------------

    def dump(
        self, reason: str, path: Optional[str] = None,
        min_interval_sec: float = 5.0,
    ) -> Optional[str]:
        """Write the postmortem file: merged timeline (time-sorted), event
        totals, native ring-drop count, and a snapshot of every registered
        registry. Per-reason rate limit (``min_interval_sec``) so a
        crash-looping recv thread cannot spray the disk. Returns the path,
        or None when rate-limited / obs disabled. Never raises: this runs
        on failure paths that must stay failure paths."""
        from . import obs_enabled

        if not obs_enabled():
            return None
        now = time.monotonic()
        with self._mu:
            if now - self._last_dump.get(reason, -1e9) < min_interval_sec:
                return None
            self._last_dump[reason] = now
            regs = dict(self._registries)
        try:
            doc = {
                "reason": reason,
                "pid": os.getpid(),
                "t_ns": time.monotonic_ns(),
                "native_events_dropped": ev.native_dropped(),
                "event_counts": dict(self.recorder.counts),
                "registries": {},
                "timeline": [e.as_dict() for e in self.recorder.timeline()],
            }
            for label, reg in regs.items():
                try:
                    doc["registries"][label] = reg.snapshot()
                except Exception:
                    doc["registries"][label] = None
            if path is None:
                base = os.environ.get(
                    "ST_OBS_POSTMORTEM_DIR", tempfile.gettempdir()
                )
                safe = "".join(
                    c if c.isalnum() or c in "-_." else "_" for c in reason
                )
                path = os.path.join(
                    base,
                    f"st_postmortem_{os.getpid()}_"
                    f"{time.monotonic_ns()}_{safe}.json",
                )
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
            with self._mu:
                self.dump_paths.append(path)
            return path
        except Exception:
            return None


    def export_timeline(self, path: str) -> str:
        """Write the recorder's merged timeline as conformance-replayable
        JSON: ``{"timeline": [...], "event_counts": {...},
        "native_events_dropped": N}`` — the shape
        tools/protospec/conformance.py (and its run_conformance.py CLI)
        accepts directly, and the shape the committed CHAOS_r* timeline
        fixtures pin. Unlike :meth:`dump` this is not a failure path:
        it raises on I/O errors so a truncated fixture can't pass for a
        captured one."""
        doc = {
            "timeline": [e.as_dict() for e in self.recorder.timeline()],
            "event_counts": dict(self.recorder.counts),
            "native_events_dropped": ev.native_dropped(),
        }
        with open(path, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        return path


_hub: Optional[ObsHub] = None
_hub_mu = threading.Lock()


def hub() -> ObsHub:
    """The process-wide hub (created on first use; capacity from
    ``ST_OBS_RECORDER_EVENTS``, default 4096)."""
    global _hub
    with _hub_mu:
        if _hub is None:
            cap = int(os.environ.get("ST_OBS_RECORDER_EVENTS", "4096"))
            _hub = ObsHub(cap)
        return _hub
