"""Cross-tier event model + the native event-ring drain (r08 tentpole).

The native trio (sttransport.cpp / stengine.cpp) records protocol events
into lock-free per-thread rings of 32-byte timestamped records; this module
drains them over the ``st_obs_drain`` ABI and decodes them into the same
:class:`Event` shape the Python tier emits directly — ONE timeline type
spanning both tiers.

Common clock: the native ring stamps CLOCK_MONOTONIC nanoseconds and
CPython's ``time.monotonic_ns()`` reads the same clock on Linux, so native
and Python timestamps merge by plain sort with no calibration pass
(``st_obs_now_ns`` is exported anyway so tests can prove the clocks agree).

Event codes are defined ONCE here and mirrored as constants in
sttransport.cpp (``kEv*``); the numeric values are ABI — changing one
requires changing both files.
"""

from __future__ import annotations

import dataclasses
import struct
import time
from typing import Optional

#: Native event record: u64 t_ns, u32 node_id, u32 code, i32 link,
#: u32 reserved, u64 arg — 32 bytes, matching sttransport.cpp's EventRec.
_EVENT_FMT = "<QIIiIQ"
EVENT_BYTES = struct.calcsize(_EVENT_FMT)
assert EVENT_BYTES == 32

#: code -> name. 1..4 are the transport's membership event kinds (same
#: numbers as transport.EventKind); 10..15 protocol/recovery events;
#: 20..26 fault-injection hits (mirroring comm/faults.py's classes).
CODE_NAMES: dict[int, str] = {
    1: "link_up",
    2: "link_down",
    3: "became_master",
    4: "isolated",
    10: "retransmit",
    11: "blackhole_teardown",
    12: "quarantine",
    13: "send_window_stall",
    14: "dedup_discard",
    15: "seal",
    20: "fault_drop",
    21: "fault_dup",
    22: "fault_corrupt",
    23: "fault_truncate",
    24: "fault_delay",
    25: "fault_stall",
    26: "fault_sever",
    27: "crash_point",
    # 30+: r09 cross-hop trace propagation. One trace_apply per accepted
    # traced DATA/BURST message: node/link say who applied it, ``arg``
    # carries the update generation (origin monotonic ns) and ``extra``
    # packs (origin_node << 8 | hop) — obs/trace_export.py reconstructs
    # full causal paths from these records.
    30: "trace_apply",
    # 31: r10 subscriber link attached in the native engine (unledgered,
    # possibly range-filtered; arg = subscribed word count). The python
    # tier emits the same name — plus "sub_resync" — directly.
    31: "sub_attach",
    # 32: r11 adaptive-precision governor flipped a link's wire precision
    # (arg = the new precision, 1 or 2). 33: one stripe socket of a
    # striped link died (arg = stripe index) and the link degraded to the
    # survivors — the LAST stripe's death shows up as link_down instead.
    32: "precision_shift",
    33: "stripe_down",
    # 34/35: r14 same-host shm lane. shm_lane_up fires once per link when
    # its data plane switches onto the shared-memory rings (arg = ring
    # bytes per direction); shm_fallback records a negotiated attach that
    # failed validation — the link stays on TCP (arg = reason: 1 segment
    # open failed, 2 map/size failed, 3 header/token mismatch).
    34: "shm_lane_up",
    35: "shm_fallback",
    # 36/37: r17 engine-tier shard plane. shard_park_drop is the native
    # twin of the python tier's event of the same name (a parked FWD
    # dropped at the ShardConfig.park_cap bound — loud bounded loss);
    # shard_dedup_discard records an end-to-end (origin, fwd_seq)
    # duplicate discarded at an engine-lane owner (arg = the fwd_seq) —
    # distinct from code 14's per-link dup/gap discards.
    36: "shard_park_drop",
    37: "shard_dedup_discard",
}
NAME_CODES = {v: k for k, v in CODE_NAMES.items()}

#: r12 cluster lifecycle events — PYTHON-tier only (the barrier protocol
#: lives in comm/peer.py; the native engine's part is just the pause flag,
#: which emits nothing). No native codes, so these are names rather than
#: ABI numbers: snap_begin (entered a barrier; arg = children awaited,
#: detail = op), snap_shard (shard captured; arg = link count), snap_done
#: (root finished; arg = shard count), lifecycle_pause/lifecycle_resume
#: (quiesce edges), drain_begin (routed drain accepted), ctl_cmd (operator
#: command received; detail = op).
LIFECYCLE_EVENT_NAMES = frozenset(
    {
        "snap_begin",
        "snap_shard",
        "snap_done",
        "lifecycle_pause",
        "lifecycle_resume",
        "drain_begin",
        "ctl_cmd",
    }
)

#: r18 fleet-health events (python tier only — the analyzer runs at the
#: root, never in the C hot path, so these are names rather than ABI
#: numbers; tools/lint_events.py pins the set). slo_alert_fire /
#: slo_alert_clear carry the severity index in arg and the burn-rate
#: numbers in detail; hot_shard carries the named shard id in arg.
HEALTH_EVENT_NAMES = frozenset(
    {
        "slo_alert_fire",
        "slo_alert_clear",
        "hot_shard",
    }
)

#: r19 elastic-resharding events (python tier, name-only — reserved by
#: the protospec reshard models BEFORE the implementation lands, so the
#: r20 implementation emits against conformance acceptors that already
#: exist; tools/lint_events.py pins the set). *_begin/*_done bracket one
#: staged transfer on the owning node (arg = shard / epoch);
#: reshard_grant carries the minted epoch in arg with node = the minter
#: (tools/protospec/spec_reshard.py's MasterAuthorityAcceptor checks the
#: epochs mint monotonically and only from the current authority).
RESHARD_EVENT_NAMES = frozenset(
    {
        "reshard_split_begin",
        "reshard_split_done",
        "reshard_merge_begin",
        "reshard_merge_done",
        "reshard_master_begin",
        "reshard_master_done",
        "reshard_grant",
    }
)

#: Names the flight recorder treats as fault-injection hits (timeline
#: accounting in the chaos soak keys on these).
FAULT_EVENT_NAMES = frozenset(
    n for c, n in CODE_NAMES.items() if 20 <= c <= 26
)


@dataclasses.dataclass(frozen=True)
class Event:
    """One timeline entry. ``tier`` is "c" (drained from the native ring)
    or "py" (emitted by the Python tier); ``node`` is the transport node's
    process-unique obs id (0 = not node-scoped); ``arg`` is the event's
    numeric payload (is_uplink for membership, message count for
    retransmit, wire seq for dedup_discard, origin ns for trace_apply,
    ...); ``extra`` is the record's fourth word (u32 on the native ABI —
    r09 packs origin<<8|hop there for trace_apply)."""

    t_ns: int
    tier: str
    name: str
    node: int = 0
    link: int = 0
    arg: int = 0
    detail: str = ""
    extra: int = 0

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if not d["detail"]:
            del d["detail"]
        if not d["extra"]:
            del d["extra"]
        return d


def py_event(
    name: str, node: int = 0, link: int = 0, arg: int = 0, detail: str = "",
    extra: int = 0,
) -> Event:
    return Event(time.monotonic_ns(), "py", name, node, link, arg, detail, extra)


def _lib():
    """The transport .so (which owns the process-wide ring); built/loaded
    lazily so importing obs never forces a native build."""
    from ..comm import transport

    return transport._load()


def drain_native(cap_events: int = 8192, lib=None) -> list[Event]:
    """Drain up to ``cap_events`` native events (all threads' rings).
    Leftovers stay ring-buffered for the next drain. Returns [] when the
    native library is unavailable (pure-Python environments)."""
    try:
        lib = lib if lib is not None else _lib()
    except Exception:
        return []
    import ctypes

    buf = bytearray(cap_events * EVENT_BYTES)
    n = lib.st_obs_drain(
        (ctypes.c_char * len(buf)).from_buffer(buf), len(buf)
    )
    out: list[Event] = []
    for off in range(0, int(n), EVENT_BYTES):
        t_ns, node, code, link, res, arg = struct.unpack_from(
            _EVENT_FMT, buf, off
        )
        out.append(
            Event(
                t_ns,
                "c",
                CODE_NAMES.get(code, f"code_{code}"),
                node,
                link,
                arg,
                extra=res,
            )
        )
    return out


def native_now_ns(lib=None) -> Optional[int]:
    """The native ring's clock, for clock-agreement checks; None when the
    native library is unavailable."""
    try:
        lib = lib if lib is not None else _lib()
    except Exception:
        return None
    return int(lib.st_obs_now_ns())


def native_dropped(lib=None) -> int:
    """Events lost to ring overflow since process start (accounting stays
    honest: a timeline with drops says so)."""
    try:
        lib = lib if lib is not None else _lib()
    except Exception:
        return 0
    return int(lib.st_obs_dropped())
