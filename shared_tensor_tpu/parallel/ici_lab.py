"""Experimental pod-tier sync steps for the codec-lab methods.

The codec lab (ops/codec_lab.py, host trajectories; ops/codec_lab_jax.py,
jitted single-buffer twins) measured where the alternative compression
methods win. This module takes the measured-best 2-bit design — Sign2:
``±s`` / ``±3s``, magnitude bit at ``|r| > 2s`` — into the REAL pod sync
path: the same GSPMD shard_map step as the production
parallel/ici.build_sync_step (same per-leaf cross-shard scale reduction,
same all-gather-over-ICI shape, same split horizon and SAT clamps), with a
2-bit wire (two packed planes: sign bits + magnitude bits = 2 bits/element
per peer over ICI, vs the production step's 1).

Deliberately a SEPARATE builder, not a flag on the production one: the
1-bit step is the reference-parity capability and stays byte-stable; this
is the lab's device-tier test bed, sharing ici.py's internals so the only
delta is the quantizer (Pareto differences stay attributable — the same
discipline as the host lab). Promotion path if a workload earns it:
ops/table.py dispatch + a wire frame tag, exactly like the host lab
documents.

Measured on the 8-virtual-device test mesh (tests/test_ici_lab.py): on
gaussian residuals the sign2 step drains RMS faster per frame than the
production step at every frame count checked, matching the host lab's
0.79-vs-0.85 per-frame decay; on uniform residuals the magnitude bit idles
and both steps drain identically (exact zero in ~28 frames); and the
flagship char-rnn TRAINS through the 2-bit sync to statistically
comparable loss on the same pinned data stream (the training-level A/B,
mirroring the overlap A/B in tests/test_trainer.py).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .ici import shard_map  # version-shimmed (jax 0.4.x..0.7)

from ..config import MeshConfig, ScalePolicy
from ..ops.codec import SAT
from ..ops.packing import LANES, pack_bits, unpack_bits
from ..ops.table import TableSpec
from .ici import PeerSyncState, _leaf_scales, _make_ctx


def build_sign2_sync_step(
    mesh: Mesh,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    config: MeshConfig | None = None,
    jit_compile: bool = True,
):
    """Compile one fused 2-bit pod sync step: ``state -> (state', scales)``.

    Contract mirrors ici.build_sync_step (same state layout, same scales
    observability output); only the quantizer differs. XLA tier only — the
    fused Pallas row kernels are pinned to the production 1-bit layout, and
    the lab's job is semantics + convergence measurement, not peak HBM
    throughput.
    """
    cfg = config or MeshConfig()
    ctx = _make_ctx(mesh, spec, per_leaf, cfg)
    peer_ax = ctx.peer_ax

    def _body(values, residual):
        r = residual.reshape(ctx.rows_local, LANES)
        row_leaf, rowcount, live = ctx.local_slices()
        scales = _leaf_scales(
            r, row_leaf, live, ctx.ns, ctx.k, policy, ctx.shard_ax
        )
        s_row = scales[row_leaf][:, None]  # (rows, 1)
        # 2-bit sign-magnitude quantize + error feedback (the codec-lab
        # Sign2 rule; sign convention matches the production codec: r <= 0
        # sends negative, quirk Q3's zero-negative kept)
        neg = r <= 0.0
        big = jnp.abs(r) > 2.0 * s_row
        mag = jnp.where(big, 3.0 * s_row, s_row)
        sent = jnp.where(neg, -mag, mag)
        r2 = jnp.where(
            live & (s_row > 0), r - sent, jnp.where(live, r, 0.0)
        ).reshape(-1)
        sign_words = pack_bits(jnp.logical_and(live, neg).reshape(-1))
        mag_words = pack_bits(jnp.logical_and(live, big).reshape(-1))
        # 2 bits/element over ICI: both planes ride one all-gather
        words = jnp.stack([sign_words, mag_words])  # (2, W_local)
        words_all = jax.lax.all_gather(words, peer_ax)  # (n_peer, 2, W)
        scales_all = jax.lax.all_gather(scales, peer_ax)  # (n_peer, k)

        # receiver half: sum of every OTHER peer's 2-bit frame, one pass
        me = jax.lax.axis_index(peer_ax)
        s_all = scales_all[:, row_leaf]  # (n_peer, rows_local)
        s_all = jnp.where((jnp.arange(ctx.n_peer) == me)[:, None], 0.0, s_all)
        neg_all = (
            unpack_bits(words_all[:, 0])
            .reshape(ctx.n_peer, ctx.rows_local, LANES)
            .astype(jnp.float32)
        )
        big_all = (
            unpack_bits(words_all[:, 1])
            .reshape(ctx.n_peer, ctx.rows_local, LANES)
            .astype(jnp.float32)
        )
        delta = jnp.sum(
            s_all[:, :, None] * (1.0 - 2.0 * neg_all) * (1.0 + 2.0 * big_all),
            axis=0,
        )
        v = values.reshape(ctx.rows_local, LANES)
        v2 = jnp.where(live, jnp.clip(v + delta, -SAT, SAT), 0.0)
        return v2.reshape(-1), r2, scales

    def _step(values, residual):
        v2, r2, scales = _body(values[0], residual[0])
        return v2[None], r2[None], scales[None]

    spec_vr = P(peer_ax, ctx.shard_ax)
    sharded = shard_map(
        _step,
        mesh=mesh,
        in_specs=(spec_vr, spec_vr),
        out_specs=(spec_vr, spec_vr, P(peer_ax, None)),
    )

    def sync_step(state: PeerSyncState) -> Tuple[PeerSyncState, jax.Array]:
        v, r, scales = sharded(state.values, state.residual)
        return PeerSyncState(v, r), scales

    if jit_compile:
        # NO buffer donation, deliberately (production donates): with many
        # live executables in one process (a full pytest run), donated
        # shard_map buffers on the virtual CPU mesh intermittently abort
        # the XLA CPU runtime (SIGABRT reproduced at suite position #132,
        # gone without donation). The lab step measures semantics, not
        # allocator throughput — correctness over the copy.
        return jax.jit(sync_step)
    return sync_step
