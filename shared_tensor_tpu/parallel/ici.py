"""The pod tier: async compressed peer sync over ICI collectives.

This is the BASELINE.json north star — "the TCP tree-topology peer sync behind
addFromTensor/copyToTensor is replaced by ICI reduce-scatter + all-gather over
the pod mesh, preserving the async eventually-consistent update semantics".

Topology re-design (TPU-first, not a port): the reference connects peers in a
binary tree because TCP links are point-to-point and flooding with per-hop
re-quantization is how a tree broadcasts (reference src/sharedtensor.c:124-127;
SURVEY.md §2.3). A TPU pod's ICI is an all-to-all fabric with hardware
collectives, so the tree disappears: every device on the ``peer`` mesh axis is
a peer holding its own replica, and one sync step is

  1. quantize the local residual (1-bit sign + per-leaf pow2-RMS scale, error
     feedback — the exact reference codec, ops/table.py semantics);
  2. ``all_gather`` the *packed sign words + scales* over the peer axis —
     1 bit/element on the wire, 32x less ICI traffic than an fp32 ``psum``;
  3. apply the sum of every *other* peer's reconstructed delta to the local
     replica (split horizon, reference sync_in src/sharedtensor.c:119-129).

Because the graph is fully connected, the reference's flood-and-requantize
(each hop re-quantizes, degrading the signal down the tree) is unnecessary:
every peer receives every other peer's frame first-hand, at one quantization.
Semantics preserved: updates merge additively, replicas are eventually
consistent with bounded +/-scale overshoot, and compute never has to wait — a
step syncs whatever residual mass exists and converged peers idle at scale 0.

The ``shard`` mesh axis additionally shards the flat table buffer, so the
replica is a pod-resident sharded jax.Array: per-leaf scale reductions psum
over the shard axis and the peer all-gather moves only local shards. Tables
beyond one device's HBM (the reference crashes at ~60 Mi elements, quirk Q6)
sync at ICI speed.

The exact arm (``compressed=False``) delivers every peer's pending residual
exactly via fp32 ``psum`` — the "exact allreduce" comparison required by
BASELINE config 4.

Everything here is functional and jitted; one fused step does codec + exchange
+ apply with no host round-trips.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:  # jax >= 0.6: top-level shard_map with the check_vma kwarg
    from jax import shard_map
except ImportError:  # jax 0.4.x: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_legacy(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..config import MeshConfig, ScalePolicy
from ..ops.codec import SAT, pow2_floor
from ..ops.packing import BITS_PER_WORD, LANES, pack_bits, unpack_bits
from ..ops.table import TableSpec, flatten, unflatten
from .mesh import rows_per_shard


class PeerSyncState(NamedTuple):
    """Per-peer replicas + residuals, sharded over the (peer, shard) mesh.

    ``values[p]`` is peer p's full replica of the flat padded table (the
    reference's ``values[]``, src/sharedtensor.c:34); ``residual[p]`` is its
    one outgoing residual toward the group (the reference's per-link
    ``delta[]``, one per tree link — fully connected needs only one)."""

    values: jax.Array  # f32[n_peer, spec.total]
    residual: jax.Array  # f32[n_peer, spec.total]


def state_sharding(mesh: Mesh, config: MeshConfig | None = None) -> NamedSharding:
    cfg = config or MeshConfig()
    return NamedSharding(mesh, P(cfg.peer_axis, cfg.shard_axis))


def init_state(
    mesh: Mesh,
    spec: TableSpec,
    template=None,
    config: MeshConfig | None = None,
) -> PeerSyncState:
    """All peers start from the same seed (``template``, or zeros). The
    reference instead has one master seed its state and stream it to joiners
    (src/sharedtensor.c:379-381); in-pod peers are born simultaneously so the
    seed is just replicated — the streaming join path lives in the DCN tier
    (comm/peer.py)."""
    sh = state_sharding(mesh, config)
    n_peer = mesh.shape[sh.spec[0]]
    rows_per_shard(spec.total, mesh.shape[sh.spec[1]])  # validate divisibility
    if template is not None:
        flat = flatten(template, spec)
    else:
        flat = jnp.zeros((spec.total,), jnp.float32)
    values = jax.device_put(jnp.broadcast_to(flat, (n_peer, spec.total)), sh)
    residual = jax.device_put(jnp.zeros((n_peer, spec.total), jnp.float32), sh)
    return PeerSyncState(values, residual)


def read_peer(state: PeerSyncState, spec: TableSpec, peer: int):
    """Peer ``peer``'s current replica as the caller's pytree (reference
    copyToTensor)."""
    return unflatten(state.values[peer], spec)


def add_updates_raw(state: PeerSyncState, updates: jax.Array) -> PeerSyncState:
    """Each peer merges its own additive update (``updates[p]`` for peer p):
    replica and residual both receive it, so it is visible locally at once and
    queued for the group (reference addFromInternal, src/sharedtensor.c:
    334-344). Sanitized like ops.table.accumulate_table (quirk Q9 fix).

    Un-jitted so callers (train/async_sgd.py) can fuse it into a larger
    step; use :func:`add_updates` standalone."""
    u = jnp.nan_to_num(updates.astype(jnp.float32), nan=0.0, posinf=3.0e38, neginf=-3.0e38)
    return PeerSyncState(
        jnp.clip(state.values + u, -3.0e38, 3.0e38),
        jnp.clip(state.residual + u, -3.0e38, 3.0e38),
    )


add_updates = jax.jit(add_updates_raw, donate_argnums=(0,))


@partial(jax.jit, donate_argnums=(0,))
def apply_external(state: PeerSyncState, delta: jax.Array) -> PeerSyncState:
    """Apply a delta that arrived from OUTSIDE the pod (the DCN/TCP peer
    tier) to every pod peer's replica — values only, residuals untouched.

    This is split-horizon at the pod boundary (reference sync_in never
    re-floods a frame back toward the link it came from,
    src/sharedtensor.c:124-127): every pod peer receives the external delta
    directly here, so queueing it into intra-pod residuals would deliver it
    twice. ``delta`` is flat [spec.total], broadcast over peers."""
    d = jnp.nan_to_num(
        delta.astype(jnp.float32), nan=0.0, posinf=3.0e38, neginf=-3.0e38
    )
    return PeerSyncState(
        jnp.clip(state.values + d[None, :], -3.0e38, 3.0e38), state.residual
    )


# --- the fused sync step ----------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _StepCtx:
    """Static layout shared by the sync-step builders: mesh axes, per-shard
    row geometry, and the leaf segmentation the scale reductions run over."""

    peer_ax: str
    shard_ax: str
    n_peer: int
    n_shard: int
    rows_local: int
    k: int
    row_leaf_full: jnp.ndarray
    rowcount_full: jnp.ndarray
    ns: jnp.ndarray

    def local_slices(self):
        """This shard's (row_leaf, rowcount, live) views. Call inside
        shard_map only (uses axis_index)."""
        sid = jax.lax.axis_index(self.shard_ax)
        start = sid * self.rows_local
        row_leaf = jax.lax.dynamic_slice_in_dim(
            self.row_leaf_full, start, self.rows_local
        )
        rowcount = jax.lax.dynamic_slice_in_dim(
            self.rowcount_full, start, self.rows_local
        )
        lane = jax.lax.broadcasted_iota(jnp.int32, (self.rows_local, LANES), 1)
        live = lane < rowcount[:, None]
        return row_leaf, rowcount, live


def _make_ctx(
    mesh: Mesh, spec: TableSpec, per_leaf: bool, cfg: MeshConfig
) -> _StepCtx:
    peer_ax, shard_ax = cfg.peer_axis, cfg.shard_axis
    n_shard = mesh.shape[shard_ax]
    if per_leaf:
        k = spec.num_leaves
        row_leaf_full = jnp.asarray(spec.row_leaf())
        ns = jnp.asarray(np.asarray(spec.ns, dtype=np.float32))
    else:
        # one global scale over the whole table (the reference's exact
        # behavior, src/sharedtensor.c:153-159) — a single segment
        k = 1
        row_leaf_full = jnp.zeros((spec.total // LANES,), jnp.int32)
        ns = jnp.asarray([float(spec.total_n)], jnp.float32)
    return _StepCtx(
        peer_ax=peer_ax,
        shard_ax=shard_ax,
        n_peer=mesh.shape[peer_ax],
        n_shard=n_shard,
        rows_local=rows_per_shard(spec.total, n_shard),
        k=k,
        row_leaf_full=row_leaf_full,
        rowcount_full=jnp.asarray(spec.live_rowcount()),
        ns=ns,
    )


def _leaf_scales(
    rows: jnp.ndarray,
    row_leaf: jnp.ndarray,
    live: jnp.ndarray,
    ns: jnp.ndarray,
    k: int,
    policy: ScalePolicy,
    shard_axis: Optional[str],
) -> jnp.ndarray:
    """Per-leaf scales from this shard's rows, reduced over the shard axis.

    Same overflow-safe normalized-RMS math as ops.table.compute_scales, with
    the segment reductions split into a local partial + a cross-shard
    psum/pmax (this is where the sharded replica pays one small collective —
    k floats — per frame)."""
    amax_row = jnp.max(jnp.where(live, jnp.abs(rows), 0.0), axis=1)
    amax = jax.ops.segment_max(amax_row, row_leaf, num_segments=k)
    amax = jnp.maximum(amax, 0.0)  # segment_max identity is -inf
    if shard_axis is not None:
        amax = jax.lax.pmax(amax, shard_axis)
    denom = jnp.where(amax > 0, amax, 1.0)
    norm = jnp.where(live, rows / denom[row_leaf][:, None], 0.0)
    if policy == ScalePolicy.ABS_MEAN:
        part = jax.ops.segment_sum(
            jnp.sum(jnp.abs(norm), axis=1, dtype=jnp.float32),
            row_leaf,
            num_segments=k,
        )
        if shard_axis is not None:
            part = jax.lax.psum(part, shard_axis)
        scales = amax * (part / ns)
    else:
        part = jax.ops.segment_sum(
            jnp.sum(norm * norm, axis=1, dtype=jnp.float32),
            row_leaf,
            num_segments=k,
        )
        if shard_axis is not None:
            part = jax.lax.psum(part, shard_axis)
        rms = amax * jnp.sqrt(part / ns)
        scales = pow2_floor(rms) if policy == ScalePolicy.POW2_RMS else rms
    return jnp.where((amax > 0) & jnp.isfinite(scales), scales, 0.0)


def _codec_send(ctx: _StepCtx, policy: ScalePolicy, pallas_tier: bool, residual):
    """Sender half of the pod sync, per shard block: per-leaf scales
    (cross-shard reduction) + sign-quantize/pack/error-feedback + all-gather
    of the packed frames over the peer axis — the wire is 1 bit/element +
    k scales per peer over ICI. One source of truth for both the fused step
    (build_sync_step) and the overlap phases (build_sync_phases).

    On TPU the quantize pass runs as the fused Pallas row kernel
    (ops/codec_pallas.quantize_rows) — one HBM pass instead of XLA's
    multi-pass pack lowering (measured in round 2: the XLA tail cost 49.8%
    of a training step on chip).

    Returns (new_residual [flat], words_all [n_peer, W_local],
    scales_all [n_peer, k], scales_local [k])."""
    r = residual.reshape(ctx.rows_local, LANES)
    row_leaf, rowcount, live = ctx.local_slices()
    scales = _leaf_scales(r, row_leaf, live, ctx.ns, ctx.k, policy, ctx.shard_ax)
    if pallas_tier:
        from ..ops import codec_pallas

        words, r2 = codec_pallas.quantize_rows(scales[row_leaf], rowcount, residual)
    else:
        s_row = scales[row_leaf][:, None]  # (rows, 1)
        # sign-quantize + error feedback (reference :166-174)
        neg = r <= 0.0
        bits = jnp.logical_and(live, neg)
        sent = jnp.where(neg, -s_row, s_row)
        r2 = jnp.where(
            live & (s_row > 0), r - sent, jnp.where(live, r, 0.0)
        ).reshape(-1)
        words = pack_bits(bits.reshape(-1))
    words_all = jax.lax.all_gather(words, ctx.peer_ax)  # (n_peer, W_local)
    scales_all = jax.lax.all_gather(scales, ctx.peer_ax)  # (n_peer, k)
    return r2, words_all, scales_all, scales


def _codec_apply(ctx: _StepCtx, pallas_tier: bool, values, words_all, scales_all):
    """Receiver half, per shard block: apply the sum of every OTHER peer's
    frame (split horizon = zero out OUR column of the per-frame scales; a
    zero-scale frame contributes exactly nothing) to the local replica, in
    one pass (fused Pallas on TPU). Result clamped to +/-codec.SAT like
    every state-mutating path. Shared by build_sync_step and
    build_sync_phases."""
    row_leaf, rowcount, live = ctx.local_slices()
    me = jax.lax.axis_index(ctx.peer_ax)
    s_all = scales_all[:, row_leaf]  # (n_peer, rows_local)
    s_all = jnp.where((jnp.arange(ctx.n_peer) == me)[:, None], 0.0, s_all)
    if pallas_tier:
        from ..ops import codec_pallas

        words2d = (
            words_all.reshape(ctx.n_peer, ctx.rows_local, LANES // 32)
            .transpose(1, 0, 2)
            .reshape(ctx.rows_local, ctx.n_peer * (LANES // 32))
        )
        (v2,) = codec_pallas.apply_rows_batch(
            s_all.T, rowcount, words2d, (values,)
        )
        return v2
    v = values.reshape(ctx.rows_local, LANES)
    bits_all = (
        unpack_bits(words_all)
        .reshape(ctx.n_peer, ctx.rows_local, LANES)
        .astype(jnp.float32)
    )
    # elementwise+sum (VPU): s is a power of 2 and bits are 0/1, but under
    # RMS policy s is arbitrary — keep the arithmetic exact f32, no MXU
    delta = jnp.sum(s_all[:, :, None] * (1.0 - 2.0 * bits_all), axis=0)
    v2 = jnp.where(live, jnp.clip(v + delta, -SAT, SAT), 0.0)
    return v2.reshape(-1)


def build_sync_step(
    mesh: Mesh,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    compressed: bool = True,
    config: MeshConfig | None = None,
    jit_compile: bool = True,
    impl: str = "auto",
):
    """Compile one fused pod sync step: ``state -> (state', scales)``.

    ``scales`` is f32[n_peer, num_leaves] — the per-frame step sizes each peer
    transmitted (0 rows = idle peers), the core observability quantity the
    reference lacks entirely (SURVEY.md §5.5).

    ``compressed=False`` builds the exact-allreduce arm instead (BASELINE
    config 4's comparison): every pending residual is delivered in full fp32
    precision and residuals drop to exactly zero.

    ``impl`` selects the codec tier around the all-gather: "auto" runs the
    fused Pallas row kernels exactly when they compile (TPU) and pure XLA
    elsewhere; "pallas"/"xla" pin a tier (parity tests).
    """
    cfg = config or MeshConfig()
    ctx = _make_ctx(mesh, spec, per_leaf, cfg)
    peer_ax, shard_ax = ctx.peer_ax, ctx.shard_ax

    pallas_tier = False
    if compressed:
        from ..ops.table import _resolve_impl

        pallas_tier = _resolve_impl(impl) == "pallas"

    def _compressed_body(values, residual):
        """Compose the shared codec halves (same blocks as
        build_sync_phases — the compose-parity test pins the equivalence)."""
        r2, words_all, scales_all, scales = _codec_send(
            ctx, policy, pallas_tier, residual
        )
        v2 = _codec_apply(ctx, pallas_tier, values, words_all, scales_all)
        return v2, r2, scales

    def _exact(values, residual):
        r = residual.reshape(ctx.rows_local, LANES)
        row_leaf, rowcount, live = ctx.local_slices()
        # report the would-have-been scales so both arms expose the same
        # observability surface (the shard-axis reduction inside also lets
        # shard_map infer the scales output is shard-replicated)
        scales = _leaf_scales(r, row_leaf, live, ctx.ns, ctx.k, policy, shard_ax)
        delta_others = jax.lax.psum(residual, peer_ax) - residual
        v2 = jnp.clip(values + delta_others, -SAT, SAT)
        v2 = jnp.where(live.reshape(-1), v2, 0.0)
        return v2, jnp.zeros_like(residual), scales

    body = _compressed_body if compressed else _exact

    def _step(values, residual):
        # local blocks: (1, spec.total // n_shard)
        v2, r2, scales = body(values[0], residual[0])
        return v2[None], r2[None], scales[None]

    spec_vr = P(peer_ax, shard_ax)
    sharded = shard_map(
        _step,
        mesh=mesh,
        in_specs=(spec_vr, spec_vr),
        out_specs=(spec_vr, spec_vr, P(peer_ax, None)),
        # pallas_call outputs carry no varying-mesh-axes annotation; disable
        # the vma checker for the kernel body (the XLA body keeps it)
        check_vma=not pallas_tier,
    )

    def sync_step(state: PeerSyncState) -> Tuple[PeerSyncState, jax.Array]:
        v, r, scales = sharded(state.values, state.residual)
        return PeerSyncState(v, r), scales

    if jit_compile:
        return jax.jit(sync_step, donate_argnums=(0,))
    # Raw (traceable) form for embedding into a larger jitted step
    # (train/async_sgd.py fuses grads + add_updates + sync into one program).
    return sync_step


def build_sync_phases(
    mesh: Mesh,
    spec: TableSpec,
    policy: ScalePolicy = ScalePolicy.POW2_RMS,
    per_leaf: bool = True,
    config: MeshConfig | None = None,
    impl: str = "auto",
):
    """The sync step split into its two halves, for the OVERLAP training mode
    (train/async_sgd.py ``overlap=True``):

      ``send(residual) -> (residual', words_all, scales_all)`` — quantize the
      outgoing residual (error feedback applied) and all-gather the packed
      frames over the peer axis. Depends ONLY on the residual.

      ``apply_gathered(values, words_all, scales_all) -> values'`` — apply
      every OTHER peer's frame (split horizon) to the local replica.

    Running ``send`` at the top of a fused train step and ``apply_gathered``
    after the backward pass gives XLA's latency-hiding scheduler a window the
    full width of the grad computation to run the all-gather in — the
    collective rides ICI while the MXU does the backward pass. This realizes
    the reference's core property, compute never waits for sync
    (README.md:24 "fully asynchronous"; SURVEY.md §7.4 hard part 1), at the
    cost that the local update added AFTER ``send`` rides the NEXT frame
    (one-step-later delivery — indistinguishable under the reference's
    always-streaming semantics, where a frame carries whatever residual mass
    exists at frame time).

    Composing ``apply_gathered(values, *send(residual)[1:])`` immediately is
    bit-for-bit ``build_sync_step`` (tests pin this).

    Shapes: ``words_all`` u32[n_peer, total//32] sharded over the shard axis;
    ``scales_all`` f32[n_peer, num_leaves] replicated (row p = the scales
    peer p transmitted — the same observability surface as build_sync_step).
    """
    from ..ops.table import _resolve_impl

    cfg = config or MeshConfig()
    ctx = _make_ctx(mesh, spec, per_leaf, cfg)
    pallas_tier = _resolve_impl(impl) == "pallas"
    spec_vr = P(ctx.peer_ax, ctx.shard_ax)

    def _send(residual_blk):
        r2, words_all, scales_all, _ = _codec_send(
            ctx, policy, pallas_tier, residual_blk[0]
        )
        return r2[None], words_all, scales_all

    # check_vma off: the gathered outputs ARE peer-replicated (all_gather
    # over the peer axis returns identical stacks everywhere) but the
    # varying-mesh-axes inference cannot see that through a collective's
    # output; correctness is pinned by the compose-parity test against the
    # fused (vma-checked) step instead.
    send = shard_map(
        _send,
        mesh=mesh,
        in_specs=(spec_vr,),
        out_specs=(spec_vr, P(None, ctx.shard_ax), P(None, None)),
        check_vma=False,
    )

    def _apply(values_blk, words_all, scales_all):
        v2 = _codec_apply(ctx, pallas_tier, values_blk[0], words_all, scales_all)
        return v2[None]

    apply_gathered = shard_map(
        _apply,
        mesh=mesh,
        in_specs=(spec_vr, P(None, ctx.shard_ax), P(None, None)),
        out_specs=spec_vr,
        check_vma=False,
    )
    return send, apply_gathered


def frame_ici_bytes(spec: TableSpec, n_peer: int, compressed: bool = True) -> int:
    """Bytes received per peer per sync step over ICI — the wire-cost model
    behind the >=10x-at-matched-error target (BASELINE.md). Compressed: 1
    bit/element + scales from each other peer; exact: fp32 psum moves ~2x the
    full buffer through each link for large rings."""
    if compressed:
        per_frame = spec.total // BITS_PER_WORD * 4 + spec.num_leaves * 4
        return (n_peer - 1) * per_frame
    return 2 * spec.total * 4
