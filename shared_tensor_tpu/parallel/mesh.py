"""Device-mesh construction for the pod tier.

The reference's scaling axis is peer count over a TCP tree (SURVEY.md §2.3);
the TPU-native equivalent runs peers *inside* one process as devices on a
`jax.sharding.Mesh` axis, exchanging compressed deltas over ICI instead of
sockets (BASELINE.json north star). Two axes:

- ``peer``: each device along this axis is an independent async-DP peer with
  its own replica of the shared table (the reference's "node").
- ``shard``: the flat table buffer is additionally sharded along this axis, so
  tables far larger than one device's HBM still sync at ICI speed (the
  reference crashes at ~60 Mi elements, quirk Q6; SURVEY.md §5.7).

Tests run this on an 8-device virtual CPU mesh
(``--xla_force_host_platform_device_count=8``); the same code runs unmodified
on a real v5e-8 (SURVEY.md §4.2 tier 2).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from ..config import MeshConfig


def make_mesh(
    n_peer: Optional[int] = None,
    n_shard: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    config: MeshConfig | None = None,
) -> Mesh:
    """A (peer, shard) mesh over ``n_peer * n_shard`` devices.

    ``n_peer=None`` uses all remaining devices. On real hardware, pass devices
    ordered so that the shard axis is innermost (contiguous ICI neighbors) —
    scale reductions ride the shard axis every frame, while peer exchange is
    one all-gather per frame.
    """
    cfg = config or MeshConfig()
    devs = list(devices if devices is not None else jax.devices())
    if n_peer is None:
        n_peer = len(devs) // n_shard
    need = n_peer * n_shard
    if need > len(devs):
        raise ValueError(
            f"mesh ({n_peer} peers x {n_shard} shards) needs {need} devices, "
            f"have {len(devs)}"
        )
    grid = np.array(devs[:need]).reshape(n_peer, n_shard)
    return Mesh(grid, (cfg.peer_axis, cfg.shard_axis))


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> int:
    """Initialize a MULTI-HOST pod: every host process calls this, then
    builds the same mesh with :func:`make_mesh` over ``jax.devices()`` (the
    global device list). XLA then routes the sync step's collectives over
    ICI within a slice and DCN between hosts automatically — one pod can
    span hosts with no code change in the sync path.

    This is the GSPMD tier of the multi-host story; the alternative tier is
    one HierarchicalTrainer per host pod bridged over the TCP tree
    (train/hierarchical.py), which tolerates asynchrony between hosts the
    way the reference's cross-machine peers do (README.md:26). Use this one
    when hosts are tightly coupled (same pod/DCN domain), the hierarchical
    tier when they are not.

    Arguments default to the standard JAX env vars (cluster auto-detection).
    Returns this process's index. No-ops safely if already initialized."""
    import jax.distributed

    # jax 0.4.x's CPU backend refuses multiprocess computations unless a
    # cross-process collectives implementation is picked explicitly; newer
    # jax selects one automatically (and may drop the config knob). On
    # 0.4.37 the option accepts update() but is NOT readable as a config
    # attribute, so probe the flag holder directly (default "none").
    try:
        from jax._src import xla_bridge as _xb

        if _xb.CPU_COLLECTIVES_IMPLEMENTATION.value in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # knob absent on this jax: the backend picks automatically

    # jax >= 0.5 exposes is_initialized(); 0.4.x only has the private
    # global client state — probe whichever this version has
    if hasattr(jax.distributed, "is_initialized"):
        initialized = jax.distributed.is_initialized()
    else:
        from jax._src import distributed as _dist

        initialized = getattr(_dist.global_state, "client", None) is not None
    if initialized:
        return jax.process_index()  # idempotent use in notebooks/tests
    # Any RuntimeError here (bad coordinator address, mismatched
    # num_processes/process_id) propagates: swallowing it would let a broken
    # multi-host launch proceed as a confusing single-process mesh.
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index()


def rows_per_shard(total: int, n_shard: int, lanes: int = 128) -> int:
    """Rows of the (rows, 128) view each shard owns; validates divisibility.

    ``total`` is always a multiple of 1024 (= 8 rows, ops/packing.py TILE), so
    any power-of-two ``n_shard`` <= 8 divides evenly; larger shard counts may
    need the caller to grow the table padding.
    """
    rows = total // lanes
    if rows % n_shard:
        raise ValueError(
            f"{rows} rows not divisible by {n_shard} shards; "
            f"pad the table to a multiple of {n_shard * lanes * 8} elements"
        )
    return rows // n_shard
