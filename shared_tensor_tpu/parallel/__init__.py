"""Pod tier: peer sync over ICI collectives on a device mesh (the north-star
replacement for the reference's TCP tree — see parallel/ici.py)."""

from .ici import (
    PeerSyncState,
    add_updates,
    build_sync_phases,
    build_sync_step,
    frame_ici_bytes,
    init_state,
    read_peer,
    state_sharding,
)
from .mesh import make_mesh, rows_per_shard

__all__ = [
    "PeerSyncState",
    "add_updates",
    "build_sync_phases",
    "build_sync_step",
    "frame_ici_bytes",
    "init_state",
    "read_peer",
    "state_sharding",
    "make_mesh",
    "rows_per_shard",
]
