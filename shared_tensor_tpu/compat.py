"""Reference-named API shim (the north star's "JAX shim").

The reference's public API is exactly three Lua calls
(reference src/sharedtensor.c:455-465, README.md:6-19):

    a = sharedtensor.createOrFetch(host, port, tensor)
    a:copyToTensor(t)
    a:addFromTensor(t)

This module exposes the same names with the same program shape, so a user
porting a Torch7/Lua script (example.lua, char-rnn) renames nothing. The
objects underneath are the real framework (comm/peer.py over the native
transport); tensors are jax arrays or pytrees of them.

`copyToTensor` returns the snapshot instead of filling a caller buffer —
jax arrays are immutable, so the out-parameter idiom has no meaning here.
"""

from __future__ import annotations

from typing import Any

from .comm.peer import SharedTensorPeer, create_or_fetch
from .config import Config


class _CompatHandle:
    """The reference's userdata object: three methods, nothing else."""

    def __init__(self, peer: SharedTensorPeer):
        self._peer = peer

    def copyToTensor(self) -> Any:  # noqa: N802 (reference-exact name)
        """Snapshot of the replica (reference l_copyToTensor,
        src/sharedtensor.c:435-446)."""
        return self._peer.read()

    def addFromTensor(self, delta: Any) -> None:  # noqa: N802
        """Async additive merge (reference l_addFromTensor,
        src/sharedtensor.c:448-453)."""
        self._peer.add(delta)

    def close(self) -> None:
        """Clean departure — the capability the reference lacks (its __gc
        exits the whole process on a connected tensor, quirk Q8)."""
        self._peer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def createOrFetch(  # noqa: N802 (reference-exact name)
    host: str, port: int, tensor: Any, config: Config | None = None
) -> _CompatHandle:
    """Create the shared tensor at host:port (becoming master, seeded from
    ``tensor``) or join the existing tree (reference l_createOrFetch,
    src/sharedtensor.c:347-391). Blocks until ready, like the reference's
    joiner wait — but via an explicit handshake, not a busy-wait on nonzero
    values (quirk Q4 fixed; an all-zero tensor joins fine)."""
    return _CompatHandle(create_or_fetch(host, port, tensor, config))
