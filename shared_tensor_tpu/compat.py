"""Reference-named API shim (the north star's "JAX shim").

The reference's public API is exactly three Lua calls
(reference src/sharedtensor.c:455-465, README.md:6-19):

    a = sharedtensor.createOrFetch(host, port, tensor)
    a:copyToTensor(t)
    a:addFromTensor(t)

This module exposes the same names with the same program shape, so a user
porting a Torch7/Lua script (example.lua, char-rnn) renames nothing. The
objects underneath are the real framework (comm/peer.py over the native
transport); tensors are jax arrays or pytrees of them.

`copyToTensor` returns the snapshot instead of filling a caller buffer —
jax arrays are immutable, so the out-parameter idiom has no meaning here.
"""

from __future__ import annotations

import os
from typing import Any

from .comm.peer import SharedTensorPeer, create_or_fetch
from .config import Config

# ---- native wire-format versioning (r09) ----------------------------------
#
# The native protocol's DATA/BURST framing is versioned here, in one place,
# because this module is the compatibility boundary of the project: v1 is
# the r08 framing ([kind][u32 seq][body]); v2 (r09) appends a 13-byte trace
# context (origin node id, origin monotonic ns, hop count — comm/wire.py
# TRACE_BYTES) that powers cross-hop trace propagation and the staleness
# telemetry. The gate is asymmetric by design:
#
# - DECODERS on both tiers accept BOTH framings forever (message length
#   disambiguates them unambiguously), so mixed-version trees interop and
#   a rollback never strands a peer;
# - EMISSION is gated: ``ObsConfig.trace_wire`` (default on) selects v2,
#   and ``ST_WIRE_TRACE=0`` in the environment force-pins a peer to v1
#   emission — the escape hatch for joining a tree of pre-r09 peers whose
#   decoders reject the longer headers.
#
# The SYNC handshake advertises the joiner's emission version
# (wire.encode_sync trailing byte) so a version skew is visible in the
# parent's logs instead of silent.

WIRE_VERSION_V1 = 1  # r08 framing, no trace context
WIRE_VERSION_V2 = 2  # r09 framing, 13-byte trace context
WIRE_VERSION = WIRE_VERSION_V2  # what this build emits by default

# ---- r10 handshake-capability flags ---------------------------------------
#
# One more trailing SYNC byte (wire.encode_sync ``flags``), following the
# same tolerant-extension discipline as the r09 version byte: pre-r10
# parents unpack the fixed header and ignore trailing bytes, and absent
# flags read back as 0 (a plain read-write peer). The serving tier
# (serve/subscriber.py) advertises itself here so WRITERS can skip all
# ledger/ACK state for the link:
#
# - SYNC_FLAG_READ_ONLY: the joiner is a read-only subscriber leaf. It will
#   never add(), never ACK, and never needs a re-graft carry — the parent
#   attaches the link UNLEDGERED (no unacked ledger, no go-back-N, no
#   retransmission; loss shows up as a seq gap the subscriber repairs by
#   re-running the SYNC/DONE handshake on the same link).
# - SYNC_FLAG_RANGE: a wire.RANGE message follows before DONE; the parent
#   forwards only the subscribed word range per frame (wire.RDATA framing —
#   the paged-subscription discipline).
#
# Joining a pre-r10 parent with these flags is detectably broken rather
# than silently wrong: the old parent treats the subscriber as a writer
# child, its unACKed ledger black-holes, and the link tears down — the
# subscriber keeps resyncing and its reads keep raising StalenessError
# (never silent staleness).

SYNC_FLAG_READ_ONLY = 0x01
SYNC_FLAG_RANGE = 0x02
# r11: the joiner can DECODE sign2 (2-bit) DATA/BURST frames (the kind
# byte's 0x80 precision bit; native engine tier only — python-tier peers
# never set it and therefore never receive a 2-bit frame). The parent's
# side of the same advertisement rides a WELCOME trailing flags byte
# (wire.encode_welcome) — pre-r11 peers send a bare 1-byte WELCOME, which
# reads back as flags 0, so emission toward them stays 1-bit and mixed
# trees interop without configuration. ST_SIGN2=0 force-disables both the
# advertisement and the governor (the A/B escape hatch, like
# ST_WIRE_TRACE=0).
SYNC_FLAG_SIGN2 = 0x04
# r14: the same-host shared-memory transport lane. A joiner sets this flag
# and appends its 16-byte host identity (Linux boot id) to the SYNC tail;
# a same-host r14 parent replies with a segment offer (host id + token +
# /dev/shm name) in the WELCOME tail, and BOTH sides then attach the
# link's data plane to SPSC shared-memory rings while TCP stays the
# control/liveness channel. Every mismatch is a silent keep-TCP: pre-r14
# peers ignore the trailing bytes entirely (the r09/r10 tolerant-extension
# discipline), cross-host peers fail the boot-id match, and a failed
# segment open/validation at attach time falls back with a shm_fallback
# timeline event. ST_SHM=0 force-disables the lane end to end (the A/B
# escape hatch, like ST_SIGN2/ST_WIRE_TRACE).
SYNC_FLAG_SHM = 0x08
# r16: the cluster-sharded tensor (shared_tensor_tpu/shard). A sharded
# joiner sets this flag and appends its 2-byte shard-index claim to the
# SYNC tail (after the shm bytes); a sharded parent answers with the same
# bit in its WELCOME flags and the shard map as a wire.SHARD control
# message right behind it. The negotiation is tolerant in BOTH
# orientations, r14 discipline:
#
# - sharded joiner -> pre-r16 (or unsharded) parent: the parent ignores
#   the tail and attaches a plain writer child; the joiner detects the
#   absent WELCOME shard flag and FALLS BACK to today's full-replica
#   protocol (shard.create_or_fetch_sharded returns a classic peer) —
#   any non-sharded tree keeps the full-replica flood untouched;
# - pre-r16 WRITER joiner -> sharded parent: REJECTed with an explicit
#   reason (the r10 detectably-broken-not-silently-wrong rule: no node
#   in a sharded cluster holds the full replica, so a full-replica child
#   cannot be served; start the cluster with ShardConfig.n_shards=0 /
#   ST_SHARD=0 to keep the classic protocol);
# - read-only SUBSCRIBERS (SYNC_FLAG_READ_ONLY) interop either way: a
#   sharded owner serves ranged subscriptions within its own shard.
#
# ST_SHARD=0 force-disables sharding end to end (the A/B escape hatch,
# like ST_SHM/ST_SIGN2/ST_WIRE_TRACE).
SYNC_FLAG_SHARD = 0x10
# the wire module hardcodes the same bits (it cannot import this module —
# compat -> peer -> wire would be a cycle); a silent drift between the two
# would degrade every negotiation to permanent fallback, so tie them
# at import time
from .comm import wire as _wire

assert SYNC_FLAG_SHM == _wire.SHM_FLAG, "SYNC_FLAG_SHM drifted from wire.SHM_FLAG"
assert SYNC_FLAG_SHARD == _wire.SHARD_FLAG, (
    "SYNC_FLAG_SHARD drifted from wire.SHARD_FLAG"
)
del _wire

# ---- r12 cluster-lifecycle control kinds ----------------------------------
#
# The consistent-cut barrier (wire.SNAP/SNAP_ACK/RESUME) and the routed
# operator command (wire.CTL) are CONTROL-plane message kinds, following
# the same tolerant-extension discipline as every protocol addition since
# r09: a pre-r12 peer that receives one logs "unknown message kind" and
# drops it without touching its data plane — nothing hangs, because the
# barrier's failure mode is explicit (the initiating root times out, logs
# which links never acked, and RESUMEs the rest; LifecycleConfig.
# snapshot_timeout_sec / pause_timeout_sec are the two budgets). The
# practical rolling-upgrade rule is therefore: finish upgrading the tree
# before relying on cluster snapshots; everything ELSE (DATA/BURST
# interop, digests, serve traffic) is version-gated independently and
# works mid-upgrade — the ``ctl versions`` audit (per-node
# st_wire_version gauge in the digest breakdown) shows exactly who still
# emits what. MIGRATION.md carries the full runbook.

LIFECYCLE_PROTOCOL = 1  # shard/manifest + barrier message format version


def sign2_mode(config: "Config | None" = None) -> int:
    """The engine's precision mode per config/env policy: 0 = fixed 1-bit
    (ST_SIGN2=0 or CodecConfig.adaptive_precision=False), 1 = telemetry-
    adaptive (default), 2 = sign2 pinned on every capable link (ST_SIGN2=2
    — the A/B arm). Engine-tier capability is checked by the caller."""
    env = os.environ.get("ST_SIGN2", "1")
    if env == "0":
        return 0
    if config is not None and not config.codec.adaptive_precision:
        return 0
    return 2 if env == "2" else 1


def wire_protocol_version(config: Config | None = None) -> int:
    """The DATA/BURST framing version this peer should EMIT: v2 unless the
    config or the ST_WIRE_TRACE=0 escape hatch pins v1 (wire-compat mode
    has no native framing at all and ignores this)."""
    if os.environ.get("ST_WIRE_TRACE", "1") == "0":
        return WIRE_VERSION_V1
    if config is not None and not config.obs.trace_wire:
        return WIRE_VERSION_V1
    return WIRE_VERSION_V2


class _CompatHandle:
    """The reference's userdata object: three methods, nothing else."""

    def __init__(self, peer: SharedTensorPeer):
        self._peer = peer

    def copyToTensor(self) -> Any:  # noqa: N802 (reference-exact name)
        """Snapshot of the replica (reference l_copyToTensor,
        src/sharedtensor.c:435-446)."""
        return self._peer.read()

    def addFromTensor(self, delta: Any) -> None:  # noqa: N802
        """Async additive merge (reference l_addFromTensor,
        src/sharedtensor.c:448-453)."""
        self._peer.add(delta)

    def close(self) -> None:
        """Clean departure — the capability the reference lacks (its __gc
        exits the whole process on a connected tensor, quirk Q8)."""
        self._peer.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def createOrFetch(  # noqa: N802 (reference-exact name)
    host: str, port: int, tensor: Any, config: Config | None = None
) -> _CompatHandle:
    """Create the shared tensor at host:port (becoming master, seeded from
    ``tensor``) or join the existing tree (reference l_createOrFetch,
    src/sharedtensor.c:347-391). Blocks until ready, like the reference's
    joiner wait — but via an explicit handshake, not a busy-wait on nonzero
    values (quirk Q4 fixed; an all-zero tensor joins fine)."""
    return _CompatHandle(create_or_fetch(host, port, tensor, config))
