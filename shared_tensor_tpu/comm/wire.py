"""Frame + handshake serialization for the peer tier.

The reference wire protocol is one raw stream of ``[f32 scale][bitmask]``
frames with no handshake at all — frame size is implied by out-of-band
agreement on the tensor size (reference src/sharedtensor.c:121-122, :176-177;
README.md:26 "one port per tensor"), and state transfer to a joiner happens
implicitly through the normal codec stream (SURVEY.md §5.4).

The native-mode protocol here keeps the codec-frame streaming but makes the
implicit parts explicit, because they are exactly where the reference breaks
(quirks Q4/Q5/Q8):

- every message is typed (1 kind byte) inside the transport's length-prefixed
  framing — no size ambiguity, no host-endianness on the wire (all little-
  endian, explicit);
- a joining link runs a SYNC handshake: the downstream node sends its current
  replica snapshot (chunked), the upstream node seeds the link residual with
  the *difference* (parent - child) and replies WELCOME. For a fresh joiner
  the snapshot is all-zero, which degenerates to the reference's
  seed-with-full-replica join; for a re-grafting peer that already has state
  (reference can't do this at all — it exit(-1)s, quirk Q8) only the missing
  delta streams, and the split-horizon flood then repairs its whole subtree;
- spec mismatch is REJECTed explicitly (the reference THError()s on size
  mismatch, src/sharedtensor.c:335, but only detects it after corrupting the
  stream framing).

Data frames carry per-leaf scales ("table sync", reference README.md:41) +
the LSB-first packed sign bits produced by ops/packing.py — prefixed (r06)
with the sender's per-link message sequence number (``tx_seq``, u32 LE):
the cumulative count of DATA/BURST messages sent on the link, starting at
1. The tag costs 4 bytes and makes the cumulative-count ACK protocol sound
under message loss: without it, delivery must be a prefix of what was sent
(true on a raw TCP stream, violated by anything that can swallow or repeat
one message — fault injection here, a dying proxy/peer in production), or
the sender acks the WRONG ledger entries and rollback re-delivers frames
the peer already applied. With it, both tiers run go-back-N:

- the receiver applies a DATA/BURST message only when it decodes AND
  ``seq == rx + 1`` (in order); its cumulative ACK is then exactly the
  last accepted seq;
- ``seq <= rx`` is a duplicate (injected, or a retransmit racing our ACK):
  discarded without applying or counting — exactly-once under dup faults;
- ``seq > rx + 1`` means a message vanished: the gap and everything after
  it is discarded unapplied, so nothing is ever mis-acked;
- the sender keeps every unacked message's frames in its ledger (capped by
  a send window — peer.SEND_WINDOW — so a stalled link cannot grow it
  unboundedly) and, when the oldest goes unacked past
  ``TransportConfig.ack_timeout_sec``, retransmits the HEAD of the unacked
  tail BYTE-IDENTICAL (same seqs; only the head can restore in-order
  progress) with per-round exponential backoff — safe to repeat because
  the receiver dedups by seq. After ``ack_retry_limit`` fruitless rounds
  the link is torn down into the LINK_DOWN -> rollback -> carry ->
  re-graft path instead of retrying forever.

Net effect: drop / duplicate / truncate / reorder faults on data frames
converge EXACTLY (no lost and no double-counted mass); the only remaining
at-least-once window is a peer dying between apply and ACK, which the
ledger re-delivers (documented crash point "between-apply-and-ack").

``encode_compat_frame``/``decode_compat_frame`` speak the reference's exact
frame bytes for wire-compat interop with C peers (SURVEY.md §2.3 wire spec).
"""

from __future__ import annotations

import logging
import struct
import threading
from typing import Iterator, Optional

import numpy as np

log = logging.getLogger("shared_tensor_tpu.wire")

from ..ops.table import TableFrame, TableSpec

# Process-wide count of non-finite scales zeroed at the decode trust
# boundary (r08 obs satellite; canonical name
# st_corrupt_scales_zeroed_total in obs/schema.py). Process-wide, not
# per-peer: the zeroing happens inside stateless decode helpers — peers'
# registries sample it via a collector, and a nonzero DELTA during a run
# means a link is feeding garbage (each hit also logs a warning).
_corrupt_mu = threading.Lock()
_corrupt_scales_zeroed = 0


def corrupt_scales_zeroed() -> int:
    with _corrupt_mu:
        return _corrupt_scales_zeroed


def _count_corrupt_scales(n: int) -> None:
    global _corrupt_scales_zeroed
    with _corrupt_mu:
        _corrupt_scales_zeroed += n

# message kinds (first payload byte, native mode)
DATA = 0  # codec frame: scales + packed sign bits
SYNC = 1  # child -> parent: join request header
CHUNK = 2  # child -> parent: replica snapshot chunk
DONE = 3  # child -> parent: snapshot complete
WELCOME = 4  # parent -> child: accepted, streaming begins
REJECT = 5  # parent -> child: spec mismatch, reason attached
ACK = 6  # cumulative count of DATA/BURST messages received on this link
BURST = 7  # K codec frames in one message (host tier, small tables)
DIGEST = 8  # child -> parent: r09 in-band cluster metrics digest (JSON)
# r10 read-path serving tier (serve/). RANGE and FRESH are control-plane;
# RDATA is the range-filtered data framing for paged subscriptions.
RANGE = 9  # subscriber -> parent: word-range subscription (before DONE)
FRESH = 10  # parent -> subscriber: freshness mark (residual fully drained)
RDATA = 11  # parent -> subscriber: one frame sliced to the subscribed range
# r12 cluster lifecycle (control plane — the r06 rule applies: chaos
# classes never touch these, so a barrier completes deterministically).
# SNAP floods the quiesce marker down the tree; per-link FIFO makes it a
# consistent-cut marker (it follows the sender's last pre-pause data).
# SNAP_ACK flows back up carrying the subtree's shard manifest entries;
# RESUME releases the barrier top-down; CTL routes an operator command
# (today: drain <node>) down the tree. All four carry bounded JSON bodies
# (encode_lifecycle), sized under the DIGEST receive bound.
SNAP = 12  # parent -> child: lifecycle barrier marker (JSON body)
SNAP_ACK = 13  # child -> parent: barrier ack + subtree shard entries (JSON)
RESUME = 14  # parent -> child: release the lifecycle barrier (JSON)
CTL = 15  # parent -> child: routed operator command (JSON)
# r16 cluster-sharded tensor (shared_tensor_tpu/shard). SHARD is the
# control plane of the shard map — claims/grants, owner route announces,
# drain-handoff state transfer — a bounded JSON body like the lifecycle
# kinds (encode_shard below). FWD is the owner-routed data plane: one
# codec frame sliced to a shard's word range, relayed hop-by-hop toward
# the shard's owner WITHOUT re-quantization (the r16 routing discipline:
# per-hop loss is repaired by the same go-back-N ledger as DATA/BURST;
# end-to-end duplication — a rollback-resend racing a delivered-but-
# unACKed original across a re-route — is deduplicated at the owner by
# the (origin, fwd_seq) identity the header carries). Pre-r16 peers that
# receive either kind log "unknown message kind" and drop it without
# touching their data plane (the r12 tolerant-extension discipline).
SHARD = 16  # shard-map control: claim/grant/own/map/handoff (JSON)
FWD = 17  # owner-routed forwarded delta frame (binary, ledgered)
# r18 clock plane (obs/clock.py): the NTP-style four-stamp offset probe
# and its reply, bounded JSON bodies like the lifecycle kinds. Control
# plane under the r06 rule — chaos classes never touch it (it is not in
# is_data), so clock estimates keep converging through injected faults
# and the corrected staleness the SLO alerts on stays honest. Python
# tier only today: engine-lane links have no estimator, and pre-r18
# peers drop the kind with the r12 "unknown message kind" tolerance.
CLOCK = 18  # both ways on a parent link: offset probe / reply (JSON)

#: r14 shm/r14-capability flag bit — MUST equal compat.SYNC_FLAG_SHM
#: (compat asserts the tie at import; defined here too because compat
#: imports peer which imports this module, so wire cannot import compat).
#: The bit gates the SYNC/WELCOME shm tails this module encodes/decodes.
SHM_FLAG = 0x08
#: r14 in-stream SWITCH marker (unstriped shm lanes): the length-prefix
#: value the sender writes as its LAST data-plane byte on TCP before
#: moving to the rings — above the transport's 1 GiB payload sanity cap,
#: so it can never collide with a real frame length. Python-tier peers
#: never negotiate the lane and so never see it on the wire; the value
#: is mirrored here as the single protocol-constant source the wire lint
#: (tools/lint_wire.py) and the protocol specs (tools/protospec)
#: cross-check against sttransport.cpp's kShmSwitchLen — a silent drift
#: would make an upgraded receiver mis-parse the marker as a length and
#: tear the link down on every lane switch.
SHM_SWITCH_LEN = 0xFFFFFFFD
#: r14 sendmmsg batch cap: most queued messages the native sender folds
#: into ONE kernel crossing on the clean send path (sttransport.cpp
#: kCoalesce). Protocol-adjacent rather than wire-visible — but it
#: bounds how many messages can shear together on a mid-batch failure,
#: which the retransmission window's sizing assumes — so it lives here
#: under the same lint tie as the header sizes.
SENDMMSG_BATCH = 16
#: r16 shard-capability flag bit — MUST equal compat.SYNC_FLAG_SHARD
#: (compat asserts the tie at import, like SHM_FLAG above; the lint
#: re-checks it statically on seeded trees that never import). The bit
#: gates the 2-byte shard-claim tail this module appends to SYNC and the
#: shard-map hello a sharded parent sends after WELCOME.
SHARD_FLAG = 0x10

_SYNC_FMT = "<IQ16s"  # num_leaves, total_n, layout digest
_CHUNK_HDR = "<Q"  # byte offset into the flat f32 snapshot

#: Snapshot chunk payload cap. Big enough to amortize framing, small enough
#: that queue-depth backpressure keeps memory bounded on huge tables.
CHUNK_BYTES = 1 << 22


#: Burst bounds. A BURST message may carry at most BURST_MAX_FRAMES frames
#: and at most ~BURST_MAX_BYTES of payload (so huge tables burst with a
#: small K instead of a 33 MB message). BOTH sides derive their receive
#: buffer bound from these and the (handshake-identical) spec, so a burst
#: can never exceed what any peer sized for — oversized incoming messages
#: would otherwise be silently truncated by the transport's recv copy.
#: Every tier bursts at every size now (host: amortizes per-message cost
#: and the engine's frame-0 scale scan; device: amortizes the device-link
#: round trip) — the K for a spec comes from burst_frames_cap below.
BURST_MAX_FRAMES = 255
#: 16 MiB: at 16 Mi elements a frame's wire body is ~2 MiB, so this budget
#: gives burst caps of ~7 there — and the k-frame fused receive
#: (stc_apply_frames) then touches the 64 MiB target ONCE per burst
#: instead of once per frame (measured r07, 16 Mi loopback through the
#: zero-copy plane: 737 f/s = 49.5 GB/s equiv — ENGINE_SWEEP_r07.json).
#: Worst-case transport memory is bounded by queue_depth (8) x this budget
#: per direction per link (~128 MiB at the largest tables) — host-RAM
#: class, like every buffer at that table size.
BURST_MAX_BYTES = 1 << 24


#: Wire overhead of a DATA message before the frame body: kind byte +
#: u32 tx_seq. BURST adds one more byte (the frame count). These are the
#: v1 (r08) headers; the v2 (r09) framing appends a TRACE_BYTES-long trace
#: context — origin node id (u32 LE), origin monotonic ns (u64 LE), hop
#: count (u8) — giving every update generation a causal provenance that
#: survives the tree walk (each hop re-stamps hops+1; obs/trace_export.py
#: reconstructs full paths from the per-hop apply events). Decoders accept
#: BOTH sizes — the frame body is a multiple of 4 bytes and the trace adds
#: 13, so message length disambiguates the version and mixed-version trees
#: interop (compat.py WIRE_VERSION documents the gate; ObsConfig.trace_wire
#: / ST_WIRE_TRACE=0 pins a peer to v1 emission).
DATA_HDR = 5
BURST_HDR = 6
TRACE_BYTES = 13
DATA_HDR_T = DATA_HDR + TRACE_BYTES  # 18
BURST_HDR_T = BURST_HDR + TRACE_BYTES  # 19
#: r14 "aligned" v3 framing (native engine tier): ONE 24-byte header for
#: DATA and BURST — [kind u8][k u8][pad u16][seq u32][origin u32][gen u64]
#: [hops u8][pad*3] — sized so the frame body lands 8-aligned in the
#: receiver's buffer (the engine's zero-repack fused apply reads scales/
#: words straight from it). Emitted only toward peers that advertised the
#: r14 capability (compat.SYNC_FLAG_SHM doubles as the marker); decoded
#: here unconditionally by exact length, like every framing before it
#: (24 mod 4 = 0 collides with neither 5/18 nor 6/19).
HDR_V3 = 24
_TRACE_FMT = "<IQB"  # origin node id, origin monotonic ns, hop count

#: Hard cap on one DIGEST message's JSON body. The digest is BOUNDED by
#: construction (obs/aggregate.py truncates per-node breakdowns past its
#: node cap), and every peer's receive buffer is sized to carry at least
#: this much (frame_wire_bytes below).
DIGEST_MAX_BYTES = 1 << 16


def burst_frames_cap(spec: TableSpec) -> int:
    """Most frames one BURST message may carry for this spec (>= 1).
    Sized against the v2 header so a traced burst never exceeds the
    receive-buffer bound either way."""
    per = frame_payload_bytes(spec)
    return max(1, min(BURST_MAX_FRAMES, (BURST_MAX_BYTES - BURST_HDR_T) // per))


def compat_burst_frames_cap(n: int) -> int:
    """Most reference-protocol frames one wire message may carry for an
    n-element tensor (>= 1) — the compat twin of burst_frames_cap, kept
    here so both modes' burst bounds share the BURST_MAX_* budget (a
    K-frame compat burst is K fixed-size frames concatenated; see
    stengine.cpp's compat-burst note)."""
    return max(1, min(BURST_MAX_FRAMES, BURST_MAX_BYTES // compat_frame_bytes(n)))


def frame_payload_bytes(spec: TableSpec) -> int:
    """Bytes of ONE frame's wire body (scales + packed words) — the single
    source of truth for the frame layout (decode_frame, decode_burst, and
    the transport buffer sizing all derive from it)."""
    return 4 * spec.num_leaves + 4 * (spec.total // 32)


def frame_payload2_bytes(spec: TableSpec) -> int:
    """Bytes of one sign2 (2-bit, r11) frame body: [scales L*4]
    [sign words W*4][mag words W*4]. Emitted by the native engine only
    (kind byte's 0x80 precision bit, capability-gated per link); sized
    here so every peer's receive bound covers the widest single sign2
    DATA message a capable sender may emit."""
    return 4 * spec.num_leaves + 8 * (spec.total // 32)


def burst_wire_bytes(spec: TableSpec) -> int:
    """Max BURST message size for this spec — the LARGEST emitted header
    (r14's 24-byte aligned v3 exceeds the 19-byte traced v2): this feeds
    every receive-buffer bound, and even 5 bytes short means a full
    burst from an r14 engine sender is silently truncated at the
    transport, rejected as undecodable without consuming its seq, and
    retransmitted identically until go-back-N black-holes the link —
    the exact r09 failure class this function exists to prevent."""
    hdr = max(BURST_HDR_T, HDR_V3)
    return hdr + burst_frames_cap(spec) * frame_payload_bytes(spec)


def frame_wire_bytes(spec: TableSpec) -> int:
    """Max payload size of any native-mode message for this spec (covers
    the v2 trace headers, the bounded DIGEST control message, the r10
    RDATA framing — whose range header is 8 bytes longer than DATA's, so a
    near-full-range subscription on a burst-cap-1 table would otherwise
    exceed every other bound by a few bytes and be silently truncated at
    the transport: the exact r09 burst_wire_bytes failure class — and the
    r11 sign2 single-frame width, which exceeds the 1-bit burst bound on
    burst-cap-1 tables for the same reason; sign2 BURSTS are capped by the
    sender against this same bound)."""
    data = max(DATA_HDR_T, HDR_V3) + frame_payload_bytes(spec)
    data2 = max(DATA_HDR_T, HDR_V3) + frame_payload2_bytes(spec)
    rdata = RDATA_HDR_T + frame_payload_bytes(spec)
    chunk = 1 + struct.calcsize(_CHUNK_HDR) + CHUNK_BYTES
    return max(
        data, data2, rdata, chunk, burst_wire_bytes(spec),
        1 + DIGEST_MAX_BYTES
    )


def data_seq(payload: bytes, spec: Optional[TableSpec] = None) -> int:
    """The per-link tx_seq of a DATA/BURST payload (module docstring).
    Pass ``spec`` when the sender may be an r14 engine peer: the v3
    framing keeps its seq at byte 4 (after the k byte and alignment pad),
    and only the exact-length test against the spec can tell the
    framings apart."""
    if len(payload) < DATA_HDR:
        raise ValueError(
            f"{len(payload)}-byte data message is too short to carry a seq"
        )
    if spec is not None and len(payload) > HDR_V3 and payload[1] > 0:
        per = (
            frame_payload2_bytes(spec)
            if payload[0] & 0x80
            else frame_payload_bytes(spec)
        )
        if len(payload) == HDR_V3 + payload[1] * per:
            return struct.unpack_from("<I", payload, 4)[0]
    return struct.unpack_from("<I", payload, 1)[0]


def data_trace(
    payload: bytes, spec: TableSpec
) -> Optional[tuple[int, int, int]]:
    """The (origin_node, origin_ns, hops) trace context of a DATA/BURST
    payload, or None for v1 (untraced) framing. Version detection is by
    exact length — see the header-constant docstring."""
    per = frame_payload_bytes(spec)
    n = len(payload)
    if not payload:
        return None
    if n > HDR_V3 and payload[1] and n == HDR_V3 + payload[1] * per:
        # r14 aligned framing: the trace context sits at bytes 8..20 in
        # the same [origin u32][gen u64][hops u8] order as v2
        return struct.unpack_from(_TRACE_FMT, payload, 8)
    if payload[0] == DATA:
        if n == DATA_HDR_T + per:
            return struct.unpack_from(_TRACE_FMT, payload, DATA_HDR)
    elif payload[0] == BURST and n > BURST_HDR_T:
        k = payload[BURST_HDR - 1]
        if k and n == BURST_HDR_T + k * per:
            return struct.unpack_from(_TRACE_FMT, payload, BURST_HDR)
    return None


class FramePool:
    """Ring of wire-sized send-buffer slots (r07 zero-copy data plane).

    Slot lifecycle: ``acquire`` -> encode in place (encode_frame_into /
    encode_burst_into) -> the slot view is the ledger's retransmission
    payload (in-flight) -> ``release`` when the receiver's ACK pops the
    ledger entry (or the link dies) -> free list, capacity warm. The send
    window (peer.SEND_WINDOW) bounds live slots per link, so steady-state
    sends allocate nothing per message: ``acquires`` grows while
    ``alloc_events`` stays flat (the assertion peer.metrics() exposes).
    ``keep`` bounds how many free slots retain their buffer, so an idle
    peer's high-water mark doesn't pin memory.

    Thread-safety: acquire runs only on the peer's send thread; release
    runs on the recv thread (ACK pops) — the lock covers the free list.
    A released slot's buffer may still be referenced by an in-flight
    retransmission VIEW, which is safe here because only the send thread
    ever writes slot buffers (reuse cannot overwrite bytes another thread
    is still sending)."""

    def __init__(self, slot_bytes: int, keep: int = 4):
        self._slot_bytes = int(slot_bytes)
        self._keep = keep
        self._free: list[memoryview] = []
        self._mu = threading.Lock()
        self.acquires = 0
        self.alloc_events = 0

    @property
    def slot_bytes(self) -> int:
        return self._slot_bytes

    def acquire(self) -> memoryview:
        """A writable slot_bytes-sized memoryview (contents undefined)."""
        with self._mu:
            self.acquires += 1
            if self._free:
                return self._free.pop()
            self.alloc_events += 1
        return memoryview(bytearray(self._slot_bytes))

    def release(self, slot: memoryview) -> None:
        with self._mu:
            if len(self._free) < self._keep:
                self._free.append(slot)
            # else: drop — bounded idle memory, GC frees the buffer

    def stats(self) -> dict:
        with self._mu:
            return {
                "tx_slot_acquires": self.acquires,
                "tx_slot_alloc_events": self.alloc_events,
                "tx_slots_free": len(self._free),
            }


def _write_frame_body(buf: memoryview, off: int, frame: TableFrame) -> int:
    """Copy one frame's scales+words into ``buf`` at ``off`` (little-endian
    wire layout) straight from the numpy buffers — no intermediate bytes
    objects. Returns the new offset."""
    scales = np.ascontiguousarray(frame.scales, "<f4")
    words = np.ascontiguousarray(frame.words, "<u4")
    sb, wb = scales.nbytes, words.nbytes
    buf[off : off + sb] = memoryview(scales).cast("B")
    buf[off + sb : off + sb + wb] = memoryview(words).cast("B")
    return off + sb + wb


def _clamp_trace(trace) -> tuple[int, int, int]:
    """The ONE place the trace stamp's field clamping lives: origin and
    generation wrap to their wire widths, hops saturate at 255."""
    origin, gen, hops = trace
    return (
        origin & 0xFFFFFFFF,
        gen & 0xFFFFFFFFFFFFFFFF,
        min(int(hops), 255),
    )


def _pack_trace(buf: memoryview, off: int, trace) -> int:
    """Write the 13-byte trace context at ``off``; returns the new
    offset."""
    struct.pack_into(_TRACE_FMT, buf, off, *_clamp_trace(trace))
    return off + TRACE_BYTES


def encode_frame_into(
    frame: TableFrame, seq: int, buf: memoryview, trace=None
) -> int:
    """encode_frame writing into a pooled slot (FramePool) instead of
    building bytes: header + scales + sign words land at their final wire
    offsets, and the filled prefix doubles as the ledger's byte-identical
    retransmission payload. ``trace`` = (origin, origin_ns, hops) selects
    the v2 framing (r09 trace context); None keeps the v1 bytes untouched.
    Returns the message length."""
    buf[0] = DATA
    struct.pack_into("<I", buf, 1, seq & 0xFFFFFFFF)
    off = DATA_HDR if trace is None else _pack_trace(buf, DATA_HDR, trace)
    return _write_frame_body(buf, off, frame)


def encode_frame(frame: TableFrame, seq: int, trace=None) -> bytes:
    scales = np.asarray(frame.scales, dtype="<f4")
    words = np.asarray(frame.words, dtype="<u4")
    th = b"" if trace is None else struct.pack(_TRACE_FMT, *_clamp_trace(trace))
    return (
        bytes([DATA])
        + struct.pack("<I", seq & 0xFFFFFFFF)
        + th
        + scales.tobytes()
        + words.tobytes()
    )


def decode_frame(
    payload: bytes, spec: TableSpec, scratch: Optional[DecodeScratch] = None
) -> TableFrame:
    """Decode one DATA message.

    Corruption guard at the trust boundary: a non-finite scale would NaN
    the replica and flood the poison tree-wide (reference quirk Q9 — the
    receive-path analog of add()'s sanitization). Zeroing makes the leaf a
    no-op; the mass that frame carried is lost (the sender's error
    feedback already debited it), bounded to the corrupted frames
    themselves — strictly better than the reference, which loses the
    whole tree. Huge-but-finite scales pass: every f32 below inf is
    inside the protocol's legal domain (residuals clamp at +/-3e38, so
    legitimate scales range up to 2^127), and the apply paths clamp to
    +/-3e38 so even those cannot create an absorbing inf/NaN state.

    Destination arrays are numpy, NOT jnp: a host-tier peer must never
    initialize a jax backend (thread-pool contention with its C codec
    loops); device tiers convert on entry to their jitted applies. COPIES,
    not views: the frombuffer views start at payload offset 5, i.e.
    4-byte-misaligned pointers, which the native C kernels must never
    receive (UB; faults on strict-alignment targets) — with ``scratch``
    (the per-link DecodeScratch pool) the copy lands in recycled arrays,
    so steady-state decode allocates nothing per frame."""
    k = spec.num_leaves
    w = spec.total // 32
    per = frame_payload_bytes(spec)
    # v1 or v2 framing by exact length (the trace context adds 13 bytes to
    # a 4-multiple body — unambiguous); the trace itself is read separately
    # via data_trace, so the decode stays format-agnostic
    if len(payload) == DATA_HDR + per:
        off = DATA_HDR
    elif len(payload) == DATA_HDR_T + per:
        off = DATA_HDR_T
    elif len(payload) == HDR_V3 + per and payload[1] == 1:
        off = HDR_V3  # r14 aligned framing, k == 1
    else:
        raise ValueError(
            f"DATA frame is {len(payload)} bytes, spec wants "
            f"{DATA_HDR + per}, {DATA_HDR_T + per} or {HDR_V3 + per} "
            f"(k={k}, words={w}) — peer table layout mismatch"
        )
    return _decode_one_frame(payload, off, spec, scratch)


def encode_burst(frames, spec: TableSpec, seq: int, trace=None) -> bytes:
    """K frames in one message: [BURST][u32 seq][u8 k][trace?][k x
    (scales||words)]. Successive frames of one link are successive halvings
    of its residual; shipping them together amortizes the per-message
    engine cost that dominates at small table sizes (see
    Config.frame_burst). ``trace`` selects the v2 framing (one context per
    MESSAGE — the burst is one ledger entry, one delivery, one hop)."""
    cap = burst_frames_cap(spec)
    if not 1 <= len(frames) <= cap:
        raise ValueError(
            f"burst of {len(frames)} frames (this spec allows 1..{cap} — "
            f"the bound peers sized their receive buffers for)"
        )
    hdr = bytes([BURST]) + struct.pack("<I", seq & 0xFFFFFFFF) + bytes(
        [len(frames)]
    )
    if trace is not None:
        hdr += struct.pack(_TRACE_FMT, *_clamp_trace(trace))
    parts = [hdr]
    for f in frames:
        parts.append(np.asarray(f.scales, dtype="<f4").tobytes())
        parts.append(np.asarray(f.words, dtype="<u4").tobytes())
    out = b"".join(parts)
    # hard check, not assert (would vanish under python -O): an encoder that
    # emits a mis-sized burst silently desyncs every downstream decoder
    want = len(hdr) + len(frames) * frame_payload_bytes(spec)
    if len(out) != want:
        raise ValueError(
            f"encoded burst is {len(out)} bytes, layout wants {want} — "
            f"frame/spec mismatch"
        )
    return out


def encode_burst_into(
    frames, spec: TableSpec, seq: int, buf: memoryview, trace=None
) -> int:
    """encode_burst writing into a pooled slot (FramePool): same layout and
    the same hard size check, zero intermediate bytes objects. Returns the
    message length."""
    cap = burst_frames_cap(spec)
    if not 1 <= len(frames) <= cap:
        raise ValueError(
            f"burst of {len(frames)} frames (this spec allows 1..{cap} — "
            f"the bound peers sized their receive buffers for)"
        )
    buf[0] = BURST
    struct.pack_into("<I", buf, 1, seq & 0xFFFFFFFF)
    buf[BURST_HDR - 1] = len(frames)
    hdr = BURST_HDR if trace is None else _pack_trace(buf, BURST_HDR, trace)
    off = hdr
    for f in frames:
        off = _write_frame_body(buf, off, f)
    # hard check, not assert (see encode_burst): a mis-sized burst silently
    # desyncs every downstream decoder
    if off != hdr + len(frames) * frame_payload_bytes(spec):
        raise ValueError(
            f"encoded burst is {off} bytes, layout wants "
            f"{hdr + len(frames) * frame_payload_bytes(spec)} — "
            f"frame/spec mismatch"
        )
    return off


class DecodeScratch:
    """Per-link pool of decode destination arrays (r07 satellite): steady-
    state decode_frame/decode_burst copy into recycled (scales, words)
    arrays instead of allocating fresh ones per frame (the old
    ``.copy()``-per-frame path — ~n/8 bytes of fresh heap per frame).

    Frames handed out stay valid until :meth:`recycle`, which the peer's
    recv loop calls after the batch has been APPLIED (receive_frames is
    synchronous on every tier, so nothing references the arrays after the
    flush). Single-consumer: only the recv loop touches a link's scratch."""

    def __init__(self, spec: TableSpec, keep: int = 16):
        self._k = spec.num_leaves
        self._w = spec.total // 32
        self._keep = keep
        self._free: list[tuple[np.ndarray, np.ndarray]] = []
        self._out: list[tuple[np.ndarray, np.ndarray]] = []

    def frame(self) -> tuple[np.ndarray, np.ndarray]:
        """A (scales, words) destination pair, reused when possible."""
        if self._free:
            pair = self._free.pop()
        else:
            pair = (
                np.empty(self._k, np.float32),
                np.empty(self._w, np.uint32),
            )
        self._out.append(pair)
        return pair

    def recycle(self) -> None:
        """Return every handed-out pair to the free list — call ONLY after
        the decoded frames have been applied."""
        if self._out:
            free = self._free
            for pair in self._out:
                if len(free) < self._keep:
                    free.append(pair)
            self._out.clear()


def _decode_one_frame(
    payload, off: int, spec: TableSpec, scratch: Optional[DecodeScratch]
) -> TableFrame:
    """Shared body of decode_frame/decode_burst: views into the payload,
    copied into pooled (scratch) or fresh destination arrays, with the
    non-finite-scale corruption guard applied IN PLACE on the copy."""
    k = spec.num_leaves
    w = spec.total // 32
    scales_v = np.frombuffer(payload, "<f4", count=k, offset=off)
    words_v = np.frombuffer(payload, "<u4", count=w, offset=off + 4 * k)
    # COPIES, not views (alignment + lifetime: see decode_frame docstring);
    # the scratch pool makes the steady-state copy land in recycled arrays
    if scratch is not None:
        scales, words = scratch.frame()
        np.copyto(scales, scales_v)
        np.copyto(words, words_v)
    else:
        scales, words = scales_v.copy(), words_v.copy()
    bad = ~np.isfinite(scales)
    if bad.any():
        nbad = int(np.count_nonzero(bad))
        log.warning(
            "zeroing %d non-finite scale(s) in received frame (corrupt link?)",
            nbad,
        )
        _count_corrupt_scales(nbad)
        scales[bad] = np.float32(0.0)
    return TableFrame(scales, words)


def decode_burst(
    payload: bytes, spec: TableSpec, scratch: Optional[DecodeScratch] = None
) -> list[TableFrame]:
    """Inverse of :func:`encode_burst`, with the same per-frame corruption
    guard as decode_frame (non-finite scales zeroed)."""
    if len(payload) < BURST_HDR:
        raise ValueError(f"BURST message of {len(payload)} bytes has no header")
    per = frame_payload_bytes(spec)
    if payload[1] > 0 and len(payload) == HDR_V3 + payload[1] * per:
        # r14 aligned framing: k lives at byte 1 (checked FIRST — byte 5
        # is mid-seq here, so the v1/v2 k_frames read below would be
        # garbage for a v3 message)
        k_frames = payload[1]
        hdr = HDR_V3
        return [
            _decode_one_frame(payload, hdr + i * per, spec, scratch)
            for i in range(k_frames)
        ]
    k_frames = payload[BURST_HDR - 1]
    if k_frames == 0:
        # encode_burst never emits k=0; accepting one would ACK a message
        # that delivered nothing (a frame-less BURST is corruption)
        raise ValueError("BURST with k_frames == 0")
    # v1 or v2 framing by exact length (see decode_frame)
    if len(payload) == BURST_HDR + k_frames * per:
        hdr = BURST_HDR
    elif len(payload) == BURST_HDR_T + k_frames * per:
        hdr = BURST_HDR_T
    else:
        raise ValueError(
            f"BURST of {k_frames} frames is {len(payload)} bytes, layout "
            f"wants {BURST_HDR + k_frames * per} or "
            f"{BURST_HDR_T + k_frames * per} — peer table layout mismatch"
        )
    return [
        _decode_one_frame(payload, hdr + i * per, spec, scratch)
        for i in range(k_frames)
    ]


def encode_sync(
    spec: TableSpec,
    wire_version: int = 1,
    flags: int = 0,
    shm_host: bytes = b"",
    shard: int = -1,
) -> bytes:
    """Join request header. Since r09 a trailing version byte advertises
    the joiner's DATA/BURST framing (compat.WIRE_VERSION); pre-r09 parents
    decode with unpack_from and ignore the trailing byte, so the SYNC
    stays backward-compatible — and decoders here tolerate both emitted
    framings regardless (the byte is informational, surfaced through
    sync_wire_version for logging/telemetry).

    ``flags`` (r10, one more trailing byte — same tolerant-extension
    discipline) advertises handshake capabilities: compat.SYNC_FLAG_*
    (read-only subscriber, range subscription to follow). Pre-r10 parents
    ignore it; pre-r10 SYNCs read back as flags 0.

    ``shm_host`` (r14, 16 trailing bytes present iff flags carries
    compat.SYNC_FLAG_SHM): the joiner's host identity (Linux boot id) for
    the same-host shared-memory lane negotiation. A parent on the same
    host answers with a segment offer in its WELCOME tail
    (:func:`encode_welcome`); any other parent — pre-r14 included — just
    ignores the bytes and the link stays on TCP.

    ``shard`` (r16, 2 trailing bytes present iff flags carries
    compat.SYNC_FLAG_SHARD, AFTER the shm tail): the joiner's shard-index
    claim for the cluster-sharded tensor (0xFFFF = a member that owns no
    shard — a pure writer/relay). A pre-r16 parent ignores the tail
    entirely and attaches the joiner as a plain writer child; the joiner
    detects the legacy parent by the absent WELCOME shard flag and falls
    back to today's full-replica protocol (shard/node.py)."""
    return (
        bytes([SYNC])
        + struct.pack(
            _SYNC_FMT, spec.num_leaves, spec.total_n, spec.layout_digest()
        )
        + bytes([wire_version & 0xFF, flags & 0xFF])
        + (shm_host[:16] if flags & SHM_FLAG else b"")
        + (
            struct.pack("<H", shard & 0xFFFF)
            if flags & SHARD_FLAG
            else b""
        )
    )


def decode_sync(payload: bytes) -> tuple[int, int, bytes]:
    return struct.unpack_from(_SYNC_FMT, payload, 1)


def sync_wire_version(payload: bytes) -> int:
    """The joiner's advertised DATA/BURST framing version (1 when absent —
    a pre-r09 SYNC has no version byte)."""
    base = 1 + struct.calcsize(_SYNC_FMT)
    return payload[base] if len(payload) > base else 1


def sync_flags(payload: bytes) -> int:
    """The joiner's advertised handshake-capability flags (r10 trailing
    byte; compat.SYNC_FLAG_*). 0 when absent — every pre-r10 joiner is a
    read-write peer with no range subscription."""
    base = 2 + struct.calcsize(_SYNC_FMT)
    return payload[base] if len(payload) > base else 0


def sync_shm_host(payload: bytes) -> Optional[bytes]:
    """The joiner's 16-byte host identity (r14 shm-lane negotiation), or
    None when the SYNC predates r14 / the joiner did not advertise
    compat.SYNC_FLAG_SHM."""
    if not sync_flags(payload) & SHM_FLAG:
        return None
    base = 3 + struct.calcsize(_SYNC_FMT)
    return bytes(payload[base : base + 16]) if len(payload) >= base + 16 \
        else None


def sync_shard(payload: bytes) -> Optional[int]:
    """The joiner's shard-index claim (r16), or None when the SYNC carries
    no compat.SYNC_FLAG_SHARD / the tail is truncated. 0xFFFF decodes to
    -1 (a member that owns no shard). The tail sits AFTER the optional
    16-byte shm host identity."""
    flags = sync_flags(payload)
    if not flags & SHARD_FLAG:
        return None
    base = 3 + struct.calcsize(_SYNC_FMT) + (16 if flags & SHM_FLAG else 0)
    if len(payload) < base + 2:
        return None
    (idx,) = struct.unpack_from("<H", payload, base)
    return -1 if idx == 0xFFFF else idx


def encode_welcome(flags: int = 0, shm_offer=None) -> bytes:
    """WELCOME with an r11 trailing capability-flags byte (same tolerant-
    extension discipline as the SYNC version/flags bytes: every receiver
    has always dispatched WELCOME on the kind byte alone, so pre-r11 peers
    ignore the tail and a pre-r11 parent's bare 1-byte WELCOME reads back
    as flags 0). Carries the PARENT-side capability advertisement —
    compat.SYNC_FLAG_SIGN2 (the child's uplink may upshift to the 2-bit
    codec) and, r14, compat.SYNC_FLAG_SHM with a same-host shared-memory
    segment offer in the tail.

    ``shm_offer`` (present iff flags carries compat.SYNC_FLAG_SHM) is
    ``(host_id16, token, name)``: the parent's host identity, the
    segment's validation token and its /dev/shm basename. Pre-r14
    children ignore the tail entirely — the link then stays on TCP, which
    is exactly the mixed-tree contract."""
    out = bytes([WELCOME, flags & 0xFF])
    if flags & SHM_FLAG and shm_offer is not None:
        host, token, name = shm_offer
        nb = name.encode()
        out += (
            host[:16].ljust(16, b"\0")
            + struct.pack("<Q", token & 0xFFFFFFFFFFFFFFFF)
            + bytes([len(nb) & 0xFF])
            + nb
        )
    return out


def welcome_flags(payload: bytes) -> int:
    """The parent's advertised capability flags (0 for a pre-r11 bare
    WELCOME)."""
    return payload[1] if len(payload) > 1 else 0


def welcome_shm(payload: bytes) -> Optional[tuple]:
    """The parent's shm segment offer ``(host_id16, token, name)`` from a
    WELCOME tail, or None when absent/truncated (the link stays on TCP)."""
    if not welcome_flags(payload) & SHM_FLAG or len(payload) < 2 + 16 + 8 + 1:
        return None
    host = bytes(payload[2:18])
    (token,) = struct.unpack_from("<Q", payload, 18)
    nlen = payload[26]
    if len(payload) < 27 + nlen:
        return None
    return host, token, payload[27 : 27 + nlen].decode(errors="replace")


# -- r10 serving-tier messages ----------------------------------------------
#
# RANGE: [kind][u32 word_lo][u32 word_cnt] — a subscriber's page-range
# subscription (32-element words of the flat table), sent between SYNC and
# DONE. The parent then forwards only those words per frame (RDATA framing)
# so the subscriber receives — and buffers — only its pages.
#
# FRESH: [kind][u64 t_ns][u32 last_seq] — the parent's CLOCK_MONOTONIC at
# an instant when the subscriber link's residual had fully drained ("as of
# t you have everything I have") plus the link's last data tx_seq at that
# instant. The seq makes the mark VERIFIABLE on the unledgered link: a
# subscriber accepts it only when it has applied exactly last_seq messages
# — otherwise the tail of the stream was swallowed (undetectable from
# data alone on an idle tree: no next message ever exposes the gap) and
# the mark must trigger a resync instead of falsely verifying freshness
# over diverged state. Same-host-monotonic semantics, like the r09 origin
# stamps (obs/schema.py st_staleness_seconds caveat).
#
# RDATA: [kind][u32 seq][u32 word_lo][u32 word_cnt][trace?][scales L*4]
# [words word_cnt*4] — ONE codec frame sliced to the subscribed word range.
# The range header sits BEFORE the optional 13-byte trace context so the
# fixed fields parse at fixed offsets; v1/v2 framing disambiguates by exact
# length exactly like DATA/BURST (the body is a multiple of 4, the trace
# adds 13). Unledgered by design: subscriber links have no ACK ledger —
# the subscriber detects loss by seq gap and re-seeds via a fresh SYNC/DONE
# handshake on the same link (serve/subscriber.py).

_RANGE_FMT = "<II"
_FRESH_FMT = "<QI"
RDATA_HDR = 13  # kind + u32 seq + u32 word_lo + u32 word_cnt
RDATA_HDR_T = RDATA_HDR + TRACE_BYTES  # 26


def encode_range(word_lo: int, word_cnt: int) -> bytes:
    return bytes([RANGE]) + struct.pack(_RANGE_FMT, word_lo, word_cnt)


def decode_range(payload: bytes) -> tuple[int, int]:
    return struct.unpack_from(_RANGE_FMT, payload, 1)


def encode_fresh(t_ns: int, last_seq: int) -> bytes:
    return bytes([FRESH]) + struct.pack(
        _FRESH_FMT, t_ns & 0xFFFFFFFFFFFFFFFF, last_seq & 0xFFFFFFFF
    )


def decode_fresh(payload: bytes) -> tuple[int, int]:
    """(t_ns, last_seq) — see the FRESH format note above."""
    return struct.unpack_from(_FRESH_FMT, payload, 1)


def encode_rdata(
    frame: TableFrame, word_lo: int, word_cnt: int, seq: int, trace=None
) -> bytes:
    """One frame's scales + the [word_lo, word_lo+word_cnt) slice of its
    sign words — the range-filtered forwarding unit for paged
    subscriptions. Scales ship whole (4L bytes — per-leaf metadata, small);
    only the word payload is sliced."""
    scales = np.asarray(frame.scales, dtype="<f4")
    words = np.asarray(frame.words, dtype="<u4")[word_lo : word_lo + word_cnt]
    if len(words) != word_cnt:
        raise ValueError(
            f"range [{word_lo}, {word_lo + word_cnt}) overruns the "
            f"{np.asarray(frame.words).size}-word frame"
        )
    th = b"" if trace is None else struct.pack(_TRACE_FMT, *_clamp_trace(trace))
    return (
        bytes([RDATA])
        + struct.pack("<I", seq & 0xFFFFFFFF)
        + struct.pack(_RANGE_FMT, word_lo, word_cnt)
        + th
        + scales.tobytes()
        + words.tobytes()
    )


def decode_rdata(
    payload: bytes, spec: TableSpec
) -> tuple[np.ndarray, np.ndarray, int, int, Optional[tuple[int, int, int]]]:
    """Inverse of :func:`encode_rdata`. Returns (scales f32[L], words
    u32[word_cnt], word_lo, word_cnt, trace-or-None) — with the same
    non-finite-scale corruption guard as decode_frame (a poisoned scale
    zeroes its leaf instead of NaN-ing the serving replica)."""
    k = spec.num_leaves
    word_lo, word_cnt = struct.unpack_from(_RANGE_FMT, payload, 5)
    if word_cnt <= 0 or word_lo + word_cnt > spec.total // 32:
        raise ValueError(
            f"RDATA range [{word_lo}, {word_lo + word_cnt}) outside the "
            f"{spec.total // 32}-word table"
        )
    body = 4 * k + 4 * word_cnt
    if len(payload) == RDATA_HDR + body:
        off, trace = RDATA_HDR, None
    elif len(payload) == RDATA_HDR_T + body:
        off = RDATA_HDR_T
        trace = struct.unpack_from(_TRACE_FMT, payload, RDATA_HDR)
    else:
        raise ValueError(
            f"RDATA is {len(payload)} bytes, range header wants "
            f"{RDATA_HDR + body} or {RDATA_HDR_T + body}"
        )
    scales = np.frombuffer(payload, "<f4", count=k, offset=off).copy()
    words = np.frombuffer(
        payload, "<u4", count=word_cnt, offset=off + 4 * k
    ).copy()
    bad = ~np.isfinite(scales)
    if bad.any():
        nbad = int(np.count_nonzero(bad))
        log.warning(
            "zeroing %d non-finite scale(s) in received RDATA (corrupt link?)",
            nbad,
        )
        _count_corrupt_scales(nbad)
        scales[bad] = np.float32(0.0)
    return scales, words, word_lo, word_cnt, trace




# -- r16 cluster-sharded tensor messages -------------------------------------
#
# FWD: [kind][u32 link_seq][u32 word_lo][u32 word_cnt][u32 origin]
# [u32 fwd_seq][k x (scales L*4 || words word_cnt*4)] — k codec frames of
# a writer's OUT-OF-SHARD delta, sliced to the target shard's word range
# and routed hop-by-hop toward the shard's owner (shard/node.py routes by
# word_lo through the shard map). Each frame is the RDATA representation
# (full-L per-leaf scales + the word slice); successive frames are
# successive HALVINGS of the sender's outbox residual (the r07 burst /
# r11 cascade insight carried over: the ladder's length is fixed by the
# codec arithmetic regardless of pacing — see the FWD_BURST_FRAMES note,
# it is THOUSANDS of steps — so shipping up to fwd_frames_cap halvings
# per message divides the message count, and with it the go-back-N round
# trips a lossy hop must win, by k). k is
# derived from the message length (the header carries word_cnt, so the
# per-frame size is fixed); one message is ONE ledger entry / ONE
# end-to-end identity however many frames it carries. The extra
# origin/fwd_seq pair is that identity:
#
# - link_seq is the per-link go-back-N seq, shared with every other
#   ledgered kind on the link (in-order accept + cumulative wire.ACK +
#   byte-identical retransmission, exactly the DATA/BURST discipline);
#   a relay RE-STAMPS it per outgoing link (struct.pack_into at offset 1)
#   while the rest of the message is forwarded verbatim — owner-routed
#   forwarding never re-quantizes;
# - (origin, fwd_seq) never changes in flight. The owner deduplicates on
#   it: when a link dies, every unacked FWD re-routes and is re-sent
#   byte-identical (same identity), so a message that was actually
#   delivered before the death — the classic at-least-once window — is
#   discarded by the owner's seen-set instead of double-applied. Rolling
#   the quantized mass back into the outbox instead would re-mint it
#   under a NEW identity and double-apply through the same window.
#
# Wire size: the sender caps k with fwd_frames_cap(spec, word_cnt), which
# keeps FWD_HDR + k frames inside frame_wire_bytes(spec) — the receive
# bound every sharded peer passes to its transport — so no sizing change
# for any receiver; decode_fwd re-derives k from the message length and
# rejects anything past the FWD_BURST_FRAMES ceiling.
#
# SHARD: [kind][JSON] — the shard-map control plane (claims/grants, owner
# route announces, map updates, drain-handoff state transfer), bounded by
# DIGEST_MAX_BYTES like every JSON control kind since r09.

_FWD_FMT = "<IIIII"  # link_seq, word_lo, word_cnt, origin, fwd_seq
FWD_HDR = 21  # kind + the five u32 fields above
#: Hard ceiling on halving frames per FWD message, shared with the BURST
#: plane; the ACTUAL cap for a shard geometry comes from fwd_frames_cap
#: below (the same budget-vs-receive-bound derivation as
#: burst_frames_cap). The drain ladder of the rms-scaled sign codec is
#: LONG — heavy-tailed residuals step down linearly at the rms scale, so
#: a fresh outbox takes a few THOUSAND halvings, not ~log2(mass/dust) —
#: and each message is one ledgered go-back-N entry, so the frames-per-
#: message cap directly divides the round trips a lossy hop must win
#: (the r07 burst insight; a 16-frame cap measured ~500 messages per
#: outbox drain where 255 takes ~11).
FWD_BURST_FRAMES = BURST_MAX_FRAMES


def fwd_frames_cap(spec: TableSpec, word_cnt: int) -> int:
    """Most halving frames one FWD message may carry for a shard of
    ``word_cnt`` words (>= 1): sized so FWD_HDR + k frames stays inside
    frame_wire_bytes(spec) — the bound every sharded peer passes to its
    transport — like burst_frames_cap sizes BURST against its budget."""
    per = 4 * spec.num_leaves + 4 * word_cnt
    return max(
        1,
        min(FWD_BURST_FRAMES, (frame_wire_bytes(spec) - FWD_HDR) // per),
    )


def encode_fwd(
    frames: list,
    word_lo: int,
    seq: int,
    origin: int,
    fwd_seq: int,
) -> bytes:
    """``frames`` is 1..FWD_BURST_FRAMES (scales f32[L], words
    u32[word_cnt]) pairs — successive halvings of one outbox residual,
    already sliced to the target shard's range by the outbox codec."""
    if not 1 <= len(frames) <= FWD_BURST_FRAMES:
        raise ValueError(
            f"FWD burst of {len(frames)} frames (allowed 1.."
            f"{FWD_BURST_FRAMES})"
        )
    word_cnt = len(frames[0][1])
    parts = [
        bytes([FWD])
        + struct.pack(
            _FWD_FMT,
            seq & 0xFFFFFFFF,
            word_lo & 0xFFFFFFFF,
            word_cnt & 0xFFFFFFFF,
            origin & 0xFFFFFFFF,
            fwd_seq & 0xFFFFFFFF,
        )
    ]
    for scales, words in frames:
        if len(words) != word_cnt:
            raise ValueError("FWD burst frames must share one word range")
        parts.append(np.asarray(scales, dtype="<f4").tobytes())
        parts.append(np.asarray(words, dtype="<u4").tobytes())
    return b"".join(parts)


def fwd_restamp(payload: bytearray, seq: int) -> None:
    """Re-stamp a FWD's per-link seq for the next hop IN PLACE (relay /
    re-route path) — everything after byte 5 is forwarded verbatim."""
    struct.pack_into("<I", payload, 1, seq & 0xFFFFFFFF)


def decode_fwd(
    payload: bytes, spec: TableSpec
) -> tuple[list, int, int, int, int]:
    """([(scales f32[L], words u32[word_cnt]), ...], word_lo, link_seq,
    origin, fwd_seq) — frame count derived from the message length; the
    same non-finite-scale corruption guard as decode_frame/decode_rdata
    applies per frame (a poisoned scale zeroes its leaf instead of
    NaN-ing the owner's shard)."""
    L = spec.num_leaves
    seq, word_lo, word_cnt, origin, fwd_seq = struct.unpack_from(
        _FWD_FMT, payload, 1
    )
    if word_cnt <= 0 or word_lo + word_cnt > spec.total // 32:
        raise ValueError(
            f"FWD range [{word_lo}, {word_lo + word_cnt}) outside the "
            f"{spec.total // 32}-word table"
        )
    per = 4 * L + 4 * word_cnt
    body = len(payload) - FWD_HDR
    nf, rem = divmod(body, per)
    if rem or not 1 <= nf <= FWD_BURST_FRAMES:
        raise ValueError(
            f"FWD is {len(payload)} bytes: not 1..{FWD_BURST_FRAMES} "
            f"whole {per}-byte frames past the {FWD_HDR}-byte header"
        )
    frames = []
    for i in range(nf):
        off = FWD_HDR + i * per
        scales = np.frombuffer(payload, "<f4", count=L, offset=off).copy()
        words = np.frombuffer(
            payload, "<u4", count=word_cnt, offset=off + 4 * L
        ).copy()
        bad = ~np.isfinite(scales)
        if bad.any():
            nbad = int(np.count_nonzero(bad))
            log.warning(
                "zeroing %d non-finite scale(s) in received FWD "
                "(corrupt link?)", nbad,
            )
            _count_corrupt_scales(nbad)
            scales[bad] = np.float32(0.0)
        frames.append((scales, words))
    return frames, word_lo, seq, origin, fwd_seq


def encode_shard(doc: dict) -> bytes:
    """One shard-map control message ({"t": "claim"|"grant"|"deny"|"map"|
    "own"|"ho_meta"|"ho_state"|"ho_ack", ...} — shard/node.py owns the
    document shapes). JSON for the same reason as DIGEST/lifecycle: this
    is off-hot-path control traffic whose debuggability matters more than
    bytes; the DIGEST_MAX_BYTES cap keeps every peer's receive bound
    valid (handoff state transfer chunks itself under it)."""
    import json

    body = json.dumps(doc, separators=(",", ":")).encode()
    if len(body) > DIGEST_MAX_BYTES:
        raise ValueError(
            f"shard control message is {len(body)} bytes, cap "
            f"{DIGEST_MAX_BYTES} — chunk handoff state / bound the map"
        )
    return bytes([SHARD]) + body


def decode_shard(payload: bytes) -> dict:
    import json

    doc = json.loads(payload[1:].decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("shard control message body is not a JSON object")
    return doc


def encode_snapshot_chunks(flat: np.ndarray) -> Iterator[bytes]:
    """Chunk a flat f32 replica snapshot into CHUNK messages + final DONE."""
    raw = np.asarray(flat, dtype="<f4").tobytes()
    for off in range(0, len(raw), CHUNK_BYTES):
        yield (
            bytes([CHUNK])
            + struct.pack(_CHUNK_HDR, off)
            + raw[off : off + CHUNK_BYTES]
        )
    yield bytes([DONE])


def decode_chunk_into(payload: bytes, buf: bytearray) -> None:
    (off,) = struct.unpack_from(_CHUNK_HDR, payload, 1)
    body = payload[1 + struct.calcsize(_CHUNK_HDR) :]
    if off + len(body) > len(buf):
        raise ValueError(
            f"snapshot chunk [{off}:{off + len(body)}] overruns "
            f"{len(buf)}-byte snapshot buffer"
        )
    buf[off : off + len(body)] = body


def encode_ack(count: int) -> bytes:
    """Receiver -> sender: cumulative DATA frames received on this link.

    Delivery acknowledgement drives the sender's in-flight ledger
    (core.SharedTensor): a frame's error feedback is only forgotten once the
    peer confirms receipt, so a link death rolls back exactly the undelivered
    tail into the carry residual (at-least-once delivery — see
    core.begin_frame). The reference has no delivery concept at all: its
    sender's residual update IS the send (src/sharedtensor.c:166-177), and
    any socket error kills the process anyway (quirk Q8)."""
    return bytes([ACK]) + struct.pack("<Q", count)


def decode_ack(payload: bytes) -> int:
    (count,) = struct.unpack_from("<Q", payload, 1)
    return count


def encode_digest(doc: dict) -> bytes:
    """Child -> parent: one bounded cluster-metrics digest (r09 in-band
    aggregation; obs/aggregate.py owns the document shape and the merge
    semantics). JSON keeps the control plane debuggable — this is
    off-hot-path traffic, one message per digest interval per link."""
    import json

    body = json.dumps(doc, separators=(",", ":")).encode()
    if len(body) > DIGEST_MAX_BYTES:
        raise ValueError(
            f"digest is {len(body)} bytes, cap {DIGEST_MAX_BYTES} — "
            f"aggregate.py must truncate before encoding"
        )
    return bytes([DIGEST]) + body


def decode_digest(payload: bytes) -> dict:
    import json

    doc = json.loads(payload[1:].decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("digest body is not a JSON object")
    return doc


def encode_lifecycle(kind: int, doc: dict) -> bytes:
    """One r12 lifecycle control message (SNAP / SNAP_ACK / RESUME / CTL):
    kind byte + a bounded JSON body. JSON for the same reason as DIGEST —
    off-hot-path operator traffic whose debuggability matters more than
    bytes. The DIGEST_MAX_BYTES cap keeps every peer's receive bound
    (frame_wire_bytes) valid; a SNAP_ACK whose subtree manifest exceeds it
    means a cluster past the digest's own per-node bound — raise rather
    than truncate (a silently partial manifest would verify as complete)."""
    import json

    if kind not in (SNAP, SNAP_ACK, RESUME, CTL):
        raise ValueError(f"{kind} is not a lifecycle message kind")
    body = json.dumps(doc, separators=(",", ":")).encode()
    if len(body) > DIGEST_MAX_BYTES:
        raise ValueError(
            f"lifecycle message is {len(body)} bytes, cap {DIGEST_MAX_BYTES}"
        )
    return bytes([kind]) + body


def decode_lifecycle(payload: bytes) -> dict:
    import json

    doc = json.loads(payload[1:].decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("lifecycle message body is not a JSON object")
    return doc


def encode_clock(doc: dict) -> bytes:
    """One r18 clock-offset control message (probe or reply — obs/clock.py
    owns the four-stamp payload shape): kind byte + bounded JSON body,
    the lifecycle pattern. Tiny in practice (~100 bytes); the shared
    DIGEST_MAX_BYTES cap keeps the receive bound uniform."""
    import json

    body = json.dumps(doc, separators=(",", ":")).encode()
    if len(body) > DIGEST_MAX_BYTES:
        raise ValueError(
            f"clock message is {len(body)} bytes, cap {DIGEST_MAX_BYTES}"
        )
    return bytes([CLOCK]) + body


def decode_clock(payload: bytes) -> dict:
    import json

    doc = json.loads(payload[1:].decode("utf-8"))
    if not isinstance(doc, dict):
        raise ValueError("clock message body is not a JSON object")
    return doc


def encode_reject(reason: str) -> bytes:
    return bytes([REJECT]) + reason.encode("utf-8", "replace")


def decode_reject(payload: bytes) -> str:
    return payload[1:].decode("utf-8", "replace")


# -- wire-compat mode (reference frame format, single flat tensor) ----------


def compat_frame_bytes(n: int) -> int:
    """4-byte f32 scale + ceil(n/8)-byte LSB-first bitmask
    (reference src/sharedtensor.c:121-122, :176-177)."""
    return 4 + (n + 7) // 8


def encode_compat_frame(frame: TableFrame, spec: TableSpec) -> bytes:
    """Reference frame bytes. Requires a single-leaf spec (the reference
    syncs exactly one flat tensor per port, README.md:26). Our u32 LSB-first
    packing laid out little-endian is byte-identical to the reference's
    ``data[i/8] |= 1 << (i%8)`` byte packing, so this is a slice, not a
    re-pack."""
    if spec.num_leaves != 1:
        raise ValueError("wire-compat mode syncs a single tensor, not a table")
    scale = float(np.asarray(frame.scales).reshape(-1)[0])
    mask = np.asarray(frame.words, dtype="<u4").tobytes()
    return struct.pack("<f", scale) + mask[: compat_frame_bytes(spec.total_n) - 4]


def decode_compat_frame(payload: bytes, spec: TableSpec) -> Optional[TableFrame]:
    """Reference frame bytes -> TableFrame. Returns None for a frame that
    must not be applied: a pure keepalive (scale == 0 — the reference sends
    one idle frame/s, quirk Q2; it carries no information, so we skip the
    device work) or a corrupt frame (non-finite / absurd scale, which would
    poison the replica — quirk Q9; see decode_frame's corruption guard)."""
    if len(payload) != compat_frame_bytes(spec.total_n):
        raise ValueError(
            f"compat frame is {len(payload)} bytes, "
            f"expected {compat_frame_bytes(spec.total_n)}"
        )
    (scale,) = struct.unpack_from("<f", payload, 0)
    if scale == 0.0 or not np.isfinite(scale):
        if not np.isfinite(scale):
            # corrupt, not idle: don't poison the replica (Q9; see
            # decode_frame's corruption guard)
            log.warning("dropping compat frame with non-finite scale")
            _count_corrupt_scales(1)
        return None
    nwords = spec.total // 32
    raw = payload[4:].ljust(nwords * 4, b"\x00")
    words = np.frombuffer(raw, "<u4", count=nwords)
    return TableFrame(
        np.full((1,), scale, np.float32), np.ascontiguousarray(words)
    )
