"""ctypes wrapper over the native steady-state link engine (stengine.cpp).

:class:`EngineTensor` is a drop-in for the subset of
:class:`~shared_tensor_tpu.core.SharedTensor` the peer needs once the
steady-state data path moves into C: the replica and per-link residuals live
in the engine's own buffers, the codec/wire/ACK cycle runs in two C threads,
and Python keeps handshake, membership, checkpoint and metrics. Activated by
the peer for host-tier, native-protocol nodes (the production CPU path);
the Python/numpy tier stays both the fallback and the semantic reference —
stengine.cpp calls the exact same stcodec.c loops, so the two tiers are
bit-identical given the same message sequence.

Why this exists (round-3 verdict item 2): the Python engine costs ~3 ms of
interpreter work per wire message, capping 4 Ki tables at ~300 messages/s
against the reference C loop's 78 k frames/s (reference
src/sharedtensor.c:133-189 — zero interpreter cost per frame).
"""

from __future__ import annotations

import ctypes
import os
from typing import Any, Optional

import numpy as np

from .. import _build
from ..config import CodecConfig, ScalePolicy
from ..core import DuplicateLink
from ..ops.table import TableFrame, TableSpec, make_spec

_LIB: Optional[ctypes.CDLL] = None
_LIB_TRIED = False

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C,ALIGNED")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C,ALIGNED")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C,ALIGNED")
_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C,ALIGNED")
_i32p = np.ctypeslib.ndpointer(np.int32, flags="C,ALIGNED")

_POLICY_CODE = {ScalePolicy.POW2_RMS: 0, ScalePolicy.RMS: 1, ScalePolicy.ABS_MEAN: 2}


def load_engine() -> Optional[ctypes.CDLL]:
    """Build-and-load libstengine.so; None when unavailable (no toolchain)."""
    global _LIB, _LIB_TRIED
    if _LIB is not None or _LIB_TRIED:
        return _LIB
    _LIB_TRIED = True
    try:
        _build.run_make()  # engine links the transport + codec .so's
        lib = ctypes.CDLL(str(_build.NATIVE_DIR / "libstengine.so"))
        lib.st_engine_create.restype = ctypes.c_void_p
        lib.st_engine_create.argtypes = [
            ctypes.c_void_p, _i64p, _i64p, _i64p,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p,  # init values (nullable -> void_p)
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32,  # compat_frame_bytes (0 = native framing)
            ctypes.c_int32,  # quarantine_send_failures (0 = disabled)
            ctypes.c_double,  # ack_timeout_sec (go-back-N; 0 = disabled)
            ctypes.c_int32,  # ack_retry_limit (rounds before teardown)
            ctypes.c_int32,  # trace_wire (r09 v2 framing; 0 = v1 emission)
        ]
        lib.st_engine_link_obs.restype = ctypes.c_int32
        lib.st_engine_link_obs.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, _u64p,
        ]
        # r10 subscriber link mode: unledgered + optionally range-filtered
        lib.st_engine_attach_sub.restype = ctypes.c_int32
        lib.st_engine_attach_sub.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p,  # snapshot (nullable)
            ctypes.c_uint64,  # rx_init
            ctypes.c_int64, ctypes.c_int64,  # word_lo, word_cnt
            ctypes.c_double,  # fresh_interval_sec
        ]
        lib.st_engine_compat_regraft.restype = ctypes.c_int32
        lib.st_engine_compat_regraft.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
        ]
        # r11 adaptive precision + cascade quantize (set between create
        # and start; see stengine.cpp st_engine_set_codec)
        lib.st_engine_set_codec.restype = None
        lib.st_engine_set_codec.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_double,
            ctypes.c_double, ctypes.c_double, ctypes.c_int32,
        ]
        lib.st_engine_link_allow_sign2.restype = ctypes.c_int32
        lib.st_engine_link_allow_sign2.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ]
        lib.st_engine_link_wire_v3.restype = ctypes.c_int32
        lib.st_engine_link_wire_v3.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32,
        ]
        lib.st_engine_link_precision.restype = ctypes.c_int32
        lib.st_engine_link_precision.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.st_engine_start.restype = None
        lib.st_engine_start.argtypes = [ctypes.c_void_p]
        lib.st_engine_seal.restype = None
        lib.st_engine_seal.argtypes = [ctypes.c_void_p]
        lib.st_engine_stash_carry.restype = ctypes.c_int32
        lib.st_engine_stash_carry.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.st_engine_take_carry_and_snapshot.restype = ctypes.c_int32
        lib.st_engine_take_carry_and_snapshot.argtypes = [
            # both out pointers nullable (drop_carry) -> void_p
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        lib.st_engine_stop.restype = None
        lib.st_engine_stop.argtypes = [ctypes.c_void_p]
        lib.st_engine_destroy.restype = None
        lib.st_engine_destroy.argtypes = [ctypes.c_void_p]
        lib.st_engine_add.restype = None
        lib.st_engine_add.argtypes = [ctypes.c_void_p, _f32p]
        lib.st_engine_read.restype = None
        lib.st_engine_read.argtypes = [ctypes.c_void_p, _f32p]
        lib.st_engine_attach.restype = ctypes.c_int32
        lib.st_engine_attach.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_uint64,
        ]
        lib.st_engine_detach.restype = ctypes.c_int32
        lib.st_engine_detach.argtypes = [ctypes.c_void_p, ctypes.c_int32, _f32p]
        lib.st_engine_inject.restype = None
        lib.st_engine_inject.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, _f32p, _u32p,
        ]
        lib.st_engine_links.restype = ctypes.c_int32
        lib.st_engine_links.argtypes = [ctypes.c_void_p, _i32p, ctypes.c_int32]
        lib.st_engine_residual_rms.restype = ctypes.c_double
        lib.st_engine_residual_rms.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.st_engine_inflight.restype = ctypes.c_int64
        lib.st_engine_inflight.argtypes = [ctypes.c_void_p]
        lib.st_engine_counters.restype = None
        lib.st_engine_counters.argtypes = [ctypes.c_void_p, _u64p]
        lib.st_engine_poll_ctrl.restype = ctypes.c_int32
        lib.st_engine_poll_ctrl.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p, ctypes.c_int32,
        ]
        lib.st_engine_snapshot_all.restype = ctypes.c_int32
        lib.st_engine_snapshot_all.argtypes = [
            ctypes.c_void_p, _f32p, _i32p, _f32p, ctypes.c_int32,
        ]
        lib.st_engine_restore.restype = None
        lib.st_engine_restore.argtypes = [
            ctypes.c_void_p, _f32p, ctypes.c_int32, _i32p, _f32p,
        ]
        # r12 lifecycle: quiesce + the extended checkpoint ABI (per-link
        # tx/rx wire seqs, precision + governor state alongside residuals)
        lib.st_engine_pause.restype = None
        lib.st_engine_pause.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.st_engine_snapshot_ex.restype = ctypes.c_int32
        lib.st_engine_snapshot_ex.argtypes = [
            ctypes.c_void_p, _f32p, _i32p, _f32p, _u64p, ctypes.c_int32,
        ]
        lib.st_engine_restore_ex.restype = None
        lib.st_engine_restore_ex.argtypes = [
            ctypes.c_void_p, _f32p, ctypes.c_int32, _i32p, _f32p,
            ctypes.c_void_p,  # aux (nullable -> void_p)
        ]
        _LIB = lib
    except Exception:
        _LIB = None
    return _LIB


def engine_eligible(config) -> bool:
    """Should the peer run the native engine for this node? Host tier,
    zero-frame suppression on (the engine has no idle-frame path —
    transport keepalives carry liveness, and in wire-compat mode the
    transport's idle zero-scale frames do), engine lib available, and not
    explicitly disabled (ST_NATIVE_ENGINE=0 or Config.native_engine). Both
    wire protocols are engine-capable: native framing with bursts + the
    ACK ledger, or the reference's raw compat frames (no ACKs, ledgerless
    — see stengine.cpp's compat_bytes)."""
    from ..core import host_tier_active

    if os.environ.get("ST_NATIVE_ENGINE", "1") == "0":
        return False
    if os.environ.get("ST_HOST_CODEC"):
        # an explicit codec-tier pin (numpy parity tests / xla) must reach
        # the pinned tier, not the engine's C loops
        return False
    if not getattr(config, "native_engine", True):
        return False
    if not config.codec.suppress_zero_frames:
        return False
    if config.sync_interval_sec > 0:
        # the native sender free-runs (condvar-paced); explicit frame pacing
        # is a Python-tier feature — honor the knob by falling back
        return False
    if not host_tier_active():
        return False
    return load_engine() is not None


class EngineTensor:
    """SharedTensor-compatible facade over the native engine. All state
    (replica, residuals, ledgers) lives in C; methods here marshal numpy
    views in and out. Thread-safe (the engine's own mutex)."""

    def __init__(
        self,
        template: Any,
        codec: CodecConfig,
        seed_values: bool,
        node,  # TransportNode
        burst: int,
        recv_cap: int,
        compat_frame_bytes: int = 0,  # >0 => reference raw wire protocol
        quarantine_send_failures: int = 0,  # see TransportConfig
        ack_timeout_sec: float = 0.0,  # go-back-N timer; see TransportConfig
        ack_retry_limit: int = 8,  # rounds before black-hole teardown
        trace_wire: bool = True,  # r09 v2 framing (compat.WIRE_VERSION)
        precision_mode: int = 0,  # r11: 0 fixed 1-bit, 1 adaptive, 2 sign2
        precision_up_ratio: float = 1.05,  # governor growth threshold (CodecConfig default)
        precision_down_ratio: float = 0.5,  # governor quiet threshold
        precision_interval_sec: float = 0.1,  # governor beat
        cascade_frames: int = 1,  # r11: frames quantized per memory pass
    ):
        from ..ops.codec_np import _layout, flatten_np

        self.spec: TableSpec = make_spec(template)
        self.codec = codec
        self._lib = load_engine()
        if self._lib is None:
            raise RuntimeError("native engine unavailable")
        self._offs, self._ns, self._padded = _layout(self.spec)
        init = flatten_np(template, self.spec) if seed_values else None
        init_ptr = (
            init.ctypes.data_as(ctypes.c_void_p) if init is not None else None
        )
        self._h = self._lib.st_engine_create(
            node._h,
            self._offs,
            self._ns,
            self._padded,
            self.spec.num_leaves,
            self.spec.total,
            self.spec.total_n,
            init_ptr,
            _POLICY_CODE[codec.scale_policy],
            1 if codec.per_leaf_scale else 0,
            burst,
            recv_cap,
            compat_frame_bytes,
            quarantine_send_failures,
            ack_timeout_sec,
            ack_retry_limit,
            1 if trace_wire else 0,
        )
        if not self._h:
            raise RuntimeError("st_engine_create failed")
        # r11 codec config BEFORE start (the sender thread reads it
        # unlocked; the tx-slot ring re-sizes for the widest sign2 burst)
        self._lib.st_engine_set_codec(
            self._h,
            precision_mode,
            precision_up_ratio,
            precision_down_ratio,
            precision_interval_sec,
            cascade_frames,
        )
        # reused across poll_ctrl calls (a per-call create_string_buffer
        # would zero-fill recv_cap bytes every ~2 ms idle pass); sized to
        # the largest wire message so a deferred CHUNK never truncates
        self._ctrl_buf = ctypes.create_string_buffer(max(recv_cap, 1 << 16))
        self._lib.st_engine_start(self._h)
        self._stopped = False

    # -- lifecycle ----------------------------------------------------------

    def _handle(self):
        """The live native handle, or raise. Every mutating native call
        goes through this: after destroy() the handle is None, and passing
        NULL into the C ABI is how the reference's process-killing failure
        mode (quirk Q8) sneaks back in through this facade — a late call
        must raise a Python error, never SIGSEGV the process (the C entry
        points also NULL-check, as defense in depth)."""
        h = self._h
        if not h:
            raise RuntimeError("EngineTensor used after destroy()")
        return h

    def seal(self) -> None:
        """Graceful-leave step 1: discard (never apply/ACK) further
        incoming DATA/BURST so their senders re-deliver after our
        departure — closes the leave-time in-transit loss window."""
        if self._h:  # sealing a destroyed engine is a no-op, not an error
            self._lib.st_engine_seal(self._h)

    def stop(self) -> None:
        """Stop the engine threads. MUST run before TransportNode.close()
        (the threads block inside the node's queues/condvars)."""
        if not self._stopped and self._h:
            self._stopped = True
            self._lib.st_engine_stop(self._h)

    def destroy(self) -> None:
        self.stop()
        if self._h:
            self._lib.st_engine_destroy(self._h)
            self._h = None

    # -- SharedTensor API subset the peer uses ------------------------------

    @property
    def host_tier(self) -> bool:
        return True

    def _asarray(self, x) -> np.ndarray:
        return np.asarray(x, np.float32)

    def read(self) -> Any:
        from ..ops.codec_np import unflatten_np

        return unflatten_np(self.snapshot_flat(), self.spec)

    def snapshot_flat(self) -> np.ndarray:
        out = np.empty(self.spec.total, np.float32)
        self._lib.st_engine_read(self._handle(), out)
        return out

    def add(self, delta: Any) -> None:
        from ..ops.codec_np import flatten_np

        # copy=False: st_engine_add consumes u synchronously (one pooled
        # accumulate under add_mu), so a single-leaf unpadded f32 delta
        # goes straight through — the zeros+copy flatten was two full
        # table passes per add() on the production throughput path
        u = np.ascontiguousarray(
            flatten_np(delta, self.spec, copy=False), np.float32
        )
        self._lib.st_engine_add(self._handle(), u)

    def new_link(self, link_id: int, seed: bool = True, rx_init: int = 0) -> None:
        """seed=True: residual = full replica (reference join seeding);
        seed=False: zero residual. The peer's explicit-residual variant
        (carry re-graft) goes through new_link_diff instead — the carry is
        folded into the snapshot the child sends (peer._start_join)."""
        r = self._lib.st_engine_attach(
            self._handle(), link_id, None, 1 if seed else 0, rx_init
        )
        if r == 0:
            raise DuplicateLink(f"link {link_id} already exists")

    def new_link_diff(
        self, link_id: int, peer_snapshot: np.ndarray, rx_init: int = 0
    ) -> None:
        snap = np.ascontiguousarray(peer_snapshot, np.float32)
        if snap.shape != (self.spec.total,):
            raise ValueError(
                f"snapshot shape {snap.shape} != ({self.spec.total},)"
            )
        r = self._lib.st_engine_attach(
            self._handle(),
            link_id,
            snap.ctypes.data_as(ctypes.c_void_p),
            0,
            rx_init,
        )
        if r == 0:
            raise DuplicateLink(f"link {link_id} already exists")

    def new_link_sub(
        self,
        link_id: int,
        peer_snapshot: Optional[np.ndarray],
        rx_init: int = 0,
        word_lo: int = 0,
        word_cnt: int = 0,
        fresh_interval_sec: float = 0.0,
    ) -> None:
        """Open a SUBSCRIBER link (r10 serving tier): unledgered — the C
        sender keeps no unacked entries, expects no ACKs and never
        retransmits — and, when ``word_cnt > 0`` names a sub-range,
        range-filtered (kRData framing ships only those words per frame).
        Attach and mode are one atomic native call: a separate mark-after-
        attach would let the sender emit a ledgered message whose missing
        ACK black-holes the link. ``peer_snapshot=None`` seeds the full
        replica (fresh subscriber / resync re-seed)."""
        snap_ptr = None
        if peer_snapshot is not None:
            snap = np.ascontiguousarray(peer_snapshot, np.float32)
            if snap.shape != (self.spec.total,):
                raise ValueError(
                    f"snapshot shape {snap.shape} != ({self.spec.total},)"
                )
            snap_ptr = snap.ctypes.data_as(ctypes.c_void_p)
        r = self._lib.st_engine_attach_sub(
            self._handle(), link_id, snap_ptr, rx_init,
            word_lo, word_cnt, fresh_interval_sec,
        )
        if r == 0:
            raise DuplicateLink(f"link {link_id} already exists")

    def link_allow_sign2(self, link_id: int, allow: bool = True) -> None:
        """r11: record that the peer on this link advertised sign2 (2-bit)
        decode capability (compat.SYNC_FLAG_SIGN2 / WELCOME flags), so the
        adaptive-precision governor may upshift it. Links without the call
        stay 1-bit forever — the mixed-tree safety default."""
        if self._h:
            self._lib.st_engine_link_allow_sign2(
                self._h, link_id, 1 if allow else 0
            )

    def link_wire_v3(self, link_id: int, allow: bool = True) -> None:
        """r14: record that the peer on this link advertised the r14
        capability (the SYNC/WELCOME shm flag), so emission to it may use
        the aligned v3 framing — whose 24-byte header lets the receiver
        apply frames straight from the wire body. Links without the call
        stay on v2, the mixed-tree safety default."""
        if self._h:
            self._lib.st_engine_link_wire_v3(
                self._h, link_id, 1 if allow else 0
            )

    def link_precision(self, link_id: int) -> int:
        """The governor's current wire precision for the link (1 or 2; 0 =
        unknown link / closed engine) — the st_link_precision gauge."""
        if not self._h:
            return 0
        return int(self._lib.st_engine_link_precision(self._h, link_id))

    def stash_carry(self, link_id: int) -> bool:
        """Park a dead uplink's residual in the engine's LIVE carry slot —
        it keeps accumulating add()/flood mass while orphaned (an orphan
        add with no residual to live in would be erased tree-wide by the
        re-graft diff; the reference's unconnected-slot mechanism)."""
        return bool(self._lib.st_engine_stash_carry(self._handle(), link_id))

    def compat_regraft(self, link_id: int) -> None:
        """Wire-compat LEAF re-graft, atomic in C: replica = carry, new
        uplink residual = carry (core.SharedTensor.regraft_reset_to_carry's
        engine analog — see that docstring for why zero would desync)."""
        if self._lib.st_engine_compat_regraft(self._handle(), link_id) == 0:
            raise DuplicateLink(f"link {link_id} already exists")

    def take_carry_and_snapshot(
        self,
    ) -> tuple[Optional[np.ndarray], np.ndarray]:
        """Atomically consume the carry and snapshot the replica (ONE lock:
        an add between the two would land in the snapshot but not the
        carry, re-creating the orphan-add loss)."""
        carry = np.empty(self.spec.total, np.float32)
        values = np.empty(self.spec.total, np.float32)
        has = self._lib.st_engine_take_carry_and_snapshot(
            self._handle(),
            carry.ctypes.data_as(ctypes.c_void_p),
            values.ctypes.data_as(ctypes.c_void_p),
        )
        return (carry if has else None), values

    def drop_carry(self) -> None:
        """Consume the carry WITHOUT snapshotting — the BECAME_MASTER
        failover path: its mass is already in the (now-authoritative)
        replica, and paying two full-table copies just to discard them is
        ~128 MB of transient traffic at a 16 Mi table."""
        self._lib.st_engine_take_carry_and_snapshot(self._handle(), None, None)

    def drop_link(self, link_id: int) -> Optional[np.ndarray]:
        out = np.empty(self.spec.total, np.float32)
        if self._lib.st_engine_detach(self._handle(), link_id, out) == 0:
            return None
        return out

    @property
    def link_ids(self) -> tuple[int, ...]:
        if not self._h:  # post-destroy introspection: empty, never NULL-call
            return ()
        arr = np.empty(64, np.int32)
        n = self._lib.st_engine_links(self._h, arr, 64)
        return tuple(int(x) for x in arr[:n])

    def inflight_total(self) -> int:
        if not self._h:
            return 0
        return int(self._lib.st_engine_inflight(self._h))

    def residual_rms(self, link_id: int) -> float:
        if not self._h:
            return 0.0
        return float(self._lib.st_engine_residual_rms(self._h, link_id))

    def receive_frame(self, link_id: int, frame: TableFrame) -> None:
        """Apply one externally-decoded frame (pre-attach flood-in). RX/ACK
        accounting stays with the caller, exactly like the Python tier."""
        scales = np.ascontiguousarray(frame.scales, np.float32).reshape(-1)
        words = np.ascontiguousarray(frame.words, np.uint32).reshape(-1)
        self._lib.st_engine_inject(self._handle(), link_id, 1, scales, words)

    def receive_frames(self, link_id: int, frames: list[TableFrame]) -> None:
        if not frames:
            return
        scales = np.ascontiguousarray(
            np.concatenate(
                [np.asarray(f.scales, np.float32).reshape(-1) for f in frames]
            )
        )
        words = np.ascontiguousarray(
            np.concatenate(
                [np.asarray(f.words, np.uint32).reshape(-1) for f in frames]
            )
        )
        self._lib.st_engine_inject(
            self._handle(), link_id, len(frames), scales, words
        )

    def snapshot_all(self) -> tuple[np.ndarray, dict[int, np.ndarray]]:
        values = np.empty(self.spec.total, np.float32)
        ids = np.empty(64, np.int32)
        resids = np.empty((64, self.spec.total), np.float32)
        n = self._lib.st_engine_snapshot_all(
            self._handle(), values, ids, resids.reshape(-1), 64
        )
        return values, {int(ids[i]): resids[i].copy() for i in range(n)}

    # -- r12 cluster lifecycle ----------------------------------------------

    def pause(self, paused: bool = True) -> None:
        """Quiesce (or resume) the sender's NEW data production — the
        consistent-cut barrier primitive. In-flight delivery (ACKs,
        go-back-N retransmission) and control traffic keep running, so a
        paused engine drains its ledgers to empty; FRESH beats continue on
        already-drained subscriber links only (st_engine_pause)."""
        if self._h:  # pausing a destroyed engine is a no-op, not an error
            self._lib.st_engine_pause(self._h, 1 if paused else 0)

    def snapshot_ex(
        self,
    ) -> tuple[np.ndarray, dict[int, np.ndarray], dict[int, dict]]:
        """snapshot_all plus each link's lifecycle aux state: ``tx_seq``
        (last DATA/BURST wire seq sent), ``rx_count`` (last in-order seq
        accepted == the cumulative ACK value), ``prec`` (governor wire
        precision), ``sub``/``sign2``/``ranged`` capability flags and
        ``gov_prev`` (the governor's previous RMS sample). One native lock
        acquisition — atomic against in-flight cascade quantizes and sign2
        frames (tests/test_checkpoint.py pins the byte-exact round trip).
        The carry pseudo-link -1 carries no aux."""
        values = np.empty(self.spec.total, np.float32)
        ids = np.empty(64, np.int32)
        resids = np.empty((64, self.spec.total), np.float32)
        aux = np.zeros((64, 4), np.uint64)
        n = self._lib.st_engine_snapshot_ex(
            self._handle(), values, ids, resids.reshape(-1),
            aux.reshape(-1), 64,
        )
        links: dict[int, np.ndarray] = {}
        meta: dict[int, dict] = {}
        for i in range(n):
            lid = int(ids[i])
            links[lid] = resids[i].copy()
            if lid >= 0:
                packed = int(aux[i, 2])
                meta[lid] = {
                    "tx_seq": int(aux[i, 0]),
                    "rx_count": int(aux[i, 1]),
                    "prec": packed & 0xFF,
                    "sub": bool(packed >> 8 & 1),
                    "sign2": bool(packed >> 9 & 1),
                    "ranged": bool(packed >> 10 & 1),
                    "gov_prev": float(
                        np.uint64(aux[i, 3]).view(np.float64)
                    ),
                }
        return values, links, meta

    def restore_ex(
        self,
        values: np.ndarray,
        links: dict[int, np.ndarray],
        meta: Optional[dict[int, dict]] = None,
    ) -> None:
        """restore_state plus per-link governor state (``prec`` and
        ``gov_prev`` from :meth:`snapshot_ex`'s meta). Live links' wire
        seqs are deliberately NOT rewound — the TCP streams they count are
        live; the barrier's drained-empty ledgers are what make a cluster
        restore seq-consistent (st_engine_restore_ex docstring)."""
        v = np.ascontiguousarray(values, np.float32)
        if v.shape != (self.spec.total,):
            raise ValueError(f"values shape {v.shape} != ({self.spec.total},)")
        ids = np.asarray(sorted(links), np.int32)
        resids = np.ascontiguousarray(
            np.stack([np.asarray(links[i], np.float32) for i in ids])
            if len(ids)
            else np.zeros((0, self.spec.total), np.float32)
        )
        aux_ptr = None
        if meta is not None:
            aux = np.zeros((max(1, len(ids)), 4), np.uint64)
            for i, lid in enumerate(ids):
                m = meta.get(int(lid))
                if m is None:
                    continue
                flags = (
                    (1 if m.get("sub") else 0)
                    | (2 if m.get("sign2") else 0)
                    | (4 if m.get("ranged") else 0)
                )
                aux[i, 0] = np.uint64(m.get("tx_seq", 0))
                aux[i, 1] = np.uint64(m.get("rx_count", 0))
                aux[i, 2] = np.uint64((m.get("prec", 0) & 0xFF) | flags << 8)
                aux[i, 3] = np.float64(m.get("gov_prev", -1.0)).view(
                    np.uint64
                )
            aux = np.ascontiguousarray(aux.reshape(-1))
            aux_ptr = aux.ctypes.data_as(ctypes.c_void_p)
        self._lib.st_engine_restore_ex(
            self._handle(), v, len(ids), ids, resids.reshape(-1), aux_ptr
        )

    def restore_state(
        self, values: np.ndarray, links: dict[int, np.ndarray]
    ) -> None:
        """Checkpoint restore (inverse of snapshot_all), atomic in C.
        Residuals restore only for links that still exist — links opened
        after the checkpoint keep their current residuals (same contract as
        utils/checkpoint.load_shared on the Python tier)."""
        v = np.ascontiguousarray(values, np.float32)
        if v.shape != (self.spec.total,):
            raise ValueError(f"values shape {v.shape} != ({self.spec.total},)")
        ids = np.asarray(sorted(links), np.int32)
        resids = np.ascontiguousarray(
            np.stack([np.asarray(links[i], np.float32) for i in ids])
            if len(ids)
            else np.zeros((0, self.spec.total), np.float32)
        )
        self._lib.st_engine_restore(
            self._handle(), v, len(ids), ids, resids.reshape(-1)
        )

    def poll_ctrl(self) -> Optional[tuple[int, bytes]]:
        """One control-plane message the engine deferred to Python, if any."""
        if not self._h:
            return None
        link = ctypes.c_int32(0)
        buf = self._ctrl_buf
        n = self._lib.st_engine_poll_ctrl(
            self._h, ctypes.byref(link), buf, len(buf)
        )
        if n <= 0:
            return None
        return int(link.value), buf.raw[:n]

    # -- observability -------------------------------------------------------

    def _counters(self) -> np.ndarray:
        """Counter snapshot; all-zero after destroy(). MUST never raise or
        segfault: pytest's failure reporting (saferepr) calls __repr__ →
        here on whatever locals a failing test left behind, including
        closed engines — an unguarded NULL call here aborted the entire
        suite process at report time (VERDICT r05 Weak #2).

        Layout (st_engine_counters): [frames_out, frames_in, updates,
        msgs_out, msgs_in, tx_slot_acquires, tx_slot_alloc_events,
        tx_slots_allocated, retx_msgs, dedup_discards, rtt_ns_total,
        rtt_msgs, hops_sum, hops_msgs, staleness_ns_last, traced_msgs_in,
        sub_msgs_out, sub_fresh_out, prec_upshifts, prec_downshifts,
        frames2_out, frames2_in]
        — [5..7] are the r07 tx-ring pool stats (steady state: acquires
        grow, alloc_events stay flat); [8..11] the r08 obs aggregates
        (go-back-N retransmits, dup/gap discards, ACK round-trip ns sum +
        sample count); [12..15] the r09 trace aggregates (hop-count sum +
        sample count, latest apply-time staleness ns, traced applied
        messages); [16..17] the r10 serving aggregates (unledgered
        subscriber data messages sent, FRESH drain marks delivered);
        [18..21] the r11 adaptive-precision aggregates (governor
        upshifts/downshifts, sign2 frames sent/applied — subsets of
        frames_out/frames_in)."""
        out = np.zeros(22, np.uint64)
        if self._h:
            self._lib.st_engine_counters(self._h, out)
        return out

    def link_obs(self, link_id: int) -> Optional[tuple[float, int]]:
        """(staleness_seconds, hops) of the latest traced message applied
        from this link, or None when the link is unknown / engine closed —
        the r09 per-link convergence gauges (st_staleness_seconds{link=},
        st_update_hops_last{link=})."""
        if not self._h:
            return None
        out = np.zeros(2, np.uint64)
        if not self._lib.st_engine_link_obs(self._h, link_id, out):
            return None
        return float(out[0]) / 1e9, int(out[1])

    def pool_stats(self) -> dict:
        """Tx-ring slot stats for metrics()/tests: zero per-message heap
        allocation in steady state means ``acquires`` grows while
        ``alloc_events`` stays flat."""
        c = self._counters()
        return {
            "tx_slot_acquires": int(c[5]),
            "tx_slot_alloc_events": int(c[6]),
            "tx_slots_allocated": int(c[7]),
        }

    def obs_stats(self) -> dict:
        """r08/r09 observability aggregates (canonical names per
        obs/schema.py): go-back-N retransmitted messages, dup/gap discards
        at the receive acceptance check, the engine-tier ACK round trip as
        a sum/count pair (the C hot path keeps no buckets), and the r09
        trace aggregates — hop counts (sum/count, same discipline as the
        RTT pair) and how many applied messages carried a trace stamp."""
        c = self._counters()
        return {
            "st_retransmit_msgs_total": int(c[8]),
            "st_dedup_discards_total": int(c[9]),
            "st_ack_rtt_seconds_sum": int(c[10]) / 1e9,
            "st_ack_rtt_seconds_count": int(c[11]),
            "st_update_hops_sum": int(c[12]),
            "st_update_hops_count": int(c[13]),
            "st_traced_msgs_in_total": int(c[15]),
            "st_sub_msgs_out_total": int(c[16]),
            "st_sub_fresh_out_total": int(c[17]),
            "st_precision_upshifts_total": int(c[18]),
            "st_precision_downshifts_total": int(c[19]),
            "st_frames2_out_total": int(c[20]),
            "st_frames2_in_total": int(c[21]),
        }

    @property
    def frames_out(self) -> int:
        return int(self._counters()[0])

    @property
    def frames_in(self) -> int:
        return int(self._counters()[1])

    @property
    def updates(self) -> int:
        return int(self._counters()[2])

    def __repr__(self) -> str:
        if not self._h:
            return (
                f"EngineTensor(destroyed, leaves={self.spec.num_leaves}, "
                f"n={self.spec.total_n})"
            )
        c = self._counters()
        return (
            f"EngineTensor(leaves={self.spec.num_leaves}, n={self.spec.total_n}, "
            f"links={list(self.link_ids)}, out={c[0]}, in={c[1]})"
        )
