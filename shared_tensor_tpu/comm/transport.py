"""ctypes binding to the native C++ transport (native/sttransport.cpp).

The native library owns the wire — TCP binary-tree overlay, framed streaming,
pacing, liveness, rejoin — while frames stay opaque bytes at this layer. The
peer engine (comm/peer.py) composes frames from device-side codec output.

Builds the shared library on demand with `make -C native` (g++ is in the
image; no pybind11 — plain C ABI).
"""

from __future__ import annotations

import ctypes
import dataclasses
import enum
import pathlib
import time
from typing import Optional

from .. import _build
from ..config import TransportConfig

_NATIVE_DIR = _build.NATIVE_DIR
_LIB_PATH = _NATIVE_DIR / "libsttransport.so"


class _StConfigC(ctypes.Structure):
    _fields_ = [
        ("wire_compat", ctypes.c_int32),
        ("compat_frame_bytes", ctypes.c_int32),
        ("listen_backlog", ctypes.c_int32),
        ("bandwidth_cap_bps", ctypes.c_int64),
        ("peer_timeout_sec", ctypes.c_double),
        ("keepalive_sec", ctypes.c_double),
        ("max_children", ctypes.c_int32),
        ("queue_depth", ctypes.c_int32),
        ("max_rejoin_attempts", ctypes.c_int32),
        ("rejoin_backoff_sec", ctypes.c_double),
        ("connect_timeout_sec", ctypes.c_double),
        ("join_timeout_sec", ctypes.c_double),
        ("stripe_count", ctypes.c_int32),  # r11: sockets per logical link
    ]


class _StEventC(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("link_id", ctypes.c_int32),
        ("is_uplink", ctypes.c_int32),
    ]


class _StStatsC(ctypes.Structure):
    _fields_ = [
        ("bytes_out", ctypes.c_uint64),
        ("bytes_in", ctypes.c_uint64),
        ("frames_out", ctypes.c_uint64),
        ("frames_in", ctypes.c_uint64),
        ("send_queue", ctypes.c_int32),
        ("recv_queue", ctypes.c_int32),
    ]


class EventKind(enum.IntEnum):
    LINK_UP = 1
    LINK_DOWN = 2
    BECAME_MASTER = 3
    REJOIN_FAILED = 4


@dataclasses.dataclass(frozen=True)
class Event:
    kind: EventKind
    link_id: int
    is_uplink: bool


@dataclasses.dataclass(frozen=True)
class LinkStats:
    """Per-link transport counters. ``frames_*`` count wire MESSAGES — data
    AND control (ACK/SYNC/CHUNK/...), excluding synthesized keepalives — so
    they exceed the peer layer's data-message counts by exactly the control
    traffic (peer.metrics() exposes them as ``wire_msgs_*``). ``bytes_*``
    include framing headers and keepalives."""

    bytes_out: int
    bytes_in: int
    frames_out: int
    frames_in: int
    send_queue: int
    recv_queue: int


_lib: Optional[ctypes.CDLL] = None


def build_native(force: bool = False) -> pathlib.Path:
    """Compile native/libsttransport.so if missing or stale (make is
    mtime-based, a no-op when fresh — edited sources must never keep serving
    a previously-built .so). Serialized across processes via _build.run_make
    so concurrent peer startups can't rebuild the .so under each other."""
    _build.run_make(force=force)
    return _LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    build_native()
    lib = ctypes.CDLL(str(_LIB_PATH))
    lib.st_node_create.restype = ctypes.c_void_p
    lib.st_node_create.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int,
        ctypes.POINTER(_StConfigC),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.st_node_listen_port.restype = ctypes.c_int32
    lib.st_node_listen_port.argtypes = [ctypes.c_void_p]
    lib.st_node_send.restype = ctypes.c_int32
    lib.st_node_send.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        # c_void_p, not c_char_p: accepts bytes AND zero-copy c_char views
        # over the peer tier's pooled frame slots (wire.FramePool) — a
        # c_char_p argtype would force a bytes() copy per message
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_double,
    ]
    lib.st_node_pool_stats.restype = None
    lib.st_node_pool_stats.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.st_node_stripe_stats.restype = ctypes.c_int32
    lib.st_node_stripe_stats.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    # r14 same-host shm lane (negotiated at the peer tier's SYNC/WELCOME;
    # the serve side creates the /dev/shm segment, the join side maps and
    # validates it — on any failure the link simply stays on TCP)
    lib.st_node_shm_serve.restype = ctypes.c_int32
    lib.st_node_shm_serve.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.st_node_shm_join.restype = ctypes.c_int32
    lib.st_node_shm_join.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_char_p,
        ctypes.c_uint64,
    ]
    lib.st_node_shm_stats.restype = ctypes.c_int32
    lib.st_node_shm_stats.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.st_node_recv.restype = ctypes.c_int32
    lib.st_node_recv.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.c_double,
    ]
    lib.st_node_poll_events.restype = ctypes.c_int32
    lib.st_node_poll_events.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(_StEventC),
        ctypes.c_int32,
        ctypes.c_double,
    ]
    lib.st_node_links.restype = ctypes.c_int32
    lib.st_node_links.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_int32,
    ]
    lib.st_node_uplink.restype = ctypes.c_int32
    lib.st_node_uplink.argtypes = [ctypes.c_void_p]
    lib.st_node_stats.restype = ctypes.c_int32
    lib.st_node_stats.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int32,
        ctypes.POINTER(_StStatsC),
    ]
    lib.st_node_drop_link.restype = ctypes.c_int32
    lib.st_node_drop_link.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.st_node_close.restype = None
    lib.st_node_close.argtypes = [ctypes.c_void_p]
    # r08 obs event ring (process-wide, defined in the transport .so)
    lib.st_node_obs_id.restype = ctypes.c_uint32
    lib.st_node_obs_id.argtypes = [ctypes.c_void_p]
    lib.st_obs_drain.restype = ctypes.c_int32
    lib.st_obs_drain.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.st_obs_now_ns.restype = ctypes.c_uint64
    lib.st_obs_now_ns.argtypes = []
    lib.st_obs_dropped.restype = ctypes.c_uint64
    lib.st_obs_dropped.argtypes = []
    lib.st_obs_set_enabled.restype = None
    lib.st_obs_set_enabled.argtypes = [ctypes.c_int32]
    from .. import obs

    if not obs.obs_enabled():
        # the .so also parses ST_OBS itself; this covers obs having been
        # disabled programmatically before the first native load
        lib.st_obs_set_enabled(0)
    _lib = lib
    return lib


class TransportNode:
    """One peer's transport endpoint: joins the tree at (host, port) or
    becomes master when nobody answers — the reference's rendezvous semantics
    (src/sharedtensor.c:271-277)."""

    def __init__(
        self,
        host: str,
        port: int,
        config: TransportConfig | None = None,
        frame_bytes: int = 0,
        max_children: int = 2,
        queue_depth: int = 8,
        keepalive_sec: float = 1.0,
    ):
        cfg = config or TransportConfig()
        self._lib = _load()
        c = _StConfigC(
            wire_compat=1 if cfg.wire_compat else 0,
            compat_frame_bytes=frame_bytes,
            listen_backlog=cfg.listen_backlog,
            bandwidth_cap_bps=cfg.bandwidth_cap_bytes_per_sec,
            peer_timeout_sec=cfg.peer_timeout_sec,
            keepalive_sec=keepalive_sec,
            max_children=max_children,
            queue_depth=queue_depth,
            max_rejoin_attempts=cfg.max_rejoin_attempts,
            rejoin_backoff_sec=0.2,
            connect_timeout_sec=cfg.connect_timeout_sec,
            join_timeout_sec=cfg.join_timeout_sec,
            stripe_count=cfg.stripe_count,
        )
        is_master = ctypes.c_int32(0)
        self._h = self._lib.st_node_create(
            host.encode(), port, ctypes.byref(c), ctypes.byref(is_master)
        )
        if not self._h:
            # bounded-time failure (join_timeout_sec of backed-off attempts,
            # each hop bounded by connect_timeout_sec) — before r06 a dead
            # rendezvous could block the constructor forever instead
            raise ConnectionError(
                f"could not join or become master at {host}:{port} "
                # 0 is the documented use-the-default sentinel; the native
                # layer coerces it to 30 s, so print the real budget
                f"within {cfg.join_timeout_sec or 30.0:.0f}s"
            )
        self.is_master = bool(is_master.value)
        #: Process-unique obs id tagging this node's events on the shared
        #: native event ring (obs/events.py; 0 only if the ABI is absent).
        self.obs_id = int(self._lib.st_node_obs_id(self._h))
        self._recv_buf = ctypes.create_string_buffer(max(frame_bytes, 1 << 20))

    # -- wire ---------------------------------------------------------------

    def send(self, link_id: int, payload, timeout: float = 1.0) -> bool:
        """Enqueue a frame; False = backpressure (retry), raises on dead
        link. ``payload`` may be bytes OR any buffer (memoryview over a
        pooled frame slot — the r07 zero-copy encode path): either way the
        bytes cross the ABI once, into the transport's recycled tx buffer,
        so the caller's buffer is free for reuse the moment this returns."""
        n = len(payload)
        if isinstance(payload, bytes):
            arg = payload
        else:
            # writable-buffer view without copying (bytes() would copy);
            # the ctypes array keeps the underlying buffer alive for the
            # duration of the call
            arg = (ctypes.c_char * n).from_buffer(payload)
        r = self._lib.st_node_send(self._h, link_id, arg, n, timeout)
        if r < 0:
            raise BrokenPipeError(f"link {link_id} is down")
        return r == 1

    def recv(self, link_id: int, timeout: float = 0.0) -> Optional[bytes]:
        """Dequeue one received frame, or None. Raises when the link is dead
        and fully drained."""
        n = self._lib.st_node_recv(
            self._h, link_id, self._recv_buf, len(self._recv_buf), timeout
        )
        if n < 0:
            raise BrokenPipeError(f"link {link_id} is down")
        if n == 0:
            return None
        return self._recv_buf.raw[:n]

    # -- topology -----------------------------------------------------------

    def poll_events(self, timeout: float = 0.0, cap: int = 16) -> list[Event]:
        arr = (_StEventC * cap)()
        n = self._lib.st_node_poll_events(self._h, arr, cap, timeout)
        return [
            Event(EventKind(arr[i].kind), arr[i].link_id, bool(arr[i].is_uplink))
            for i in range(n)
        ]

    @property
    def links(self) -> list[int]:
        # empty after close(), never a NULL-handle native call: the r08
        # metrics collectors (registry snapshot, postmortem dump) can race
        # a closing peer, and this introspection path must degrade to
        # nothing rather than SIGSEGV (the r05 st_engine_counters lesson)
        if not self._h:
            return []
        arr = (ctypes.c_int32 * 64)()
        n = self._lib.st_node_links(self._h, arr, 64)
        return [arr[i] for i in range(n)]

    @property
    def uplink(self) -> Optional[int]:
        if not self._h:
            return None
        u = self._lib.st_node_uplink(self._h)
        return None if u < 0 else u

    @property
    def listen_port(self) -> int:
        return self._lib.st_node_listen_port(self._h)

    def pool_stats(self) -> dict:
        """Transport buffer-pool counters (r07 data plane): tx/rx buffer
        acquires vs misses (fresh allocations) and zero-copy sends. Steady
        state shows acquires growing while misses stay flat."""
        out = (ctypes.c_uint64 * 5)()
        # st_node_pool_stats NULL-checks natively; skip the call anyway
        # when closed so the zeros are explicit
        if self._h:
            self._lib.st_node_pool_stats(self._h, out)
        return {
            "tx_acquires": out[0],
            "tx_misses": out[1],
            "rx_acquires": out[2],
            "rx_misses": out[3],
            "zc_msgs": out[4],
        }

    def shm_serve(self, link_id: int, ring_bytes: int) -> Optional[tuple]:
        """Create this link's same-host shm segment (the parent's half of
        the r14 lane negotiation). Returns ``(name, token)`` to hand to
        the peer, or None when the lane cannot be served (compat mode,
        dead link, /dev/shm unavailable) — the link then stays on TCP."""
        if not self._h:
            return None
        name = ctypes.create_string_buffer(96)
        token = ctypes.c_uint64(0)
        r = self._lib.st_node_shm_serve(
            self._h, link_id, ring_bytes, name, len(name),
            ctypes.byref(token),
        )
        if r != 0:
            return None
        return name.value.decode(), int(token.value)

    def shm_join(self, link_id: int, name: str, token: int) -> bool:
        """Map and validate the peer's shm segment (the child's half).
        False — with the reason recorded as a ``shm_fallback`` timeline
        event — means the link keeps TCP; negotiation failure is never an
        error."""
        if not self._h:
            return False
        return (
            self._lib.st_node_shm_join(
                self._h, link_id, name.encode(), token
            )
            == 0
        )

    def shm_stats(self, link_id: int) -> Optional[dict]:
        """r14 shm-lane telemetry: lane state (0 = TCP only, 1 = segment
        mapped, 2 = tx live), per-lane message/byte counters, ring size
        and futex sleeps. None for an unknown link or a closed node."""
        if not self._h:
            return None
        out = (ctypes.c_uint64 * 8)()
        if self._lib.st_node_shm_stats(self._h, link_id, out) < 0:
            return None
        return {
            "state": int(out[0]),
            "msgs_out": int(out[1]),
            "msgs_in": int(out[2]),
            "bytes_out": int(out[3]),
            "bytes_in": int(out[4]),
            "ring_bytes": int(out[5]),
            "tx_waits": int(out[6]),
            "rx_waits": int(out[7]),
        }

    def stripe_stats(self, link_id: int) -> Optional[dict]:
        """r11 per-link stripe telemetry: negotiated/live socket counts +
        stripe lifecycle totals (deaths, re-routed messages). None for an
        unknown link or a closed node."""
        if not self._h:
            return None
        out = (ctypes.c_uint64 * 4)()
        if self._lib.st_node_stripe_stats(self._h, link_id, out) < 0:
            return None
        return {
            "stripes": int(out[0]),
            "live": int(out[1]),
            "deaths": int(out[2]),
            "reroutes": int(out[3]),
        }

    def stats(self, link_id: int) -> Optional[LinkStats]:
        if not self._h:
            return None  # closed node: no stats, never a NULL native call
        s = _StStatsC()
        if self._lib.st_node_stats(self._h, link_id, ctypes.byref(s)) < 0:
            return None
        return LinkStats(
            s.bytes_out, s.bytes_in, s.frames_out, s.frames_in,
            s.send_queue, s.recv_queue,
        )

    def drop_link(self, link_id: int) -> None:
        self._lib.st_node_drop_link(self._h, link_id)

    def drop_link_flushed(self, link_id: int, timeout: float = 0.5) -> None:
        """Drop a link AFTER its userspace send queue has drained (bounded
        wait). ``send`` only enqueues; ``drop_link`` kills the socket and
        closes the queue in the same breath, so a reject-then-drop races
        the sender thread still holding the REJECT — lose the race and the
        refused peer sees a bare link death instead of the reason, retries
        its join forever, and times out instead of failing loudly. Polling
        the queue to empty (plus one scheduling grace for the in-flight
        socket write) closes the race; the deadline keeps a wedged peer
        from pinning the caller's control thread."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            s = self.stats(link_id)
            if s is None or s.send_queue == 0:
                break
            time.sleep(0.005)
        time.sleep(0.05)
        self.drop_link(link_id)

    def close(self) -> None:
        if self._h:
            self._lib.st_node_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
