"""The peer engine: a complete shared-tensor node.

Composes the three lower layers into the reference's user-facing object
(reference src/sharedtensor.c:347-465 — createOrFetch / copyToTensor /
addFromTensor):

  - :class:`~shared_tensor_tpu.core.SharedTensor` — replica + per-link
    residuals + codec (device-side, functional JAX);
  - :class:`~shared_tensor_tpu.comm.transport.TransportNode` — the native C++
    TCP binary-tree overlay (host-side);
  - :mod:`~shared_tensor_tpu.comm.wire` — typed message encoding between them.

Where the reference runs 2 threads per link all doing O(n) float loops on the
CPU (src/sharedtensor.c:113-189; measured codec-CPU-bound, SURVEY.md §6), this
engine runs exactly two host threads per node — a sender and a receiver — that
only move opaque bytes and dispatch device work; the O(n) math executes on the
accelerator via the jitted table codec. Sends are event-driven (woken by
``add()`` and by incoming frames) and quiesce when residuals hit exact zero —
the reference instead burns 1 frame/s/link forever when idle (quirk Q2).

Threading model: the receive thread is the only consumer of transport events
(LINK_UP/LINK_DOWN) and the only writer of handshake state; the send thread
only reads ``SharedTensor.link_ids`` (created exactly at handshake
completion), so no lock beyond SharedTensor's own is needed.

Join/rejoin semantics (native mode) are the SYNC handshake documented in
wire.py. Wire-compat mode skips the handshake and speaks the reference's raw
protocol for interop with C peers (SURVEY.md §2.3).
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from .. import obs as _obs
from ..config import Config
from ..core import DuplicateLink, SharedTensor
from ..obs import schema as _schema
from ..ops.table import make_spec
from . import faults, wire
from .transport import EventKind, TransportNode

log = logging.getLogger("shared_tensor_tpu.peer")

_HOST_ID: Optional[bytes] = None


def _shm_host_id() -> bytes:
    """16-byte host identity for the r14 same-host shm-lane negotiation.
    The Linux boot id is per-boot-unique ACROSS containers sharing a
    kernel only when the container runtime namespaces it — but two
    processes that CAN open the same /dev/shm path validate the segment
    token anyway, so a boot-id collision can at worst cost one failed
    attach (shm_fallback event) before the link keeps TCP."""
    global _HOST_ID
    if _HOST_ID is None:
        try:
            import uuid

            with open("/proc/sys/kernel/random/boot_id") as f:
                _HOST_ID = uuid.UUID(f.read().strip()).bytes
        except (OSError, ValueError):
            import hashlib
            import socket as _socket

            _HOST_ID = hashlib.sha256(
                _socket.gethostname().encode()
            ).digest()[:16]
    return _HOST_ID

#: Pseudo-link id holding the re-graft carry as a LIVE slot in the Python
#: tier's SharedTensor (the engine keeps its carry internally): a dead
#: uplink's rolled-back residual parks here and keeps receiving add()/flood
#: mass while the node is orphaned. Without a live slot, an add made with
#: no links lives only in the replica; the re-join snapshot then presents
#: it as tree-known state and the parent's diff seed erases it tree-wide
#: (the reference avoids this by accumulating into unconnected slots,
#: src/sharedtensor.c:124-126/:338-342). Never a transport link id
#: (transport ids start at 1); the send loop and drain skip it.
CARRY_LINK = -1

#: Go-back-N send window: max unacked DATA/BURST messages per link before
#: the send loop stops producing new frames for it. Bounds the retained
#: retransmission payloads (a stalled link would otherwise grow its ledger
#: — and the retransmittable tail — without limit until teardown) while
#: leaving a healthy link's pipeline far deeper than its ms-scale ACK
#: latency ever needs. The native engine enforces the same window.
SEND_WINDOW = 32

#: Max messages re-sent per retransmission round: go-back-N only needs the
#: HEAD of the unacked tail to restore in-order progress at the receiver
#: (everything behind a hole is discarded until the hole fills); resending
#: a short prefix repairs it without re-shipping the whole window's bytes
#: every round. Ditto in the native engine.
RETX_PREFIX = 4

def _python_tier_auto_burst(spec) -> int:
    """Auto burst for the PYTHON fallback tier: each burst frame is a full
    synchronous numpy rescan under the state lock, so only small tables —
    where per-message dispatch dominates — come out ahead."""
    if spec.total <= (1 << 15):
        return max(24, min(128, (1 << 19) // max(1, spec.total)))
    return 1


class _PeerObs:
    """One peer's observability bundle (r08 tentpole): a metrics registry
    publishing the canonical schema (obs/schema.py) — live histograms for
    the Python tier's per-message latencies, everything else sampled at
    snapshot time via a collector — plus the peer's handle on the process
    hub (flight recorder, native event-ring drain, postmortems).

    Hot-path cost when enabled: one ``time.monotonic()`` pair + one
    histogram observe per wire message on the PYTHON tier only; the native
    engine's data plane exports aggregates through the counters ABI and
    never calls into Python. Disabled (Config.obs.enabled=False or
    ST_OBS=0): the peer holds ``_obs = None`` and pays one None-check."""

    def __init__(self, peer: "SharedTensorPeer"):
        self.hub = _obs.hub()
        self.registry = _obs.Registry()
        h = self.registry.histogram
        self.ack_rtt = h(
            "st_ack_rtt_seconds",
            help="ledger-append to cumulative-ACK-pop round trip",
        )
        self.encode = h(
            "st_encode_seconds", help="wire-encode latency per DATA/BURST"
        )
        self.apply = h(
            "st_apply_seconds", help="decode+apply latency per received batch"
        )
        # Delivery counters exist as LIVE instruments only on the Python
        # tier: an engine peer's retransmit/dedup truth lives in the C
        # counters ABI and arrives via the collector — registering a
        # never-incremented instrument under the same name would shadow
        # the collector's real value in every snapshot/scrape (instrument
        # values take precedence), reporting 0 while a link black-holes.
        # Ditto the r09 st_update_hops histogram: the engine tier exports
        # sum/count through the widened counters ABI instead.
        self.retransmits = self.dedup = self.hops = None
        if peer._engine is None:
            self.retransmits = self.registry.counter(
                "st_retransmit_msgs_total",
                help="go-back-N messages re-sent byte-identical",
            )
            self.dedup = self.registry.counter(
                "st_dedup_discards_total",
                help="duplicate/out-of-order data messages discarded unapplied",
            )
            self.hops = self.registry.histogram(
                "st_update_hops",
                buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
                help="tree hops traversed by applied traced updates",
            )
        # r09 in-band digest plumbing (python-side on BOTH tiers: digests
        # ride the control plane, never the C data path)
        self.digest_out = self.registry.counter(
            "st_digest_sends_total",
            help="cluster metrics digests sent up the tree",
        )
        self.digest_in = self.registry.counter(
            "st_digest_msgs_in_total",
            help="cluster metrics digests received from subtree links",
        )
        self.cluster_nodes = self.registry.gauge(
            "st_cluster_nodes",
            help="nodes represented in the latest merged cluster digest",
        )
        self.registry.register_collector(peer._obs_collect)
        self.label = f"peer-{peer.node.obs_id}"
        self.hub.register_registry(self.label, self.registry)
        ocfg = peer.config.obs
        self.drain_interval = ocfg.native_drain_interval_sec
        if ocfg.jsonl_path:
            self.registry.start_jsonl_sink(
                ocfg.jsonl_path, ocfg.jsonl_interval_sec
            )
        # r18: engine-tier origin attribution. The C receiver's trace_apply
        # ring events carry (origin << 8 | hop) in extra; a drain tap picks
        # this peer's out of each batch so _stale_origin stays current on
        # engine links too (the python tier writes it in _note_trace).
        self._peer = peer
        self._tap = self._on_native_batch if peer._engine is not None else None
        if self._tap is not None:
            self.hub.add_tap(self._tap)

    def _on_native_batch(self, batch) -> None:
        peer = self._peer
        me = peer.node.obs_id
        for e in batch:
            if e.name == "trace_apply" and e.node == me:
                peer._stale_origin[e.link] = e.extra >> 8

    def event(
        self, name: str, node: int = 0, link: int = 0, arg: int = 0,
        detail: str = "", extra: int = 0,
    ) -> None:
        self.hub.emit(
            name, node=node, link=link, arg=arg, detail=detail, extra=extra
        )

    def close(self) -> None:
        self.registry.stop_jsonl_sink()
        if self._tap is not None:
            self.hub.remove_tap(self._tap)
        self.hub.poll_native()  # final drain: close() must not strand events
        self.hub.unregister_registry(self.label)


class SpecMismatch(ConnectionError):
    """Peer tried to sync a different table layout (the reference's
    THError("Not the right size!"), src/sharedtensor.c:335, made explicit
    at join time instead of corrupting the stream)."""


class SharedTensorPeer:
    """One node of the shared tensor: join the tree at (host, port) — or
    become master if nobody answers — then stream codec frames forever.

    The reference equivalent is ``sharedtensor.createOrFetch(host, port, t)``
    (src/sharedtensor.c:347-391): master seeds the shared state from
    ``template``; a joiner's ``template`` only defines the table *layout* and
    its values are ignored, with real state streaming in from the tree.
    """

    def __init__(
        self,
        host: str,
        port: int,
        template: Any,
        config: Config | None = None,
    ):
        self.config = config or Config()
        codec = self.config.codec
        tcfg = self.config.transport
        spec = make_spec(template)
        # r09 cross-hop trace propagation: which DATA/BURST framing this
        # peer EMITS (compat.WIRE_VERSION; decoders accept both). Lazy
        # import — compat.py imports this module at its top level. Decided
        # BEFORE the fault plan: corrupt()'s bounded-flip geometry must
        # skip the v2 trace bytes too.
        from ..compat import wire_protocol_version

        self._wire_version = (
            1 if tcfg.wire_compat else wire_protocol_version(self.config)
        )
        self._trace_wire = self._wire_version >= 2
        # Python-tier fault injection (Config.faults): consulted at the
        # send boundary and at named protocol points. None when disabled —
        # the production path pays one None-check per send. The NATIVE data
        # planes (transport sender loop, engine) read the same schedule
        # from the ST_FAULT_PLAN/ST_FAULT_CRASH env table instead
        # (faults.to_env), parsed at node-create time. scale_bytes/
        # trace_bytes hand the plan the frame geometry so corrupt() flips
        # land in sign words, not scale exponents or trace fields (the
        # bounded fault class).
        self._faults: Optional[faults.FaultPlan] = (
            faults.FaultPlan(
                self.config.faults,
                scale_bytes=4 * spec.num_leaves,
                wire_compat=tcfg.wire_compat,
                trace_bytes=wire.TRACE_BYTES if self._trace_wire else 0,
            )
            if self.config.faults.enabled
            else None
        )
        # pending trace stamp (origin node, origin monotonic ns, hops):
        # re-seeded by add(), advanced at every traced apply; read by the
        # send path when stamping outgoing messages. Tuple assignment —
        # atomic under the GIL, no lock on the hot path.
        self._trace_stamp: Optional[tuple[int, int, int]] = None
        # per-link (origin generation stamp ns, hops) of the latest traced
        # apply (python tier; the engine tier serves st_engine_link_obs
        # instead). r18: the GENERATION is stored, not a frozen age — the
        # collector ages it live so stalls are visible to the SLO.
        self._staleness: dict[int, tuple[int, int]] = {}
        self._traced_in = 0
        # r09 in-band digest aggregation: each child link's latest digest
        # (replaced wholesale per arrival; merged on demand)
        self._child_digests: dict[int, dict] = {}
        # digests ride the native control plane AND presume an r09 peer on
        # the other end: a peer pinned to v1 emission (ST_WIRE_TRACE=0 —
        # the join-a-pre-r09-tree escape hatch) must not spray kind-8
        # messages a pre-r09 parent would log as unknown every beat
        self._digest_interval = (
            0.0
            if tcfg.wire_compat or self._wire_version < 2
            else self.config.obs.digest_interval_sec
        )
        self._digest_last = 0.0
        # r18 fleet health plane. _skew_ns simulates a skewed host clock
        # (tests/benches only — env ST_CLOCK_SKEW_SEC overrides config):
        # applied via _now_ns() at every cross-node-comparable stamp site
        # (trace stamps, clock probes, digest t_ns), so the offset
        # estimator has a real skew to recover on a single host. _clock is
        # the per-node offset estimator (obs/clock.py); a node probes its
        # UPLINK every clock_sync_interval_sec with a wire.CLOCK message
        # (chaos-exempt control plane) — master peers are roots (offset
        # pinned 0). _stale_origin tracks the origin node of each link's
        # freshest traced apply, feeding the health analyzer's
        # offset-corrected staleness. _health exists only at a root with
        # health_json_path set; it is beaten from _publish_digest.
        skew_env = os.environ.get("ST_CLOCK_SKEW_SEC", "")
        self._skew_ns = int(
            float(skew_env if skew_env else self.config.obs.clock_skew_sim_sec)
            * 1e9
        )
        self._clock_interval = (
            0.0
            if tcfg.wire_compat or self._wire_version < 2
            else self.config.obs.clock_sync_interval_sec
        )
        self._clock_last = 0.0
        self._stale_origin: dict[int, int] = {}
        from ..core import host_tier_active

        # Burst sizing (Config.frame_burst): host tier only — the device
        # tier pipelines async dispatches (and has its own
        # device_frame_burst). Auto policy: the native engine fills the
        # wire message budget at every size; the Python fallback tier
        # bursts only small tables and never in compat mode (its compat
        # path sends one reference frame per message). Compat bursts exist
        # only on the engine: K fixed-size reference frames concatenate
        # into one wire message — protocol-identical to K sequential sends
        # for any reference peer (stengine.cpp compat-burst note).
        burstable = (
            host_tier_active()
            and self.config.codec.suppress_zero_frames  # the burst path has
            # no idle frames to send; honor the knob by streaming instead
        )
        from .engine import engine_eligible

        engine_ok = burstable and engine_eligible(self.config)
        if not burstable:
            self._burst = 1
        elif tcfg.wire_compat:
            if not engine_ok:
                self._burst = 1
            else:
                # the same wire-message byte budget as native mode bounds
                # BOTH the auto fill and an explicit Config.frame_burst —
                # without it a 255-frame burst on a 16 Mi tensor would
                # build single ~535 MB payloads
                cap = wire.compat_burst_frames_cap(spec.total_n)
                if self.config.frame_burst == 0:
                    self._burst = cap
                else:
                    self._burst = min(max(1, self.config.frame_burst), cap)
        elif self.config.frame_burst == 0:
            if engine_ok:
                # auto (engine): FILL the wire message budget — throughput
                # is monotone in K up to the per-spec cap at every measured
                # size (ENGINE_SWEEP_r07.json, the committed re-measure the
                # round-5 verdict asked for: 710 k f/s at 4 Ki, 52 k at
                # 64 Ki, 7.2 k at 1 Mi — all at their per-spec caps). The
                # engine's fused quantize+partials makes marginal frames
                # one memory pass, and a burst is one ledger entry/ACK.
                self._burst = wire.burst_frames_cap(spec)
            else:
                self._burst = _python_tier_auto_burst(spec)
        else:
            self._burst = max(1, self.config.frame_burst)
        if not tcfg.wire_compat:
            # wire-level invariant (native framing): every peer sizes its
            # receive buffer for burst_frames_cap(spec) frames
            # (frame_wire_bytes), so a sender must never burst beyond that
            # regardless of Config.frame_burst. Compat needs no cap-by-spec:
            # each frame is its own fixed-size wire message on the receive
            # side.
            self._burst = min(self._burst, wire.burst_frames_cap(spec))
        # Device-tier burst (Config.device_frame_burst): any size — the
        # point is amortizing the device-link round trip, which hurts at
        # every table size (VERDICT r03 item 3).
        dev_burstable = (
            not tcfg.wire_compat
            and not host_tier_active()
            and self.config.codec.suppress_zero_frames
        )
        if not dev_burstable:
            self._burst_device = 1
        elif self.config.device_frame_burst == 0:
            self._burst_device = min(16, wire.burst_frames_cap(spec))
        else:
            self._burst_device = max(
                1,
                min(
                    wire.burst_frames_cap(spec), self.config.device_frame_burst
                ),
            )
        if tcfg.wire_compat:
            if spec.num_leaves != 1:
                raise ValueError(
                    "wire-compat mode syncs one flat tensor per port "
                    "(reference README.md:26); use native mode for tables"
                )
            frame_bytes = wire.compat_frame_bytes(spec.total_n)
        else:
            # covers the worst-case incoming BURST from ANY peer (shared
            # spec via the layout handshake), not just our own burst size
            frame_bytes = wire.frame_wire_bytes(spec)
        self.node = TransportNode(
            host,
            port,
            tcfg,
            frame_bytes=frame_bytes,
            max_children=tcfg.max_children,
            keepalive_sec=min(1.0, max(0.05, tcfg.peer_timeout_sec / 4)),
        )
        self.is_master = self.node.is_master
        # r18 clock plane: master peers are tree roots (offset pinned to
        # 0/0); everyone else converges by probing the uplink. The health
        # analyzer exists only at a root with health_json_path set and is
        # beaten from _publish_digest on the recv thread.
        from ..obs.clock import ClockSync

        self._clock = ClockSync(self._now_ns, is_root=self.is_master)
        self._health = None
        if self.is_master and self.config.obs.health_json_path:
            from ..obs.health import HealthAnalyzer

            ocfg = self.config.obs
            self._health = HealthAnalyzer(
                path=ocfg.health_json_path,
                history=ocfg.health_history,
                objective_sec=ocfg.staleness_slo_sec,
                budget=ocfg.slo_budget,
                windows=ocfg.slo_windows,
                skew_ratio=ocfg.heat_skew_ratio,
                emit=self._health_event,
            )
        # Native engine (stengine.cpp): on the host tier the full
        # steady-state cycle — quantize, encode, send, receive, flood apply,
        # ACK ledger — runs in two C threads against the same stcodec.c
        # loops; Python keeps the handshakes and membership. Closes the
        # ~3 ms/message interpreter floor (round-3 verdict item 2).
        self._engine = None
        self._engine_links: set[int] = set()
        from .engine import EngineTensor, engine_eligible

        # r11 adaptive precision: on iff the engine owns the data plane,
        # native framing, and the config/env policy allows it
        # (compat.sign2_mode — ST_SIGN2=0 is the escape hatch). The
        # capability is advertised in SYNC/WELCOME; emission additionally
        # gates per link on the PEER's advertisement.
        from ..compat import sign2_mode

        self._sign2_mode = (
            sign2_mode(self.config)
            if engine_eligible(self.config) and not tcfg.wire_compat
            else 0
        )
        self._sign2 = self._sign2_mode != 0
        if engine_eligible(self.config):
            try:
                self.st = EngineTensor(
                    template,
                    codec,
                    seed_values=self.is_master,
                    node=self.node,
                    burst=self._burst,
                    recv_cap=frame_bytes,
                    # compat: the engine speaks the reference's raw frames
                    # directly (no ACK ledger — the protocol has none)
                    compat_frame_bytes=frame_bytes if tcfg.wire_compat else 0,
                    quarantine_send_failures=tcfg.quarantine_send_failures,
                    ack_timeout_sec=tcfg.ack_timeout_sec,
                    ack_retry_limit=tcfg.ack_retry_limit,
                    trace_wire=self._trace_wire,
                    precision_mode=self._sign2_mode,
                    precision_up_ratio=codec.precision_up_ratio,
                    precision_down_ratio=codec.precision_down_ratio,
                    precision_interval_sec=codec.precision_interval_sec,
                    cascade_frames=(
                        codec.cascade_frames if not tcfg.wire_compat else 1
                    ),
                )
                self._engine = self.st
                # Vacuous-chaos guard: Config.faults WIRE knobs inject in
                # the PYTHON tier's send path, which engine links never
                # traverse — on this tier the same classes come from the
                # ST_FAULT_PLAN env table (faults.to_env), parsed by
                # st_node_create above. A chaos test that forgot the env
                # render would pass green having injected nothing.
                import os as _os

                fcfg = self.config.faults
                if (
                    fcfg.enabled
                    and not _os.environ.get("ST_FAULT_PLAN")
                    and any((
                        fcfg.drop_pct, fcfg.dup_pct, fcfg.truncate_pct,
                        fcfg.corrupt_pct, fcfg.delay_pct,
                        fcfg.stall_after_frames >= 0,
                        fcfg.sever_after_frames,
                    ))
                ):
                    log.warning(
                        "FaultConfig wire faults are configured but the "
                        "NATIVE engine owns this peer's data plane — they "
                        "will inject NOTHING on engine links; render them "
                        "into the env with faults.to_env() around node "
                        "creation (crash_point still fires)"
                    )
            except Exception as e:
                log.warning("native engine unavailable, using python tier: %s", e)
        if self._engine is None:
            self._sign2 = False  # the python tier neither decodes nor
            # advertises sign2 — peers stay 1-bit toward us automatically
            # the burst was sized for the engine (fill the wire budget);
            # if the engine did not actually construct, the Python tier
            # must re-size — at the cap it would pay up to 255 synchronous
            # numpy rescans per message under the state lock. Its compat
            # path has no burst at all (one reference frame per message).
            if tcfg.wire_compat:
                self._burst = 1
            elif self.config.frame_burst == 0 and self._burst > 1:
                self._burst = min(self._burst, _python_tier_auto_burst(spec))
            self.st = SharedTensor(template, codec, seed_values=self.is_master)
        # r12 cluster lifecycle (consistent-cut snapshot/restore, drain,
        # operator surface). All barrier state is owned by the RECV thread
        # (_lc_tick / the SNAP/SNAP_ACK/RESUME handlers); public APIs
        # enqueue requests and wait on _lc_done. _paused gates NEW data
        # production on both tiers (engine: st_engine_pause; python: the
        # send loop) while in-flight delivery keeps draining — the
        # consistent cut is "paused + every ledger empty".
        self._lc_requests: deque = deque()
        self._lc_api_mu = threading.Lock()  # serializes _lc_request callers
        self._lc_op: Optional[dict] = None
        self._lc_done = threading.Event()
        self._lc_result: Optional[dict] = None
        self._paused = False
        self._pause_deadline = 0.0
        self._snap_total = 0
        self._snap_acks = 0
        self._snap_last_dur = 0.0
        self._restore_total = 0
        self._drain_total = 0
        self._draining = False
        self._lc_errors = 0
        self._ctl_last_poll = 0.0
        self._restored_from: Optional[str] = None
        # consistent-cut ordering state (python data plane): the send
        # loop's pass counter (pause is synchronous across one in-flight
        # pass — a pass already quantizing when the flag lands may still
        # enqueue, and a barrier marker must never overtake its data) and
        # the device pipeline's queued-frame gauge (markers only flood
        # once the paused pipeline has fully drained into the sockets)
        self._send_pass = 0
        self._pipe_frames = 0
        if self.config.lifecycle.restore_path:
            # full-cluster restart path: load this node's shard BEFORE the
            # data plane starts (threads are not running yet, so no lock
            # ordering to worry about)
            self._restore_at_startup(self.config.lifecycle.restore_path)
        self._ready = threading.Event()
        self._error: Optional[Exception] = None
        if self.is_master:
            self._ready.set()
        self._stop = threading.Event()
        self._wake = threading.Event()
        # parent-side handshake state: link_id -> snapshot being received
        self._pending: dict[int, bytearray] = {}
        # child-side re-graft accounting. Invariant: the snapshot we send a
        # prospective parent is "state the tree already has from/for us" =
        # replica - carried_residual, so the parent's diff seed never
        # subtracts updates we still owe the tree. _sent_snapshot is kept
        # until WELCOME so the uplink residual can be seeded with
        # replica_now - sent_snapshot (= carry + everything added or flooded
        # in during the handshake).
        self._sent_snapshot: Optional[jnp.ndarray] = None
        # set when the uplink died BEFORE the handshake finished (no codec
        # link existed to stash): the carry is then values - this base,
        # computed lazily at re-join so orphan-period adds are included
        self._mid_handshake_base: Optional[jnp.ndarray] = None
        self._compat_reset_on_regraft = False
        self._sealed = False  # leave() in progress: discard unACKed ingress
        self._uplink: Optional[int] = None
        # r10 serving tier, WRITER side. _sub_links: attached read-only
        # subscriber links -> their word range (None = full table). These
        # links are UNLEDGERED: the send loop never appends to _unacked for
        # them (no ACKs will come — compat.SYNC_FLAG_READ_ONLY), loss is
        # the subscriber's seq-gap detector + resync handshake to repair,
        # and LINK_DOWN discards their residual without a carry (a
        # read-only leaf owes the tree nothing). _pending_sub: handshake
        # state between a read-only SYNC and its DONE (value = the RANGE
        # subscription received so far, None = full). _sub_fresh: last
        # FRESH drain-mark time per link (python-tier beat; the engine
        # tier beats in C).
        self._sub_links: dict[int, Optional[tuple[int, int]]] = {}
        self._pending_sub: dict[int, Optional[tuple[int, int]]] = {}
        self._sub_fresh: dict[int, float] = {}
        # r11 sign2 capability flags gathered during handshakes, consumed
        # at attach time (link id -> the peer advertised sign2 decode)
        self._peer_sign2: dict[int, bool] = {}
        # r14 same-host shm lane: whether this peer may negotiate it at
        # all, our host identity, and per-link whether the JOINER's SYNC
        # advertised a matching host (consumed at WELCOME time, when the
        # parent serves the segment). Negotiation is fail-safe — every
        # mismatch keeps the link on TCP.
        self._shm_ok = (
            self.config.transport.shm_enabled
            and not self.config.transport.wire_compat
            and sys.platform.startswith("linux")
            and os.path.isdir("/dev/shm")
            and os.environ.get("ST_SHM", "1") != "0"
        )
        self._shm_host = _shm_host_id() if self._shm_ok else b""
        self._peer_shm: dict[int, bool] = {}
        # r14 capability per link (the peer advertised the SYNC/WELCOME
        # shm flag at all — host match or not): gates the aligned v3
        # framing toward it (engine.link_wire_v3)
        self._peer_r14: dict[int, bool] = {}
        # replica state_version at each ranged link's last residual mask
        # (skip the full-table mask copy on idle passes)
        self._sub_mask_ver: dict[int, int] = {}
        self._sub_msgs_out = 0
        self._sub_fresh_out = 0
        # delivery accounting (see _send_loop): per link, the in-order list
        # of sent-but-unacked messages as (ledger_seq, wire_seq, payload)
        # — the payload is kept so an ACK timeout can retransmit it
        # byte-identical (go-back-N; wire.py tx_seq docstring). Send thread
        # appends, recv thread pops on wire.ACK (entries with
        # wire_seq <= ack count). Plus cumulative TX/RX/ACK counters and
        # the per-link retransmission timer state.
        self._ack_mu = threading.Lock()
        # (ledger_seq, wire_seq, payload, pool_slot, sent_at) — payload is
        # a memoryview over pool_slot's pooled buffer (r07: the ledger
        # entry IS its send buffer; pool_slot is None only for legacy
        # bytes payloads), released back to _tx_pool when the entry pops;
        # sent_at (r08) feeds the st_ack_rtt_seconds histogram at ACK pop
        self._unacked: dict[int, list[tuple[int, int, Any, Any, float]]] = {}
        # r07 zero-copy send plane (native framing only): encode writes
        # into a pooled wire-sized slot; the slot then serves as ledger
        # payload and byte-identical retransmission source. Slots are
        # allocated lazily on first acquire, so an engine-tier peer (whose
        # C data plane has its own tx ring) never pays for this pool.
        self._tx_pool: Optional[wire.FramePool] = None
        if not tcfg.wire_compat:
            per = wire.frame_payload_bytes(spec)
            # slots sized for the v2 (traced) headers either way — 13
            # bytes of slack on a v1 peer, never an overrun on a v2 one
            self._tx_pool = wire.FramePool(
                max(
                    wire.DATA_HDR_T + per,
                    wire.BURST_HDR_T
                    + max(self._burst, self._burst_device, 1) * per,
                ),
                keep=max(1, int(self.config.frame_pool_keep)),
            )
        # per-link decode destination pools (r07 satellite): steady-state
        # decode reuses (scales, words) arrays; recycled after each applied
        # batch, dropped on LINK_DOWN
        self._rx_scratch: dict[int, wire.DecodeScratch] = {}
        self._tx_seq: dict[int, int] = {}  # wire seq of last data msg sent
        self._acked: dict[int, int] = {}
        self._rx_count: dict[int, int] = {}
        self._ack_sent: dict[int, int] = {}  # highest ACK actually delivered
        # time.monotonic() of the link's last delivery progress (ACK moved,
        # or the unacked list became non-empty), and fruitless
        # retransmission rounds since — both guarded by _ack_mu
        self._ack_progress: dict[int, float] = {}
        self._retx_rounds: dict[int, int] = {}
        # r08 observability: per-peer registry + the process hub (flight
        # recorder, native event-ring drain). None when disabled — every
        # hot-path call site pays one None-check, like the fault plan.
        # Created LAST, after every attribute the registry collector reads
        # exists and nothing below can raise: registering a half-built
        # peer with the process hub would leak its registry (and a JSONL
        # sink thread) if __init__ died before close() became reachable.
        self._obs: Optional[_PeerObs] = None
        if _obs.obs_enabled() and self.config.obs.enabled:
            self._obs = _PeerObs(self)
        self._recv_thread = threading.Thread(
            target=self._recv_loop, daemon=True, name="st-recv"
        )
        self._send_thread = threading.Thread(
            target=self._send_loop, daemon=True, name="st-send"
        )
        self._recv_thread.start()
        self._send_thread.start()

    # -- user API (the reference's three calls) -----------------------------

    def read(self) -> Any:
        """Snapshot of the shared state (reference copyToTensor)."""
        return self.st.read()

    def add(self, delta: Any) -> None:
        """Merge an additive update into the shared state; it becomes visible
        locally at once and streams to every peer asynchronously (reference
        addFromTensor)."""
        self.st.add(delta)
        if self._trace_wire and self._engine is None:
            # a local update is a fresh generation: re-seed the pending
            # trace stamp (the engine tier stamps inside st_engine_add)
            self._trace_stamp = (self.node.obs_id, self._now_ns(), 0)
        self._wake.set()

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until joined and the state stream is flowing. Replaces the
        reference's busy-wait-until-nonzero (quirk Q4: spins a core and hangs
        forever on an all-zero tensor) with an explicit handshake."""
        if not self._ready.wait(timeout):
            if self._error is not None:
                raise self._error
            raise TimeoutError(f"not ready after {timeout}s")
        if self._error is not None:
            raise self._error

    def drain(self, timeout: float = 60.0, tol: float = 0.0) -> bool:
        """Block until every outgoing link residual is down to ``tol`` RMS,
        the transport send queues are empty, AND every sent frame has been
        acknowledged by its receiver — i.e. all local updates now live in our
        neighbors' replicas (they apply + flood atomically on receive). After
        a successful drain, close() loses nothing. Use before :meth:`close`
        to leave gracefully (the reference has no flush concept at all; a
        leaving node takes its undelivered residuals down with the whole
        process, quirk Q8). A crash without drain instead falls under the
        bounded-loss arm of the delivery contract (core.SharedTensor).

        ``tol=0`` caveat: the pow2 scale policy flushes SUBNORMAL rms to
        scale 0 (idle), so residual dust below the smallest normal f32
        (~1.2e-38) can never drain — after long add sequences use a tiny
        nonzero tol (e.g. 1e-30) unless the workload is known to cancel
        exactly."""
        deadline = time.time() + timeout
        # the native engine quiesces in microseconds once residuals hit
        # zero; the Python tier needs the coarser poll to stay off its lock
        poll = 0.005 if self._engine is not None else 0.05
        while time.time() < deadline and not self._stop.is_set():
            # the carry pseudo-slot (CARRY_LINK) is excluded: an orphan by
            # definition has nobody to deliver to — its owed mass rides the
            # next re-graft, not this drain
            links = [l for l in self.st.link_ids if l >= 0]
            if all(self.st.residual_rms(l) <= tol for l in links):
                stats = [self.node.stats(l) for l in self.node.links]
                if (
                    all(s is None or s.send_queue == 0 for s in stats)
                    and self.st.inflight_total() == 0
                ):
                    return True
            time.sleep(poll)
        return False

    def leave(self, timeout: float = 60.0, tol: float = 1e-30) -> bool:
        """Graceful exit that loses nothing even MID-STREAM: (1) seal
        ingress — further incoming frames are discarded unACKed, so their
        senders keep them ledgered and re-deliver after our departure's
        re-graft; (2) drain everything we owe; (3) close. Returns the drain
        verdict.

        A bare ``drain(); close()`` has a loss window this closes: a frame
        that lands (and is applied + ACKed, flooding into our other links'
        residuals) in the instant between drain's last check and close dies
        with those residuals, and its sender — holding our ACK — never
        re-sends. Sealing first makes new arrivals un-ACKed, so the
        interrupted mass re-routes around us instead. (Wire-compat mode has
        no ACK ledger; there a mid-stream leave keeps the reference
        protocol's lossy semantics.) ``tol`` defaults just above the
        subnormal-dust floor (see :meth:`drain`)."""
        if self._engine is not None:
            self._engine.seal()  # emits the engine-tier seal event itself
        elif self._obs is not None:
            self._obs.event("seal", self.node.obs_id)
        self._sealed = True
        ok = self.drain(timeout=timeout, tol=tol)
        self.close()
        return ok

    # -- r12 cluster lifecycle (tentpole) ------------------------------------
    #
    # Consistent-cut protocol. The root pauses its own production, floods a
    # wire.SNAP marker down every child link, and each node on SNAP: pauses,
    # forwards the marker, waits for (a) every child's SNAP_ACK and (b) its
    # own in-flight ledgers to drain empty, then captures its shard (or
    # loads it — op "load" is the in-place restore) and acks up. Per-link
    # FIFO makes this a Chandy-Lamport-style cut with EMPTY channels: the
    # marker follows the sender's last pre-pause data, a child's SNAP_ACK
    # follows its last pre-capture data, and "ledger empty" means
    # everything we sent was applied — so at every capture instant both
    # ends of every link agree on the stream position and nothing is in
    # flight. No retransmission storm and no double-apply on restore, with
    # no seq surgery. Control traffic is outside the chaos classes (r06
    # rule), so a barrier completes deterministically even mid-chaos.

    @property
    def node_name(self) -> str:
        """Stable lifecycle name (LifecycleConfig.node_name, or the
        process-unique ``node-<obs_id>`` fallback)."""
        return (
            self.config.lifecycle.node_name or f"node-{self.node.obs_id}"
        )

    def snapshot_cluster(
        self,
        dirpath: str,
        snap_id: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> dict:
        """Root-initiated consistent-cut snapshot of the WHOLE tree into
        ``dirpath`` (one shard per node + MANIFEST.json with per-node
        sha256 digests). Blocks until the barrier completes; the tree is
        resumed before this returns — on success, failure, or timeout (a
        lifecycle op may fail, the cluster must never stay paused).
        Returns the result dict (``manifest``, ``duration_sec``, ...)."""
        if self._uplink is not None:
            raise RuntimeError(
                "snapshot_cluster is root-initiated: this node has an "
                "uplink (use ctl against the root, or call it there)"
            )
        return self._lc_request(
            {
                "op": "save",
                "dir": str(dirpath),
                "id": str(snap_id or f"snap-{time.monotonic_ns():x}"),
            },
            timeout,
        )

    def restore_cluster(
        self, dirpath: str, timeout: Optional[float] = None
    ) -> dict:
        """Root-initiated IN-PLACE restore of a live tree to the
        consistent cut under ``dirpath``: same barrier as
        :meth:`snapshot_cluster`, but at the quiesced instant every node
        LOADS its shard (replica + surviving links' residuals + carry +
        governor state) instead of writing one. Link wire seqs are never
        rewound — the drained-empty ledgers are what make the restored
        residuals pairwise consistent (st_engine_restore_ex). Subscriber
        links are re-seeded from the restored replica, so no FRESH mark
        can verify a read across the cut. Requires unchanged membership
        since the snapshot for full fidelity: residuals of links that no
        longer exist are dropped (their subtrees' own diff handshakes
        already repaired that mass — the load_shared contract)."""
        from ..utils import checkpoint as ckpt

        problems = ckpt.verify_manifest(dirpath)
        if problems:
            raise ValueError(
                f"snapshot at {dirpath} fails its manifest audit: "
                + "; ".join(problems)
            )
        if self._uplink is not None:
            raise RuntimeError("restore_cluster is root-initiated")
        return self._lc_request(
            {
                "op": "load",
                "dir": str(dirpath),
                "id": str(ckpt.load_manifest(dirpath).get("snap_id", "?")),
            },
            timeout,
        )

    def drain_node(self, target: str) -> None:
        """Planned migration: route a drain command (wire.CTL) down the
        tree to ``target``, which then runs the r06-proven graceful exit —
        seal ingress, drain everything it owes, close — and its children
        re-graft through the quarantine → carry → re-graft path with zero
        mass loss. Fire-and-forget: watch ``obs.top``'s drain row (or the
        membership events) for completion."""
        if self._uplink is not None:
            raise RuntimeError("drain_node is root-initiated")
        if self.config.transport.wire_compat:
            raise RuntimeError(
                "drain routing needs the native protocol's control plane"
            )
        if str(target) == self.node_name:
            raise ValueError(
                "cannot drain the root from itself — fail the root over "
                "first (master failover) or drain its children instead"
            )
        doc = {"op": "drain", "target": str(target), "from": self.node_name}
        if self._obs is not None:
            self._obs.event("ctl_cmd", self.node.obs_id, detail="drain")
        self._ctl_forward(doc, exclude=None)

    def _lc_request(self, req: dict, timeout: Optional[float]) -> dict:
        if self.config.transport.wire_compat:
            raise RuntimeError(
                "the lifecycle barrier needs the native protocol's typed "
                "control plane — the reference wire format cannot carry it "
                "(single-peer save_shared/load_shared still works)"
            )
        budget = (
            timeout
            if timeout is not None
            else self.config.lifecycle.snapshot_timeout_sec
        )
        # one barrier at a time: _lc_done/_lc_result are a single slot, so
        # concurrent API callers serialize here instead of a second
        # request's overlap-refusal waking the first with a spurious
        # failure while its barrier is still running. Results are also
        # MATCHED to requests by uid: a caller that timed out leaves its
        # barrier running, and its late result must never be handed to
        # the next caller as that caller's own verdict.
        import uuid as _uuid

        req["req"] = _uuid.uuid4().hex
        with self._lc_api_mu:
            req["deadline"] = time.monotonic() + budget
            req["budget_sec"] = budget
            self._lc_done.clear()
            self._lc_result = None
            self._lc_requests.append(req)
            self._wake.set()
            deadline = time.monotonic() + budget + 10.0
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"lifecycle {req['op']} barrier did not complete "
                        f"inside {budget}s (+grace)"
                    )
                if not self._lc_done.wait(min(remaining, 1.0)):
                    continue
                res = self._lc_result
                if res is not None and res.get("req") == req["req"]:
                    break
                # a previously-abandoned barrier's late verdict: discard
                # and keep waiting for OUR result
                self._lc_done.clear()
        if not res.get("ok"):
            raise RuntimeError(
                f"lifecycle {req['op']} failed: {res.get('error')}"
            )
        return res

    def _set_paused(self, paused: bool) -> None:
        """Quiesce (or resume) data production. Pausing is SYNCHRONOUS
        across one in-flight sender pass on BOTH tiers: the engine's
        st_engine_pause waits out its sender's pass boundary, and the
        python tier waits for two _send_loop pass increments — a pass
        already past its paused-check when the flag lands may still
        enqueue data produced from pre-pause state, and the consistent
        cut's SNAP marker must follow the last such message on every
        link, never overtake it."""
        if paused == self._paused:
            return
        self._paused = paused
        if self._engine is not None:
            self._engine.pause(paused)
        elif paused and self._send_thread.is_alive():
            g0 = self._send_pass
            deadline = time.monotonic() + 2.0
            while (
                self._send_pass < g0 + 2
                and time.monotonic() < deadline
                and not self._stop.is_set()
            ):
                self._wake.set()
                time.sleep(0.001)
        self._pause_deadline = (
            time.monotonic() + self.config.lifecycle.pause_timeout_sec
            if paused
            else 0.0
        )
        if self._obs is not None:
            self._obs.event(
                "lifecycle_pause" if paused else "lifecycle_resume",
                self.node.obs_id,
            )
        self._wake.set()

    def _lc_children(self, exclude: Optional[int] = None) -> list[int]:
        """Writer links the barrier/CTL flood covers: every attached codec
        link except the uplink, subscriber leaves (no shard, no drain —
        they re-seed from scratch), and ``exclude`` (the marker's source)."""
        up = self._uplink
        return [
            l
            for l in self.st.link_ids
            if l >= 0
            and l != up
            and l != exclude
            and l not in self._sub_links
        ]

    def _ctl_forward(self, doc: dict, exclude: Optional[int]) -> None:
        payload = wire.encode_lifecycle(wire.CTL, doc)
        for link in self._lc_children(exclude):
            try:
                self._send_blocking(link, payload)
            except Exception:
                log.exception("CTL forward failed on link %d", link)

    def _lc_begin(self, doc: dict, from_link: Optional[int]) -> None:
        """Enter the barrier (recv thread only). ``from_link`` is the
        uplink that delivered the SNAP marker; None = root-initiated."""
        if self._lc_op is not None:
            if doc.get("id") == self._lc_op["id"]:
                return  # duplicate marker (e.g. replayed): already in it
            msg = (
                f"{self.node_name}: lifecycle barrier overlap "
                f"({self._lc_op['id']} active, {doc.get('id')} refused)"
            )
            log.warning(msg)
            self._lc_errors += 1
            if from_link is None:
                self._lc_result = {
                    "ok": False, "error": msg, "req": doc.get("req"),
                }
                self._lc_done.set()
            else:
                # NACK so the parent's barrier completes with the error
                # recorded instead of hanging on this subtree
                self._send_blocking(
                    from_link,
                    wire.encode_lifecycle(
                        wire.SNAP_ACK,
                        {"id": doc.get("id"), "nodes": [], "errors": [msg]},
                    ),
                )
            return
        op = {
            "op": doc.get("op", "save"),
            "id": str(doc.get("id")),
            "dir": str(doc.get("dir", "")),
            "req": doc.get("req"),
            "from": from_link,
            "t0": time.monotonic(),
            "deadline": doc.get("deadline"),
            # the barrier's time budget: the root's remaining budget as
            # carried by the marker; a budget-less marker (shouldn't
            # happen from this build's roots) falls back to the LOCAL
            # pause timeout — the conservative never-stay-paused default
            "budget": float(
                doc.get(
                    "budget_sec",
                    self.config.lifecycle.snapshot_timeout_sec
                    if from_link is None
                    else self.config.lifecycle.pause_timeout_sec,
                )
            ),
            "waiting": set(self._lc_children(from_link)),
            "entries": [],
            "errors": [],
            "marked": False,  # markers flood from _lc_tick once the
            # paused data plane has fully flushed (ordering note there)
            "captured": False,
            "acked": False,  # SNAP_ACK delivered (retried until it is)
        }
        self._lc_op = op
        self._set_paused(True)
        # the pause safety deadline scales to the BARRIER's budget, not
        # the bare pause_timeout: a deep tree's barrier legitimately
        # outlives the default 30 s (slow drains), and a captured child
        # auto-resuming mid-barrier would silently tear the cut the root
        # then reports as ok. The marker carries the root's remaining
        # budget down (+5 s RESUME-propagation grace); the deadline still
        # bounds a dead-root wedge.
        self._pause_deadline = time.monotonic() + op["budget"] + 5.0
        if self._obs is not None:
            self._obs.event(
                "snap_begin", self.node.obs_id, arg=len(op["waiting"]),
                detail=op["op"],
            )

    def _lc_mark_children(self, op: dict) -> None:
        """Flood the SNAP marker down — only AFTER every data message this
        node will ever send pre-cut has been DELIVERED: _set_paused already
        synchronized the in-flight sender pass, the device-tier pipeline
        gauge must read empty (a paused pipeline only drains), and every
        unacked ledger must be empty. The ledger condition is what makes
        the cut sound under LOSS: a chaos-dropped frame's go-back-N
        retransmission would otherwise arrive AFTER the marker — applied
        past the receiver's capture while our shard records it delivered,
        i.e. mass in neither shard (fatal for the in-place restore, which
        has no diff-join to re-derive it). Paused production + active
        retransmission drain the ledgers in bounded time; a black-holed
        link tears down at ack_retry_limit and leaves the barrier through
        the LINK_DOWN error path."""
        if self._engine is None and self._pipe_frames > 0:
            return  # pipeline still draining; next tick re-checks
        if self.st.inflight_total() != 0:
            return  # undelivered pre-cut data; retransmission is on it
        op["marked"] = True
        now = time.monotonic()
        remaining = (
            op["deadline"] - now
            if op["from"] is None and op.get("deadline")
            else op["budget"] - (now - op["t0"])
        )
        fwd = wire.encode_lifecycle(
            wire.SNAP,
            {
                "op": op["op"], "id": op["id"], "dir": op["dir"],
                "parent": self.node_name,
                # the root's remaining budget rides the marker so every
                # node's pause deadline covers the WHOLE barrier
                "budget_sec": max(5.0, remaining),
            },
        )
        for link in list(op["waiting"]):
            if not self._send_blocking(link, fwd):
                op["waiting"].discard(link)
                op["errors"].append(
                    f"{self.node_name}: SNAP marker send failed on link "
                    f"{link}"
                )

    def _lc_quiesced(self) -> bool:
        """Paused AND nothing in flight: every unacked ledger empty (our
        sends were applied by their receivers) and every transport send
        queue drained (our markers/acks actually left)."""
        if self.st.inflight_total() != 0:
            return False
        for link in self.node.links:
            s = self.node.stats(link)
            if s is not None and s.send_queue != 0:
                return False
        return True

    def _lc_tick(self) -> None:
        """One barrier-driving pass (recv thread, every loop iteration)."""
        while self._lc_requests:
            self._lc_begin(self._lc_requests.popleft(), None)
        op = self._lc_op
        now = time.monotonic()
        if op is None:
            if (
                self._paused
                and self._pause_deadline
                and now > self._pause_deadline
            ):
                # never-leave-paused safety net (op state already gone)
                log.warning("lifecycle pause expired with no barrier — resuming")
                self._lc_errors += 1
                self._set_paused(False)
            self._ctl_poll(now)
            return
        if op["from"] is None:
            if op.get("deadline") and now > op["deadline"]:
                missing = sorted(op["waiting"])
                op["errors"].append(
                    f"{self.node_name}: barrier timeout "
                    f"(awaiting links {missing})" if missing else
                    f"{self.node_name}: barrier timeout (quiesce)"
                )
                self._lc_finish(ok=False)
                return
        elif now > self._pause_deadline:
            # RESUME never arrived (root/parent died mid-barrier): unpause
            # rather than stay frozen — the op is abandoned
            log.warning(
                "lifecycle barrier %s: no RESUME before the pause "
                "deadline — auto-resuming", op["id"],
            )
            self._lc_errors += 1
            self._lc_op = None
            self._set_paused(False)
            return
        if not op["marked"]:
            self._lc_mark_children(op)
        if op["captured"]:
            if op["from"] is not None and not op["acked"]:
                # the SNAP_ACK send failed (or over-cap encode fell back)
                # on an earlier tick: retry until delivered or the pause
                # deadline abandons the barrier — a latched-but-unacked
                # capture would otherwise wedge the parent into its
                # timeout with no error naming the cause
                self._lc_send_ack(op)
            return
        if (
            not op["marked"]
            or op["waiting"]
            or not self._lc_quiesced()
        ):
            return
        # subtree complete + locally quiesced: the cut instant for this node
        try:
            if op["op"] == "save":
                entry = self._write_shard(op["dir"], op["id"])
                op["entries"].append(entry)
                self._snap_total += 1
            else:
                self._load_shard_inplace(op["dir"])
                op["entries"].append(
                    {"node": self.node_name, "restored": True}
                )
                self._restore_total += 1
        except Exception as e:
            log.exception("lifecycle %s failed at %s", op["op"], self.node_name)
            op["errors"].append(f"{self.node_name}: {e!r}")
            self._lc_errors += 1
        op["captured"] = True
        if op["from"] is not None:
            self._lc_send_ack(op)
            # stay paused until the root's RESUME releases the barrier
        else:
            self._lc_finish(ok=not op["errors"])

    def _lc_send_ack(self, op: dict) -> None:
        doc = {
            "id": op["id"],
            "nodes": op["entries"],
            "errors": op["errors"],
        }
        try:
            payload = wire.encode_lifecycle(wire.SNAP_ACK, doc)
        except ValueError:
            # subtree manifest exceeded the wire cap (clusters past the
            # digest's own per-node bound): deliver the verdict with the
            # entries dropped rather than wedging the whole barrier — the
            # root fails it honestly, naming this node
            doc = {
                "id": op["id"],
                "nodes": [],
                "errors": op["errors"][:8]
                + [
                    f"{self.node_name}: subtree manifest exceeded the wire "
                    f"cap ({len(op['entries'])} shard entries dropped)"
                ],
            }
            payload = wire.encode_lifecycle(wire.SNAP_ACK, doc)
        if self._send_blocking(op["from"], payload):
            op["acked"] = True

    def _lc_finish(self, ok: bool) -> None:
        """Root only: write the manifest (save op), release the barrier
        down the tree, resume, and hand the verdict to the waiter. Runs on
        EVERY exit path — the cluster never stays paused."""
        op = self._lc_op
        assert op is not None and op["from"] is None
        dur = time.monotonic() - op["t0"]
        result: dict = {
            "ok": ok,
            "op": op["op"],
            "id": op["id"],
            "req": op.get("req"),
            "dir": op["dir"],
            "duration_sec": dur,
            "nodes": len(op["entries"]),
            "errors": op["errors"],
        }
        if op["errors"]:
            result["error"] = "; ".join(str(e) for e in op["errors"])
        if ok and op["op"] == "save":
            from ..utils import checkpoint as ckpt

            try:
                result["manifest"] = ckpt.write_manifest(
                    op["dir"], op["id"], op["entries"],
                    extra={"root": self.node_name, "duration_sec": dur},
                )
            except OSError as e:
                result["ok"] = False
                result["error"] = f"manifest write failed: {e}"
        self._snap_last_dur = dur
        resume = wire.encode_lifecycle(wire.RESUME, {"id": op["id"]})
        for link in self._lc_children():
            self._send_blocking(link, resume)
        self._lc_op = None
        self._set_paused(False)
        if self._obs is not None:
            self._obs.event(
                "snap_done", self.node.obs_id,
                arg=result["nodes"], detail=op["op"],
            )
        self._lc_result = result
        self._lc_done.set()

    def _write_shard(self, dirpath: str, snap_id: str) -> dict:
        """Capture this node's shard at the (quiesced) cut instant. The
        engine capture is ONE native lock acquisition (snapshot_ex), so
        sign2 residual planes, in-flight cascade frames and governor state
        cannot tear; the python tier's snapshot_all has the same contract
        under its state lock."""
        from ..utils import checkpoint as ckpt

        up = self._uplink
        if self._engine is not None:
            values, links, meta = self._engine.snapshot_ex()
        else:
            values, links = self.st.snapshot_all()
            values = np.asarray(values, np.float32)
            meta = {}
            with self._ack_mu:
                tx = dict(self._tx_seq)
            for lid in links:
                if lid < 0:
                    continue
                meta[lid] = {
                    "tx_seq": tx.get(lid, 0),
                    "rx_count": self._rx_count.get(lid, 0),
                    "prec": 1,
                    "sub": lid in self._sub_links,
                }
        entries = []
        for lid, resid in links.items():
            if lid < 0:
                entries.append(
                    {
                        "id": lid, "role": "carry",
                        "resid": np.asarray(resid, np.float32),
                    }
                )
                continue
            m = meta.get(lid, {})
            sub = bool(m.get("sub")) or lid in self._sub_links
            entries.append(
                {
                    "id": lid,
                    "role": "up" if lid == up else ("sub" if sub else "child"),
                    "tx_seq": m.get("tx_seq", 0),
                    "rx_count": m.get("rx_count", 0),
                    "prec": m.get("prec", 1),
                    # subscriber links persist meta only: a read-only leaf
                    # re-seeds from scratch on restore
                    "resid": None if sub else np.asarray(resid, np.float32),
                }
            )
        entry = ckpt.save_cluster_shard(
            dirpath,
            self.node_name,
            snap_id,
            self.st.spec.layout_digest(),
            values,
            entries,
            wire_version=self._wire_version,
        )
        if self._obs is not None:
            self._obs.event(
                "snap_shard", self.node.obs_id, arg=len(entries)
            )
        return entry

    def _load_shard_inplace(self, dirpath: str) -> None:
        """The in-place restore step (op "load"), at the quiesced barrier
        instant: replica + surviving writer links' residuals + carry +
        governor state from this node's shard, then a forced re-seed of
        every subscriber link from the restored replica — across the cut a
        subscriber's state is superseded and NO seq gap would ever expose
        it (the falsely-verified-read hazard the lifecycle test pins)."""
        import os as _os

        from ..utils import checkpoint as ckpt

        path = _os.path.join(dirpath, ckpt.shard_filename(self.node_name))
        shard = ckpt.load_cluster_shard(path)
        if shard["layout"] != self.st.spec.layout_digest():
            raise ValueError(
                f"shard {path} was written for a different table layout"
            )
        live = set(self.st.link_ids)
        links: dict[int, np.ndarray] = {}
        meta: dict[int, dict] = {}
        for lid, ent in shard["links"].items():
            if ent.get("role") == "carry":
                if ent.get("resid") is not None:
                    links[CARRY_LINK] = ent["resid"]
                continue
            if ent.get("role") == "sub" or ent.get("resid") is None:
                continue
            if lid in live:
                links[lid] = ent["resid"]
                meta[lid] = {"prec": ent.get("prec", 1)}
        if self._engine is not None:
            self._engine.restore_ex(shard["values"], links, meta)
        else:
            with self.st._lock:
                self.st.values = self.st._asarray(shard["values"])
                for lid, r in links.items():
                    if lid in self.st._links or lid == CARRY_LINK:
                        self.st._links[lid] = self.st._asarray(r)
        for lid, rng in list(self._sub_links.items()):
            self._attach_sub(lid, rng)
        self._wake.set()

    def _restore_at_startup(self, path: str) -> None:
        """Full-cluster restart restore (LifecycleConfig.restore_path),
        before the data plane starts. Values load into the replica; a
        NON-master node's checkpointed uplink residual (+ carry) becomes
        the re-graft carry, so the normal join handshake re-delivers
        exactly the owed up-flow (snapshot claims ``values - carry`` as
        tree-known; the diff seed covers the rest). The master drops its
        carry — its replica is now the authoritative seed and every
        child's diff join pulls the missing mass from it (the
        BECAME_MASTER discipline). Child-link residuals are discarded on
        BOTH: the children's own re-join diffs re-derive the down-flow
        (checkpoint.restore_carry_from_shard)."""
        from ..utils import checkpoint as ckpt

        shard = ckpt.load_cluster_shard(path)
        if shard["layout"] != self.st.spec.layout_digest():
            raise ValueError(
                f"restore shard {path} was written for a different table "
                f"layout"
            )
        values = shard["values"]
        carry = None if self.is_master else ckpt.restore_carry_from_shard(shard)
        if self._engine is not None:
            self._engine.restore_state(
                values, {} if carry is None else {CARRY_LINK: carry}
            )
        else:
            with self.st._lock:
                self.st.values = self.st._asarray(values)
                if carry is not None:
                    self.st._links[CARRY_LINK] = self.st._asarray(carry)
        self._restored_from = path
        self._restore_total += 1
        log.info(
            "restored %s from shard %s (snap %s)%s",
            self.node_name, path, shard["meta"].get("snap_id"),
            "" if carry is None else " with re-graft carry",
        )

    def _start_drain(self) -> None:
        """This node is the CTL drain target: run the graceful exit on a
        helper thread (leave() blocks and joins the recv thread — it must
        never run ON the recv thread)."""
        if self._draining:
            return
        self._draining = True
        self._drain_total += 1
        if self._obs is not None:
            self._obs.event("drain_begin", self.node.obs_id)
        grace = self.config.lifecycle.drain_grace_sec

        def _run():
            try:
                ok = self.leave(timeout=grace)
                log.info(
                    "drain of %s %s", self.node_name,
                    "complete" if ok else "timed out (closed anyway)",
                )
            except Exception:
                log.exception("drain of %s failed", self.node_name)

        threading.Thread(target=_run, daemon=True, name="st-drain").start()

    def _handle_ctl_msg(self, doc: dict, from_link: Optional[int]) -> None:
        op = doc.get("op")
        if op == "drain":
            if doc.get("target") == self.node_name:
                self._start_drain()
            else:
                self._ctl_forward(doc, exclude=from_link)
        else:
            log.warning("ignoring unknown CTL op %r", op)

    def _ctl_poll(self, now: float) -> None:
        """Root-side operator command channel: poll
        ``LifecycleConfig.ctl_dir`` for a cmd.json written by
        ``python -m shared_tensor_tpu.ctl`` and execute it on a worker
        thread (a snapshot blocks on the barrier this recv thread drives)."""
        lc = self.config.lifecycle
        if not lc.ctl_dir or self._uplink is not None:
            return
        if now - self._ctl_last_poll < 0.25:
            return
        self._ctl_last_poll = now
        import json as _json
        import os as _os

        cmd_path = _os.path.join(lc.ctl_dir, "cmd.json")
        try:
            with open(cmd_path) as f:
                cmd = _json.load(f)
            _os.unlink(cmd_path)  # claim
        except (OSError, ValueError):
            return  # absent, or mid-write; next poll gets it
        if self._obs is not None:
            self._obs.event(
                "ctl_cmd", self.node.obs_id, detail=str(cmd.get("op"))
            )
        threading.Thread(
            target=self._ctl_execute, args=(cmd,), daemon=True,
            name="st-ctl",
        ).start()

    def _ctl_execute(self, cmd: dict) -> None:
        import os as _os

        res: dict = {"req_id": cmd.get("req_id"), "op": cmd.get("op")}
        try:
            op = cmd.get("op")
            if op == "snapshot":
                r = self.snapshot_cluster(cmd["dir"], cmd.get("id"))
                res.update(
                    ok=True, id=r["id"], nodes=r["nodes"],
                    duration_sec=r["duration_sec"],
                    manifest=r.get("manifest"),
                )
            elif op == "restore":
                r = self.restore_cluster(cmd["dir"])
                res.update(
                    ok=True, id=r["id"], nodes=r["nodes"],
                    duration_sec=r["duration_sec"],
                )
            elif op == "drain":
                self.drain_node(cmd["target"])
                res.update(ok=True, target=cmd["target"], initiated=True)
            else:
                res.update(ok=False, error=f"unknown ctl op {op!r}")
        except Exception as e:
            res.update(ok=False, error=str(e))
        from ..utils.checkpoint import atomic_write_json

        lc = self.config.lifecycle
        path = _os.path.join(lc.ctl_dir, "result.json")
        try:
            atomic_write_json(path, res)
        except Exception as e:
            # the CLI is polling for SOME verdict: even a non-serializable
            # result value must not leave it timing out undiagnosed
            log.exception("ctl result write failed")
            try:
                atomic_write_json(
                    path,
                    {
                        "req_id": res.get("req_id"), "ok": False,
                        "error": f"result write failed: {e}",
                    },
                )
            except Exception:
                pass

    def close(self) -> None:
        """Leave the tree. Peers survive and re-graft (the reference prints an
        apology and exit(-1)s the entire process instead — quirk Q8)."""
        self._stop.set()
        self._wake.set()
        for t in (self._send_thread, self._recv_thread):
            t.join(timeout=5.0)
        if self._engine is not None:
            # engine threads block inside the node's queues/condvars: they
            # must stop BEFORE the node is torn down
            self._engine.stop()
        if self._obs is not None:
            # final native-ring drain + sink/registry teardown, BEFORE the
            # node closes so the close-path events still merge in
            self._obs.close()
        self.node.close()
        if self._engine is not None:
            self._engine.destroy()

    # -- introspection -------------------------------------------------------

    @property
    def ready(self) -> bool:
        return self._ready.is_set()

    def _delivery_counts(self) -> tuple[int, int, int, int, int]:
        """(frames_out, frames_in, updates, msgs_out, msgs_in) — ONE
        engine-counter snapshot when native (separate reads would mix
        instants and could show e.g. msgs_in > frames_in mid-run)."""
        if self._engine is not None:
            c = self._engine._counters()
            return int(c[0]), int(c[1]), int(c[2]), int(c[3]), int(c[4])
        fo, fi = self.st.frames_out, self.st.frames_in
        up = self.st.updates
        if self.config.transport.wire_compat:
            # no ACK ledger in the reference protocol: one frame == one
            # message (metrics() taxonomy)
            return fo, fi, up, fo, fi
        with self._ack_mu:
            mo = sum(self._acked.values()) + sum(
                len(v) for v in self._unacked.values()
            )
            mi = sum(self._rx_count.values())
        return fo, fi, up, mo, mi

    def _obs_collect(self) -> dict:
        """Registry collector: the canonical-schema view of everything this
        peer can report that is not a live histogram — sampled once per
        snapshot/scrape (obs/schema.py is the name authority)."""
        import math

        out: dict = {}
        fo, fi, up, mo, mi = self._delivery_counts()
        out["st_frames_out_total"] = fo
        out["st_frames_in_total"] = fi
        out["st_updates_total"] = up
        out["st_msgs_out_total"] = mo
        out["st_msgs_in_total"] = mi
        out["st_inflight_msgs"] = self.st.inflight_total()
        # r07 buffer-pool planes — the zero-per-message-allocation
        # assertion: in steady state the acquire counters grow while the
        # alloc/miss counters stay flat (every buffer is a reuse).
        # st_tx_slot_* is the frame-slot ring (engine tx ring, or
        # wire.FramePool on the Python tier); st_transport_* is the C
        # transport's per-link tx/rx recycling.
        if self._engine is not None:
            p = self._engine.pool_stats()
            out["st_tx_slot_acquires_total"] = p["tx_slot_acquires"]
            out["st_tx_slot_alloc_events_total"] = p["tx_slot_alloc_events"]
            out["st_tx_slots_allocated"] = p["tx_slots_allocated"]
        elif self._tx_pool is not None:
            p = self._tx_pool.stats()
            out["st_tx_slot_acquires_total"] = p["tx_slot_acquires"]
            out["st_tx_slot_alloc_events_total"] = p["tx_slot_alloc_events"]
            out["st_tx_slots_allocated"] = p["tx_slots_free"]
        tp = self.node.pool_stats()
        out["st_transport_tx_acquires_total"] = tp["tx_acquires"]
        out["st_transport_tx_misses_total"] = tp["tx_misses"]
        out["st_transport_rx_acquires_total"] = tp["rx_acquires"]
        out["st_transport_rx_misses_total"] = tp["rx_misses"]
        out["st_transport_zc_msgs_total"] = tp["zc_msgs"]
        # r10 writer-side serving gauges/counters. The python-tier counts
        # are authoritative only on the python tier (the engine's C sender
        # owns them otherwise and obs_stats() below overrides).
        out["st_sub_links"] = len(self._sub_links)
        out["st_sub_msgs_out_total"] = self._sub_msgs_out
        out["st_sub_fresh_out_total"] = self._sub_fresh_out
        # r12 lifecycle telemetry (obs.top's lifecycle rows; schema.py).
        # st_wire_version rides the per-node digest breakdown so
        # ``ctl versions`` can audit a rolling upgrade from the root.
        op = self._lc_op
        out["st_wire_version"] = self._wire_version
        out["st_lifecycle_paused"] = 1 if self._paused else 0
        out["st_snapshot_in_progress"] = (
            1 if op is not None and op.get("op") == "save" else 0
        )
        out["st_snapshot_shards_acked"] = self._snap_acks
        out["st_snapshot_total"] = self._snap_total
        out["st_snapshot_last_duration_seconds"] = self._snap_last_dur
        out["st_restore_total"] = self._restore_total
        out["st_drain_in_progress"] = 1 if self._draining else 0
        out["st_drain_total"] = self._drain_total
        out["st_lifecycle_errors_total"] = self._lc_errors
        if self._engine is not None:
            out.update(self._engine.obs_stats())
        out["st_corrupt_scales_zeroed_total"] = wire.corrupt_scales_zeroed()
        from ..obs import events as _events

        out["st_obs_events_dropped_total"] = _events.native_dropped()
        # r09 convergence telemetry. st_residual_norm: the L2 norm over
        # EVERY error-feedback residual (carry slot included — that is
        # owed mass too), derived from the per-link RMS both tiers already
        # serve: norm^2 = sum(rms_l^2 * n). 0 = quiesced, nothing owed.
        # The python tier's link_ids lists the carry pseudo-slot itself;
        # the engine keeps its carry outside the link map, so query it
        # explicitly (st_engine_residual_rms answers -1 with the carry).
        ss = 0.0
        n = self.st.spec.total_n
        links = list(self.st.link_ids)
        if self._engine is not None:
            links.append(CARRY_LINK)
        for link in links:
            rms = self.st.residual_rms(link)
            ss += rms * rms * n
        out["st_residual_norm"] = math.sqrt(ss)
        # per-link staleness/hops of the latest traced apply: the engine
        # tier serves them over the st_engine_link_obs ABI; the python
        # tier records them at _note_trace time
        if self._engine is not None:
            for link in self.st.link_ids:
                if link < 0:
                    continue
                lo = self._engine.link_obs(link)
                if lo is not None and lo[1] > 0:
                    out[_schema.link_key("st_staleness_seconds", link)] = lo[0]
                    out[_schema.link_key("st_update_hops_last", link)] = lo[1]
        else:
            # r18: live aging — the stored value is the origin GENERATION
            # stamp; its age is computed NOW, so a stalled link's gauge
            # grows between applies (the SLO's staleness signal)
            now_ns = self._now_ns()
            for link, (gen, hop) in list(self._staleness.items()):
                out[_schema.link_key("st_staleness_seconds", link)] = max(
                    0.0, (now_ns - gen) / 1e9
                )
                out[_schema.link_key("st_update_hops_last", link)] = hop
            out["st_traced_msgs_in_total"] = self._traced_in
        # r18 origin attribution + clock plane: the origin node of each
        # link's freshest traced apply (python tier; the engine tier's
        # arrives via the native-ring tap), and this node's estimated
        # offset to the tree root — the health analyzer joins the two to
        # widen staleness to offset-corrected +/- uncertainty.
        for link, origin in list(self._stale_origin.items()):
            out[_schema.link_key("st_staleness_origin", link)] = origin
        if self._clock.known:
            out["st_clock_offset_seconds"] = self._clock.offset_seconds
            out["st_clock_uncertainty_seconds"] = (
                self._clock.uncertainty_seconds
            )
        out["st_clock_probes_total"] = self._clock.probes
        if self._health is not None:
            out.update(self._health.metrics())
        for link in self.node.links:
            s = self.node.stats(link)
            if s is not None:
                out[_schema.link_key("st_link_bytes_out_total", link)] = (
                    s.bytes_out
                )
                out[_schema.link_key("st_link_bytes_in_total", link)] = (
                    s.bytes_in
                )
                out[_schema.link_key("st_link_wire_msgs_out_total", link)] = (
                    s.frames_out
                )
                out[_schema.link_key("st_link_wire_msgs_in_total", link)] = (
                    s.frames_in
                )
                out[_schema.link_key("st_link_residual_rms", link)] = (
                    self.st.residual_rms(link)
                )
                out[_schema.link_key("st_link_send_queue", link)] = s.send_queue
                out[_schema.link_key("st_link_recv_queue", link)] = s.recv_queue
            # r11 stripe telemetry (per logical link): negotiated and
            # surviving socket counts + stripe lifecycle totals
            st = self.node.stripe_stats(link)
            if st is not None and st["stripes"] > 1:
                out[_schema.link_key("st_stripe_count", link)] = st["stripes"]
                out[_schema.link_key("st_stripe_live", link)] = st["live"]
                out["st_stripe_deaths_total"] = (
                    out.get("st_stripe_deaths_total", 0) + st["deaths"]
                )
                out["st_stripe_reroutes_total"] = (
                    out.get("st_stripe_reroutes_total", 0) + st["reroutes"]
                )
            # r14 shm-lane telemetry (per logical link): lane state plus
            # the lane's own message/byte traffic (also folded into the
            # link wire counters above — these isolate the shm share)
            sh = self.node.shm_stats(link)
            if sh is not None and sh["state"] > 0:
                out[_schema.link_key("st_shm_active", link)] = sh["state"]
                out["st_shm_msgs_out_total"] = (
                    out.get("st_shm_msgs_out_total", 0) + sh["msgs_out"]
                )
                out["st_shm_msgs_in_total"] = (
                    out.get("st_shm_msgs_in_total", 0) + sh["msgs_in"]
                )
                out["st_shm_bytes_out_total"] = (
                    out.get("st_shm_bytes_out_total", 0) + sh["bytes_out"]
                )
                out["st_shm_bytes_in_total"] = (
                    out.get("st_shm_bytes_in_total", 0) + sh["bytes_in"]
                )
        # r11 per-link wire precision (engine tier; 1-bit everywhere else)
        if self._engine is not None:
            for link in self.st.link_ids:
                if link < 0:
                    continue
                prec = self._engine.link_precision(link)
                if prec > 0:
                    out[_schema.link_key("st_link_precision", link)] = prec
        return out

    def metrics(
        self, canonical: bool = True, cluster: bool = False
    ) -> dict:
        """Observability the reference entirely lacks (SURVEY.md §5.5).

        Returns the flat canonical-schema view (obs/schema.py is the name
        authority): delivery counters, buffer-pool planes, per-link
        gauges, engine aggregates — all under ``st_*`` names.
        ``cluster=True`` (r09) returns the merged WHOLE-TREE digest from
        this node's vantage — own registry + every subtree digest
        (obs/aggregate.py); at the root that is the cluster.

        The r08 legacy NESTED shape (``frames_out`` / ``delivery.*`` /
        ``links[i].*`` keys) was kept "for one release" as a deprecated
        alias view and is REMOVED as of r13 — ``canonical=False`` raises,
        and tools/lint_metrics.py forbids the alias keys from returning.
        The canonical twins carry byte-equal values: the removal renamed
        keys, never accounting.

        Counter taxonomy (ONE definition per number, reconcilable across
        layers — round-3 verdict Weak #6):

        - ``st_frames_out_total`` / ``st_frames_in_total`` — CODEC frames:
          non-idle quantized frames handed toward the wire / applied from
          it. A burst message carries many; idle (all-zero-scale) frames
          count nowhere. Invariant: a quiesced single-writer pair has
          ``sender frames_out == receiver frames_in``.
        - ``st_msgs_out_total`` / ``st_msgs_in_total`` — wire DATA/BURST
          messages sent / received (what the ACK ledger tracks; an
          undecodable data message still counts on the receive side).
        - ``st_inflight_msgs`` — sent-but-unacked messages; 0 after a
          successful :meth:`drain`. Acked messages = msgs_out - inflight.
          Wire-compat exception: the reference protocol has no ACK
          (delivery degrades to ack-on-enqueue), so there one frame == one
          message — msgs == frames and inflight is always 0.
        - ``st_link_wire_msgs_out_total{link=}`` / ``..in..`` —
          transport-level messages on the socket: data AND control
          (ACK/SYNC/CHUNK/...), excluding keepalives; >= the data-message
          counts above by exactly the control traffic.
          ``st_link_bytes_*`` include framing and keepalives. Wire-compat
          caveat: a compat keepalive IS a real zero-scale frame on the
          wire, indistinguishable at the transport layer — so the
          RECEIVE-side wire count includes idle-period keepalives there
          (the send side still excludes them).
        """
        if cluster:
            return self.cluster_metrics()
        if not canonical:
            raise ValueError(
                "the legacy nested peer.metrics() shape was removed (r13);"
                " consume the canonical st_* schema (obs/schema.py)"
            )
        # the registry snapshot merges the collector (this peer's sampled
        # counters) with the LIVE instruments (histograms, python-tier
        # delivery counters); with obs disabled the collector view alone
        # still serves the schema
        if self._obs is not None:
            return self._obs.registry.snapshot()
        return self._obs_collect()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- send side -----------------------------------------------------------

    def _send_loop(self) -> None:
        if self._engine is not None:
            return  # the native engine's own sender thread owns this path
        compat = self.config.transport.wire_compat
        interval = self.config.sync_interval_sec
        # Pipelined frame production (round-2 verdict Weak #2): up to
        # ``send_pipeline_depth`` dispatched-but-unfetched frames per link,
        # each with its device->host copy started asynchronously at dispatch
        # time. Quantizes chain on device, their transfers overlap each other
        # and the host's encode+socket work, so on a high-latency device link
        # the frame rate is bandwidth-bound, not round-trip-bound.
        # Error-feedback ordering is safe: the residual update happens at
        # dispatch time under SharedTensor's lock.
        #
        # Delivery accounting: a sent frame stays in SharedTensor's in-flight
        # ledger until the RECEIVER acknowledges it (wire.ACK, handled in
        # _on_message) — enqueue into the native send queue is NOT delivery
        # (a link can die with queued frames, and their error feedback would
        # be silently lost; measured as the regraft divergence flake). In
        # wire-compat mode the reference protocol has no ACK, so delivery
        # degrades to ack-on-enqueue (the C peer loses everything on death
        # anyway, quirk Q8).
        # r07 double-buffered encode/drain: node.send() copies the pooled
        # slot into the C transport's (recycled) tx buffer and returns the
        # moment it is QUEUED — the C sender thread drains the socket while
        # this loop encodes the next batch into a fresh slot. The pool
        # makes that overlap allocation-free: encode k+1 and the socket
        # write of k proceed concurrently with zero per-message heap
        # traffic on either side (wire.FramePool here, the transport's
        # BufPool below).
        # numpy host tier: quantize is synchronous host work — pipelining
        # just hoards the SharedTensor lock; depth only pays on device tiers
        # where dispatch/transfer are async.
        depth = 1 if self.st.host_tier else max(1, int(self.config.send_pipeline_depth))
        pipe: dict[int, deque] = {}
        hot: set[int] = set()  # links whose last finished frame carried data
        while not self._stop.is_set():
            self._send_pass += 1  # pass boundary (_set_paused's sync wait)
            self._pipe_frames = sum(len(q) for q in pipe.values())
            sent_any = False
            links = [l for l in self.st.link_ids if l >= 0]  # skip CARRY_LINK
            for stale in [l for l in pipe if l not in links]:
                del pipe[stale]  # LINK_DOWN already rolled their ledger back
                hot.discard(stale)
            for link in links:
                if link in self._sub_links:
                    # r10 subscriber link: unledgered send path (no window,
                    # no unacked entries, no retransmission) + FRESH beats.
                    # Paused (r12 quiesce): no production, but an already-
                    # DRAINED link keeps its FRESH beat so a current
                    # subscriber can still verify its bound across the
                    # barrier (an undrained one gets no mark — a read
                    # across the cut must refuse, never falsely verify).
                    if self._paused:
                        self._sub_fresh_beat(link)
                        continue
                    if self._send_sub(link):
                        sent_any = True
                    continue
                if self._paused and not pipe.get(link):
                    # r12 lifecycle quiesce: no NEW production. Frames
                    # already dispatched into the device pipeline still
                    # finish and send below (their error feedback is
                    # applied; the barrier waits for their ACKs), the
                    # pipeline just stops topping up.
                    continue
                if not compat and self._window_full(link):
                    # go-back-N send window: a link whose unacked ledger is
                    # full (stalled peer, black hole in progress) produces
                    # no new frames — bounds both the retained-payload
                    # memory and the retransmittable tail; residual mass
                    # keeps accumulating and quantizes once ACKs reopen
                    # the window (or teardown rolls it into the carry)
                    continue
                if self._burst > 1:
                    # Host-tier burst path: K residual halvings quantized in
                    # one synchronous call, ONE message, ONE ledger entry,
                    # ONE receiver ACK (Config.frame_burst rationale).
                    out = self.st.begin_frame_burst(link, self._burst)
                    if out is None:
                        continue  # link dropped concurrently
                    seq, burst = out
                    if not burst:
                        self.st.ack_frame(link, seq)  # idle: no-op burst
                        hot.discard(link)
                        continue
                    hot.add(link)
                    payload = self._register_data(
                        link,
                        seq,
                        lambda buf, s, t: wire.encode_burst_into(
                            burst, self.st.spec, s, buf, trace=t
                        ),
                    )
                    # crash point: frames ledgered + error feedback applied,
                    # message NOT yet on the wire — death here must roll the
                    # whole burst into the re-graft carry
                    self._fault_point("mid-burst")
                    if self._send_blocking(link, payload, data=True):
                        sent_any = True
                    else:
                        self.st.nack_frame(link)
                    continue
                # Device tier: K-frame bursts when enabled — ONE dispatch +
                # ONE device->host fetch per message (self._burst_device;
                # a tunneled/PCIe device link pays its round trip per
                # FETCH, so K frames per fetch multiply delivered residual
                # per round trip exactly as BURST does on host).
                dev_burst = (
                    not compat
                    and not self.st.host_tier
                    and self._burst_device > 1
                )
                q = pipe.setdefault(link, deque())
                # top up: a cold (idle) link risks one speculative frame per
                # wake tick; a hot link keeps the full pipeline busy —
                # and a paused (r12 quiesce) one only drains, never refills
                target = (
                    0 if self._paused else depth if link in hot else 1
                )
                while len(q) < target:
                    df = (
                        self.st.begin_frame_burst_device(
                            link, self._burst_device
                        )
                        if dev_burst
                        else self.st.begin_frame(link)
                    )
                    if df is None:
                        break  # link dropped concurrently
                    for arr in df[1]:
                        try:
                            arr.copy_to_host_async()
                        except AttributeError:
                            pass  # non-jax array (already host-side)
                    q.append(df)
                if not q:
                    continue

                def _finish(d):
                    return (
                        self.st.finish_frame_burst(d)
                        if dev_burst
                        else self.st.finish_frame(d)
                    )

                seq, df = q.popleft()
                frame = _finish(df)
                while frame is None:
                    # Idle frame (a no-op: scale 0 left the residual
                    # untouched): ack it and drain the remaining speculative
                    # frames — they must be FINISHED, not dropped (an add()
                    # may have raced the dispatches, making a later one
                    # non-idle, and its error feedback is already applied;
                    # dropping it would lose that delta forever).
                    self.st.ack_frame(link, seq)
                    hot.discard(link)
                    if not q:
                        break
                    seq, df = q.popleft()
                    frame = _finish(df)
                if frame is None:
                    continue
                hot.add(link)
                # registered (with its wire seq) BEFORE sending: the
                # receiver's ACK must never race ahead of the ledger entry
                # it acknowledges
                if compat:
                    payload = wire.encode_compat_frame(frame, self.st.spec)
                elif dev_burst:
                    payload = self._register_data(
                        link,
                        seq,
                        lambda buf, s, t: wire.encode_burst_into(
                            frame, self.st.spec, s, buf, trace=t
                        ),
                    )
                else:
                    payload = self._register_data(
                        link,
                        seq,
                        lambda buf, s, t: wire.encode_frame_into(
                            frame, s, buf, trace=t
                        ),
                    )
                self._fault_point("mid-burst")  # ledgered, not yet sent
                if self._send_blocking(link, payload, data=True):
                    if compat:
                        self.st.ack_frame(link, seq)  # no ACK in the protocol
                    sent_any = True
                else:
                    # link died with this frame (and possibly speculative
                    # successors) undelivered: roll their error feedback back
                    # so drop_link/carry sees the full owed residual
                    pipe.pop(link, None)
                    hot.discard(link)
                    self.st.nack_frame(link)
            self._check_retransmit(links)
            if self._stop.is_set():
                return
            if interval > 0:
                time.sleep(interval)
            elif not sent_any:
                # idle: wait for a local add() or an incoming frame to create
                # new residual mass (event-driven wake, fixing quirk Q2)
                self._wake.wait(0.05)
                self._wake.clear()

    def _send_sub(self, link: int) -> bool:
        """One sender pass for a read-only subscriber link (python tier;
        the native engine runs the same logic in C — stengine.cpp's
        subscriber branch). Unledgered: the message is considered delivered
        on enqueue (``ack_frame`` immediately — the compat-tier discipline),
        no unacked entry is kept and no ACK will come; a message the wire
        swallows surfaces as a seq gap at the subscriber, whose resync
        handshake re-seeds the link. Ranged subscriptions ship one
        wire.RDATA per frame (only the subscribed words); full-table ones
        ship ordinary DATA/BURST. Idle links get a periodic wire.FRESH
        drain mark so the subscriber can keep verifying its staleness
        bound while nothing is being written."""
        rng = self._sub_links.get(link)
        if rng is not None:
            # drop out-of-range residual BEFORE scale selection (the range
            # discipline — core.mask_link_residual docstring), but only
            # when the replica has actually moved since the last mask: the
            # mask is a full-table copy under the state lock, and paying
            # it every idle send-loop pass would contend with add() for
            # nothing (st.state_version() is two counter reads)
            ver = self.st.state_version()
            if ver != self._sub_mask_ver.get(link):
                wlo, wcnt = rng
                self.st.mask_link_residual(link, wlo * 32, (wlo + wcnt) * 32)
                self._sub_mask_ver[link] = ver
        # FRESH stamp candidate, captured BEFORE the drained-residual
        # determination below: an add() racing in after the begin_* call
        # found the residual empty must not be covered by the mark (its
        # mass is not in what we sent) — a stamp taken at send time would
        # falsely verify freshness over it. Any t at or before the
        # determination is safe: everything added before the determination
        # was either quantized+enqueued already (FIFO delivers it before
        # the FRESH) or left mass that made the determination non-empty.
        # The C tier gets the same guarantee by stamping under e->mu.
        fresh_t = self._now_ns()
        if self.st.host_tier:
            # serving links trade batch efficiency for pipeline LATENCY:
            # the subscriber's staleness floor is queue depth x per-message
            # apply time, so cap the burst well under the wire budget
            # (stengine.cpp kSubBurstCap — same bound on the C tier)
            out = self.st.begin_frame_burst(link, min(self._burst, 32))
            if out is None:
                return False
            seq, frames = out
        else:
            out = self.st.begin_frame(link)
            if out is None:
                return False
            seq, df = out
            f = self.st.finish_frame(df)
            frames = [f] if f is not None else []
        if not frames:
            self.st.ack_frame(link, seq)  # idle: no-op
            self._sub_fresh_mark(link, fresh_t)
            return False
        trace = None
        if self._trace_wire:
            trace = self._trace_stamp
            if trace is None:
                trace = (self.node.obs_id, self._now_ns(), 0)
        nmsg = len(frames) if rng else 1
        with self._ack_mu:
            base = self._tx_seq.get(link, 0)
            self._tx_seq[link] = base + nmsg
        ok = True
        if rng:
            wlo, wcnt = rng
            for i, f in enumerate(frames):
                payload = wire.encode_rdata(
                    f, wlo, wcnt, base + i + 1, trace=trace
                )
                if not self._send_blocking(link, payload, data=True):
                    ok = False
                    break
                self._sub_msgs_out += 1
        else:
            if len(frames) == 1:
                payload = wire.encode_frame(frames[0], base + 1, trace=trace)
            else:
                payload = wire.encode_burst(
                    frames, self.st.spec, base + 1, trace=trace
                )
            ok = self._send_blocking(link, payload, data=True)
            if ok:
                self._sub_msgs_out += 1
        if ok:
            self.st.ack_frame(link, seq)  # delivered-on-enqueue (unledgered)
        else:
            self.st.nack_frame(link)
        return ok

    def _sub_fresh_mark(self, link: int, fresh_t: int) -> None:
        """Send ONE FRESH drain mark, interval-throttled — the shared tail
        of both freshness paths (the running sender's idle branch and the
        paused-quiesce beat), so the mark's contract (carries the link's
        last tx_seq, lossy zero-timeout send, bookkeeping) lives in one
        place. ``fresh_t`` must have been stamped BEFORE the caller's
        drained-residual determination (the _send_sub ordering note)."""
        now = time.monotonic()
        if now - self._sub_fresh.get(link, 0.0) < (
            self.config.serve.fresh_interval_sec
        ):
            return
        with self._ack_mu:
            last_seq = self._tx_seq.get(link, 0)
        try:
            if self.node.send(
                link, wire.encode_fresh(fresh_t, last_seq), timeout=0.0
            ):
                self._sub_fresh[link] = now
                self._sub_fresh_out += 1
        except BrokenPipeError:
            pass  # LINK_DOWN will clean the link up

    def _sub_fresh_beat(self, link: int) -> None:
        """FRESH beat for a PAUSED sender (r12 quiesce): only a fully
        drained residual may be marked fresh — a paused link still owing
        mass gets no mark, so a subscriber read across the cut refuses
        (StalenessError) instead of falsely verifying. Stamp captured
        BEFORE the drained determination, same discipline as _send_sub."""
        fresh_t = self._now_ns()
        if self.st.residual_rms(link) > 0.0:
            return
        self._sub_fresh_mark(link, fresh_t)

    def _register_data(self, link: int, ledger_seq: int, encode_into):
        """Allocate the link's next wire seq, encode the outgoing DATA/BURST
        message with it INTO a pooled slot (r07/r09: ``encode_into(buf,
        seq, trace)`` writes the wire bytes — v2-framed when ``trace`` is
        set — in place and returns the length), and append
        (ledger_seq, wire_seq, payload, slot) to the unacked retransmission
        ledger — the slot's filled prefix IS the payload, kept verbatim so
        a delivery timeout can resend it byte-identical (go-back-N; wire.py
        tx_seq docstring), and it returns to the pool when the entry pops.
        The encode itself (multi-MB numpy serialization for big bursts)
        runs OUTSIDE _ack_mu so it never stalls the recv thread's ACK pops;
        this thread is the link's only seq allocator and appender, and the
        peer cannot ACK a seq before the send that follows the append, so
        the two lock windows cannot misorder the ledger.

        Slot reuse is single-writer-safe: only this (send) thread acquires
        slots, so a slot released by the recv thread's ACK pop cannot be
        overwritten while any in-flight payload view of it is still being
        sent — the next acquire happens on this thread, after that send."""
        obs = self._obs
        with self._ack_mu:
            txs = self._tx_seq.get(link, 0) + 1
            self._tx_seq[link] = txs
        # r09 trace context: the pending stamp (latest local add or traced
        # apply); a peer that has neither yet stamps itself at hop 0
        trace = None
        if self._trace_wire:
            trace = self._trace_stamp
            if trace is None:
                trace = (self.node.obs_id, self._now_ns(), 0)
        slot = self._tx_pool.acquire()
        t0 = time.monotonic()
        n = encode_into(slot, txs, trace)
        if obs is not None:
            obs.encode.observe(time.monotonic() - t0)
        payload = slot[:n]
        with self._ack_mu:
            if link not in self._tx_seq:
                # LINK_DOWN raced between the two lock windows and purged
                # this link's ledger state; appending now would recreate
                # the dict entry for a dead link (ids are never reused)
                # and pin the payload until close(). The slot goes back to
                # the pool at once — safe to send the view first, because
                # only this thread can re-acquire it (docstring above).
                self._tx_pool.release(slot)
                return payload
            q = self._unacked.setdefault(link, [])
            now = time.monotonic()
            if not q:
                self._ack_progress[link] = now
            # 5th field: ledger-append time, consumed by the ACK-pop RTT
            # histogram (st_ack_rtt_seconds; includes retransmission
            # rounds by construction — same definition as the engine tier)
            q.append((ledger_seq, txs, payload, slot, now))
        return payload

    def _window_full(self, link: int) -> bool:
        with self._ack_mu:
            return len(self._unacked.get(link, ())) >= SEND_WINDOW

    def _check_retransmit(self, links) -> None:
        """Go-back-N delivery timer (TransportConfig.ack_timeout_sec): when
        a link's oldest unacked message has waited past the timeout, resend
        the HEAD of the unacked tail byte-identical (RETX_PREFIX messages —
        same wire seqs, so the receiver's dedup makes a spurious retransmit
        harmless, and in-order acceptance means only the head can restore
        progress anyway). After ack_retry_limit rounds with zero ACK
        progress the link is a black hole (accepts writes, acknowledges
        nothing): tear it down so LINK_DOWN -> rollback -> carry ->
        re-graft recovers every undelivered frame on a fresh link instead
        of retrying forever."""
        tcfg = self.config.transport
        # Sweep ledger state whose link is gone (runs even with the timer
        # disabled): _register_data's first lock window can recreate
        # _tx_seq for a link whose LINK_DOWN purge already ran, pinning the
        # payload forever — link ids are never reused, so anything not in
        # the live set is garbage. Only this thread appends, so a link
        # attached after `links` was snapshotted cannot have entries yet.
        purged = []
        with self._ack_mu:
            live = set(links)
            for stale in [l for l in self._unacked if l not in live]:
                purged.extend(self._unacked.pop(stale, ()))
                self._tx_seq.pop(stale, None)
                self._acked.pop(stale, None)
                self._ack_progress.pop(stale, None)
                self._retx_rounds.pop(stale, None)
        self._release_slots(purged)
        if tcfg.ack_timeout_sec <= 0 or tcfg.wire_compat:
            return
        now = time.monotonic()
        for link in links:
            with self._ack_mu:
                q = self._unacked.get(link)
                # per-round exponential backoff (capped 8x): the timer
                # measures time since ledger append, so on a
                # bandwidth-capped link a big burst can legitimately wait
                # out several timeouts while still queued locally — a flat
                # timer would retransmit (and eventually tear down) a
                # healthy saturated link; backoff keeps spurious rounds
                # from compounding while a true black hole still hits the
                # retry limit in bounded time
                wait = tcfg.ack_timeout_sec * min(
                    1 << self._retx_rounds.get(link, 0), 8
                )
                if not q or now - self._ack_progress.get(link, now) < wait:
                    continue
                rounds = self._retx_rounds.get(link, 0) + 1
                self._retx_rounds[link] = rounds
                self._ack_progress[link] = now
                # payload views over ledger-held slots: safe to send after
                # the lock drops even if an ACK pops them mid-send — a
                # released slot can only be REUSED by this same (send)
                # thread, after these sends (see _register_data)
                tail = [e[2] for e in q[:RETX_PREFIX]]
            if rounds > max(1, tcfg.ack_retry_limit):
                log.warning(
                    "link %d: no ACK progress after %d retransmission "
                    "rounds — tearing down for re-graft",
                    link, rounds - 1,
                )
                if self._obs is not None:
                    # the black-hole verdict is exactly what a postmortem
                    # should explain: dump the merged timeline around it
                    self._obs.event(
                        "blackhole_teardown", self.node.obs_id, link,
                        rounds - 1,
                    )
                    self._obs.hub.dump("goback_teardown")
                self.node.drop_link(link)
                continue
            log.info(
                "link %d: retransmitting %d unacked message(s), round %d",
                link, len(tail), rounds,
            )
            if self._obs is not None:
                self._obs.retransmits.inc(len(tail))
                self._obs.event(
                    "retransmit", self.node.obs_id, link, len(tail)
                )
            for payload in tail:
                if not self._send_blocking(link, payload, data=True):
                    break

    def _release_slots(self, entries) -> None:
        """Return popped ledger entries' pool slots (r07 slot lifecycle:
        acked/purged -> free). Entries are (ledger_seq, wire_seq, payload,
        slot, sent_at) tuples; legacy bytes payloads carry slot=None."""
        if self._tx_pool is None:
            return
        for entry in entries:
            slot = entry[3]
            if slot is not None:
                self._tx_pool.release(slot)

    def _fault_point(self, name: str) -> None:
        """Named protocol point for the fault plan's kill schedule."""
        if self._faults is not None:
            self._faults.point(name)

    def _send_blocking(
        self, link: int, payload: bytes, data: bool = False
    ) -> bool:
        """Deliver one frame, riding out backpressure. On a dead link the
        frame is dropped — its content is still in our replica, and the
        re-graft handshake re-derives exactly the missing delta.

        ``data=True`` marks DATA/BURST payloads: the fault plan (when one
        is installed) may drop, delay, duplicate, truncate, bit-corrupt,
        stall or sever them here — the Python tier's wire boundary.
        Handshake and ACK traffic never goes through the chaos."""
        # ONE load of the plan: the chaos soak detaches it mid-run
        # (p._faults = None) from another thread, and a re-load between
        # the None-check and the call would AttributeError — killing this
        # daemon send thread silently, the exact wedge class r06 hardened
        # the recv thread against
        plan = self._faults
        if plan is not None and data:
            payloads, delay, sever = plan.on_send(link, payload)
            if delay > 0:
                time.sleep(delay)
            ok = True
            for p in payloads:
                ok = self._send_raw(link, p)
                if not ok:
                    break
            if sever:
                self.node.drop_link(link)
                return False
            # a dropped/stalled frame reports success: the sender must
            # believe it delivered (that is the fault) — its ledger entry
            # stays unacked, which is exactly what rollback recovers
            return ok
        return self._send_raw(link, payload)

    def _send_raw(self, link: int, payload: bytes) -> bool:
        quarantine = self.config.transport.quarantine_send_failures
        fails = 0
        while not self._stop.is_set():
            try:
                if self.node.send(link, payload, timeout=0.1):
                    return True
            except BrokenPipeError:
                return False
            fails += 1
            if quarantine > 0 and fails >= quarantine:
                # Per-link quarantine: ~quarantine/10 seconds of a full
                # send queue with zero drained bytes means the peer has
                # stopped consuming but kept its socket open. Retrying hot
                # pins this thread (and the frames) on a dead-in-practice
                # link until peer_timeout_sec; tearing it down routes
                # through LINK_DOWN -> rollback -> carry -> re-graft, the
                # path that loses nothing.
                log.warning(
                    "quarantining link %d after %d consecutive send "
                    "failures (~%.0fs stalled): tearing down for re-graft",
                    link, fails, fails * 0.1,
                )
                if self._obs is not None:
                    self._obs.event(
                        "quarantine", self.node.obs_id, link, fails
                    )
                self.node.drop_link(link)
                return False
        return False

    # -- receive side ---------------------------------------------------------

    def _recv_loop(self) -> None:
        """Guard shell around the real loop: an UNHANDLED exception here
        used to kill the daemon thread silently and wedge the peer (the
        r05/r06 failure class). Now it dumps a flight-recorder postmortem
        (merged native+Python timeline + registry snapshots) and restarts
        the loop — bounded retries so a hot crash loop still surfaces."""
        failures = 0
        while not self._stop.is_set():
            try:
                self._recv_loop_inner()
                return  # clean exit: stop was set
            except Exception:
                failures += 1
                log.exception(
                    "recv thread hit an unhandled exception (restart %d/3)",
                    failures,
                )
                if self._obs is not None:
                    self._obs.hub.poll_native()
                    self._obs.hub.dump("recv_thread_exception")
                if failures >= 3:
                    raise
                time.sleep(0.1)

    def _recv_loop_inner(self) -> None:
        compat = self.config.transport.wire_compat
        while not self._stop.is_set():
            if self._obs is not None:
                # drain the native event ring into the flight recorder on
                # the peer's own thread (never a background thread racing
                # node teardown); rate-limited inside poll_native
                self._obs.hub.poll_native(self._obs.drain_interval)
            if (
                self._digest_interval > 0
                and self._obs is not None
                and _obs.obs_enabled()
            ):
                # r09 in-band aggregation: piggyback this subtree's merged
                # metrics digest up the tree (or, at the root, publish the
                # whole-tree view) once per interval — control-plane
                # traffic on the peer's own housekeeping thread. Gated on
                # obs like everything else: ST_OBS=0 / ObsConfig.enabled
                # =False means NO periodic snapshot/JSON/wire work (the
                # explicit metrics(cluster=True) call still serves), and
                # the RUNTIME flag (obs.set_enabled) pauses the beat too —
                # that is what lets obs_overhead.py's health arm A/B the
                # full digest+health+clock housekeeping cost.
                now = time.monotonic()
                if now - self._digest_last >= self._digest_interval and (
                    self._uplink is not None
                    or self.config.obs.cluster_json_path
                    or self._health is not None
                ):
                    # a root with no JSON/health sink has nobody to
                    # publish TO — its cluster view is built on demand
                    # (metrics(cluster=True)); don't pay the snapshot per
                    # beat just to discard it
                    self._digest_last = now
                    try:
                        self._publish_digest()
                    except Exception as e:
                        log.debug("digest publish failed: %s", e)
                # r18 clock plane beat rides the same housekeeping pass
                self._clock_beat(now)
            busy = self._handle_events()
            try:
                # r12 lifecycle: drive any active barrier / operator
                # command channel. Must never kill the recv loop — a
                # failed lifecycle op resolves through its own error path.
                self._lc_tick()
            except Exception:
                log.exception("lifecycle tick failed (recv thread continues)")
            if (
                compat
                and self._engine is not None
                and not self._ready.is_set()
                and self._uplink is not None
            ):
                # Engine-mode compat readiness: the engine consumes the
                # uplink's frames, so _decode_compat (the python tier's
                # readiness hook) never runs. The transport's per-link
                # frames_in counts EVERY received frame including zero-scale
                # keepalives — the same "parent's stream is flowing, even
                # idle" bar (quirk Q4's fix) the python tier uses.
                s = self.node.stats(self._uplink)
                if s is not None and s.frames_in > 0:
                    self._ready.set()
            if self._engine is not None:
                # control-plane messages the engine deferred (it owns only
                # DATA/BURST/ACK on attached links)
                while True:
                    c = self._engine.poll_ctrl()
                    if c is None:
                        break
                    busy = True
                    try:
                        self._on_message(c[0], c[1])
                    except Exception as e:
                        log.warning("dropping bad ctrl message on link %d: %s", c[0], e)
            for link in list(self.node.links):
                if link in self._engine_links:
                    continue  # the engine's receiver thread consumes these
                # Consecutive DATA/BURST frames batch into ONE device apply
                # (core.receive_frames): without this, per-frame dispatch on
                # a busy device falls behind a fast sender and the RX queue
                # backs up by hundreds of frames. Control messages flush the
                # batch first so relative order is preserved. ``msgs`` counts
                # wire MESSAGES (what the sender's ledger tracks and ACKs
                # acknowledge); a burst message carries many frames. Trace
                # notes are buffered and recorded AFTER the flush applies
                # (same accounting instant as the native receiver) —
                # telemetry must not claim a hop whose batch then failed.
                batch: list = []
                traced: list = []
                msgs = 0
                # host tier only: its applies are synchronous numpy/C work,
                # so recycling after the flush cannot race anything. A
                # device tier's jitted apply may consume the arrays
                # asynchronously (H2D transfer) — it keeps fresh copies.
                scratch = self._rx_scratch.get(link)
                if scratch is None and not compat and self.st.host_tier:
                    scratch = self._rx_scratch.setdefault(
                        link, wire.DecodeScratch(self.st.spec)
                    )
                for _ in range(256):  # bounded so other links aren't starved
                    try:
                        payload = self.node.recv(link, timeout=0.0)
                    except BrokenPipeError:
                        break
                    if payload is None:
                        break
                    busy = True
                    try:
                        if compat:
                            frame = self._decode_compat(link, payload)
                            if frame is not None:
                                batch.append(frame)
                            continue
                        if payload[0] in (wire.DATA, wire.BURST):
                            if self._sealed:
                                # leaving: discard unACKed — the sender's
                                # ledger re-delivers after our departure
                                continue
                            # Go-back-N acceptance (wire.py tx_seq): only
                            # the next in-order, decodable message is
                            # applied and counted. A duplicate (seq <= rx:
                            # injected, or a retransmit racing our ACK) and
                            # anything after a gap (seq > rx+1: a message
                            # vanished at the wire) are discarded unapplied
                            # — the sender retransmits the hole
                            # byte-identical, so nothing is lost, nothing
                            # applies twice, and the cumulative ACK is
                            # always exactly the last accepted seq. An
                            # undecodable message (truncated/garbled) is
                            # likewise discarded WITHOUT consuming its seq;
                            # its retransmission arrives whole.
                            # expected seq masked to u32: the wire field
                            # wraps at 2^32 while rx_count counts on
                            # (matching the native engine's compare)
                            seq = wire.data_seq(payload, self.st.spec)
                            want = (
                                self._rx_count.get(link, 0) + msgs + 1
                            ) & 0xFFFFFFFF
                            if seq != want:
                                log.debug(
                                    "link %d: discarding out-of-order "
                                    "data message (seq %d, expected %d)",
                                    link, seq, want,
                                )
                                if self._obs is not None:
                                    # dedup instrument is None on engine
                                    # peers; this path is still reachable
                                    # there pre-attach (handshake-window
                                    # DATA), so guard it
                                    if self._obs.dedup is not None:
                                        self._obs.dedup.inc()
                                    self._obs.event(
                                        "dedup_discard", self.node.obs_id,
                                        link, seq,
                                    )
                                continue
                            if payload[0] == wire.DATA:
                                batch.append(
                                    wire.decode_frame(
                                        payload, self.st.spec, scratch
                                    )
                                )
                            else:
                                batch.extend(
                                    wire.decode_burst(
                                        payload, self.st.spec, scratch
                                    )
                                )
                            msgs += 1
                            traced.append(payload)
                            continue
                    except Exception as e:  # a bad frame must not kill the node
                        log.warning("dropping bad frame on link %d: %s", link, e)
                        continue
                    # control message: flush queued frames first (order), and
                    # never let a flush failure swallow the control message —
                    # a dropped WELCOME/DONE would hang the join handshake
                    self._flush_frames(link, batch, msgs, scratch)
                    for p in traced:
                        self._note_trace(link, p)
                    batch, traced, msgs = [], [], 0
                    try:
                        self._on_message(link, payload)
                    except Exception as e:
                        log.warning("dropping bad message on link %d: %s", link, e)
                    if link in self._engine_links:
                        # the handshake just attached this link to the native
                        # engine: stop consuming NOW — the next message is
                        # the engine's (and its rx accounting took over at
                        # the attach-time count)
                        break
                self._flush_frames(link, batch, msgs, scratch)
                for p in traced:
                    self._note_trace(link, p)
                self._flush_acks(link)  # retry any backpressure-dropped ACK
            if not busy:
                time.sleep(0.002)

    def _flush_frames(
        self,
        link: int,
        batch: list,
        msgs: int | None = None,
        scratch: Optional[wire.DecodeScratch] = None,
    ) -> None:
        n_ack = len(batch) if msgs is None else msgs
        if batch:
            t0 = time.monotonic()
            try:
                self.st.receive_frames(link, batch)
            except Exception:
                # Fall back to per-frame apply so one bad frame costs only
                # itself, not up to 255 good ones (received deltas are never
                # resent — the sender's error feedback already cleared them,
                # so a discarded good frame would silently diverge the
                # replicas).
                for f in batch:
                    try:
                        self.st.receive_frame(link, f)
                    except Exception as e:
                        log.warning("dropping bad frame on link %d: %s", link, e)
            if self._obs is not None:
                self._obs.apply.observe(time.monotonic() - t0)
            if scratch is not None:
                # frames applied (receive_frames is synchronous on every
                # tier): their pooled decode arrays are reusable now
                scratch.recycle()
            self._wake.set()  # flood refills other links' residuals
        # crash point: mass applied + flooded, ACK not yet sent — the
        # two-generals window; the sender re-delivers (at-least-once)
        if n_ack:
            self._fault_point("between-apply-and-ack")
        # ACK counts ACCEPTED wire MESSAGES (one ledger entry each), not
        # frames: a burst message carries many frames but rolls back / acks
        # whole. With the tx_seq discipline (recv loop) the cumulative count
        # is exactly the last in-order seq applied — undecodable or
        # out-of-order messages were never counted and will be
        # retransmitted by their sender.
        if n_ack:
            self._ack_received(link, n_ack)

    def _now_ns(self) -> int:
        """Monotonic ns for cross-node-comparable stamps (trace
        generations, clock probes, digest times), plus the simulated skew
        when a test/bench configured one — so every stamp another node
        compares against behaves like a genuinely skewed host clock."""
        return time.monotonic_ns() + self._skew_ns

    def _health_event(self, name: str, arg: int, detail: str) -> None:
        """Analyzer event sink -> the flight recorder timeline."""
        obs = self._obs
        if obs is not None:
            obs.event(name, self.node.obs_id, 0, arg, detail=detail)

    def _clock_beat(self, now: float) -> None:
        """r18 clock plane beat (housekeeping thread): probe the uplink
        with a four-stamp offset sample every clock_sync_interval_sec.
        The root never probes — it IS the reference. Lossy like the
        digest beat: a bounced send just waits for the next interval."""
        if (
            self._clock_interval <= 0
            or self.is_master
            or now - self._clock_last < self._clock_interval
        ):
            return
        up = self._uplink
        if up is None:
            return
        self._clock_last = now
        try:
            self.node.send(
                up, wire.encode_clock(self._clock.probe_payload()), timeout=0.05
            )
        except BrokenPipeError:
            pass  # uplink died; re-graft re-targets the next probe

    def _note_trace(self, link: int, payload: bytes) -> None:
        """r09 trace bookkeeping for one ACCEPTED data message (python
        tier; the engine's receiver does the same in C): advance the
        pending stamp one hop, record the link's staleness/hop gauges, and
        put a trace_apply record on the timeline. Telemetry gates on obs
        exactly like the native twin (st_obs_is_enabled in stengine.cpp's
        receiver) — with obs off only the stamp advance remains, the part
        PROPAGATION needs."""
        obs = self._obs
        if obs is None and not self._trace_wire:
            return
        tr = wire.data_trace(payload, self.st.spec)
        if tr is None:
            return
        origin, gen, hops = tr
        hop = min(hops + 1, 255)
        if self._trace_wire:
            self._trace_stamp = (origin, gen, hop)
        if obs is None:
            return
        # r18: store the origin GENERATION stamp, not a frozen age — the
        # collector computes the live age at snapshot time, so a stalled
        # link's staleness GROWS (what the SLO burn-rate alert watches)
        # instead of freezing at its last-apply value. The origin node id
        # feeds the health analyzer's cross-host offset correction.
        self._staleness[link] = (gen, hop)
        self._stale_origin[link] = origin
        self._traced_in += 1
        if obs.hops is not None:
            obs.hops.observe(hop)
        obs.event(
            "trace_apply", self.node.obs_id, link, gen,
            extra=((origin << 8) | hop),
        )

    # -- r09 in-band cluster digest -----------------------------------------

    def _build_digest(self) -> dict:
        """This subtree's merged metrics digest: our own registry snapshot
        folded with each child link's latest digest (obs/aggregate.py owns
        the merge semantics; subtree disjointness makes counter sums
        exact). Bounded before it ever hits the wire."""
        from ..obs import aggregate

        doc = aggregate.from_snapshot(
            self.node.obs_id,
            self.metrics(canonical=True),
            self._now_ns(),
        )
        # r12: the lifecycle node name rides the per-node breakdown so the
        # operator surface (ctl drain/versions) can address nodes by name
        ent = doc["nodes"].get(str(int(self.node.obs_id)))
        if ent is not None:
            ent["name"] = self.node_name
        for child in list(self._child_digests.values()):
            aggregate.merge(doc, child)
        aggregate.bounded(doc)
        if self._obs is not None:
            self._obs.cluster_nodes.set(aggregate.cluster_nodes(doc))
        return doc

    def _publish_digest(self) -> dict:
        """One digest beat: send the subtree digest to the uplink, or —
        at the root — write the whole-tree view to
        ObsConfig.cluster_json_path for ``obs.top``. Lossy by design
        (backpressure skips a beat; the next one carries fresher
        totals)."""
        doc = self._build_digest()
        up = self._uplink
        if up is not None:
            try:
                # small blocking budget, NOT 0: a saturated data plane (the
                # normal state of a training run — the engine keeps the
                # 8-deep transport queue full) would bounce every
                # zero-timeout enqueue and the tree view would silently go
                # stale exactly when it matters; 50 ms is one queue-drain
                # on any healthy link, paid on the housekeeping thread. A
                # beat that still bounces is dropped — the next one
                # carries fresher totals anyway.
                if (
                    self.node.send(up, wire.encode_digest(doc), timeout=0.05)
                    and self._obs is not None
                ):
                    self._obs.digest_out.inc()
            except BrokenPipeError:
                pass  # uplink died; LINK_DOWN will re-route the next beat
        else:
            if self._health is not None:
                # r18: the root's health analyzer samples every digest
                # beat — time-series ingest, heat scoring, SLO burn rates,
                # health.json (the analyzer writes it itself)
                try:
                    self._health.beat(doc, self._now_ns())
                except Exception as e:
                    log.debug("health beat failed: %s", e)
            if self.config.obs.cluster_json_path:
                import json as _json
                import os as _os

                path = self.config.obs.cluster_json_path
                tmp = f"{path}.tmp.{_os.getpid()}"
                try:
                    with open(tmp, "w") as f:
                        _json.dump(doc, f)
                        f.write("\n")
                    _os.replace(tmp, path)  # atomic: never a torn read
                except OSError as e:
                    log.debug("cluster digest write failed: %s", e)
        return doc

    def push_digest(self) -> dict:
        """Force one digest beat NOW (the periodic timer keeps running).
        Tests and quiesce-time accounting use this to propagate exact
        totals bottom-up instead of waiting out the interval."""
        self._digest_last = time.monotonic()
        return self._publish_digest()

    def cluster_metrics(self) -> dict:
        """The live whole-tree view from this node's vantage: its own
        registry + every digest its subtree has reported. At the tree ROOT
        this is the cluster — ``metrics(cluster=True)`` is the documented
        spelling."""
        return self._build_digest()

    def cluster_prometheus_text(self) -> str:
        """Prometheus text exposition of the cluster digest (merged
        counters/histograms; per-node gauges labeled ``{node=}``)."""
        from ..obs import aggregate

        return aggregate.prometheus_text(self._build_digest())

    def _ack_received(self, link: int, n: int) -> None:
        """Tell the sender its frames arrived (drives its in-flight ledger;
        see _send_loop). Cumulative, and RETRIED: an ACK dropped to send-queue
        backpressure is only healed by a later one if more DATA arrives — the
        final ACK of a burst would otherwise be lost forever, leaving the
        sender's ledger undrained (drain() spinning, rollback re-delivering
        delivered frames on link death)."""
        if self.config.transport.wire_compat or n <= 0:
            return
        count = self._rx_count.get(link, 0) + n
        self._rx_count[link] = count
        self._flush_acks(link)

    def _flush_acks(self, link: int) -> None:
        count = self._rx_count.get(link, 0)
        if count <= self._ack_sent.get(link, 0):
            return
        try:
            if self.node.send(link, wire.encode_ack(count), timeout=0.0):
                self._ack_sent[link] = count
        except BrokenPipeError:
            self._ack_sent[link] = count  # link dead; nothing left to ack

    #: EventKind -> timeline event name (matches the native codes 1..4, so
    #: every native membership event pairs with a later "py"-tier twin —
    #: the handled-at timestamp the cross-tier ordering test leans on)
    _EVENT_NAMES = {
        EventKind.LINK_UP: "link_up",
        EventKind.LINK_DOWN: "link_down",
        EventKind.BECAME_MASTER: "became_master",
        EventKind.REJOIN_FAILED: "isolated",
    }

    def _handle_events(self) -> bool:
        evs = self.node.poll_events(timeout=0.0)
        for ev in evs:
            if self._obs is not None:
                self._obs.event(
                    self._EVENT_NAMES[ev.kind], self.node.obs_id,
                    ev.link_id, int(ev.is_uplink),
                )
            if ev.kind == EventKind.LINK_UP:
                try:
                    self._on_link_up(ev)
                except DuplicateLink:
                    # A duplicate link id (e.g. a LINK_UP replayed across a
                    # transport hiccup) must be a logged no-op: this runs on
                    # the daemon recv thread, and an escaped raise would
                    # silently kill it and wedge the peer — the link is
                    # already attached, which is the state the event asks
                    # for anyway.
                    log.warning(
                        "duplicate LINK_UP for link %d ignored", ev.link_id
                    )
                except Exception:
                    # Any OTHER attach-path error must surface loudly (it is
                    # NOT a replay and may mean the link never attached) —
                    # but never by killing the daemon recv thread: a dead
                    # recv loop wedges the whole peer, the exact
                    # exit(-1)-class failure this framework exists to
                    # delete. The link CANNOT be left up either: a
                    # half-attached link still ACKs every message by count
                    # while the apply path drops its frames (unknown link),
                    # so the sender would clear error feedback for mass
                    # that never landed — silent permanent divergence. Tear
                    # it down instead: LINK_DOWN -> rollback -> carry ->
                    # re-graft re-delivers everything on a fresh link.
                    log.exception(
                        "LINK_UP handling failed for link %d — tearing the "
                        "link down for re-graft (recv thread continues)",
                        ev.link_id,
                    )
                    try:
                        self.node.drop_link(ev.link_id)
                    except Exception:
                        log.exception(
                            "teardown of half-attached link %d failed",
                            ev.link_id,
                        )
            else:
                try:
                    self._on_membership_event(ev)
                except Exception:
                    # same thread-survival rule as LINK_UP above
                    log.exception(
                        "membership event %s for link %d failed "
                        "(recv thread continues)", ev.kind, ev.link_id
                    )
        return bool(evs)

    def _on_link_up(self, ev) -> None:
        if ev.is_uplink:
            self._uplink = ev.link_id
            # a re-grafted uplink supersedes any earlier isolation
            # verdict (REJOIN_FAILED is a status, not a sentence —
            # the native layer keeps retrying and may heal)
            self._error = None
            if self.config.transport.wire_compat:
                # reference protocol has no handshake: start
                # streaming at once — into the carried residual
                # when re-grafting (our undelivered mass), else
                # zero. A re-grafting leaf resets its replica NOW
                # to EXACTLY the carry (fresh-joiner semantics: a
                # true fresh joiner with pending adds holds them in
                # values AND residual; the parent's re-seed then
                # refills tree state additively on top). Resetting
                # to zero instead would desync this node by the
                # carry forever: the carry floods to every OTHER
                # peer, and split horizon never returns it here —
                # see the LINK_DOWN comment.
                if self._compat_reset_on_regraft:
                    self._compat_reset_on_regraft = False
                    if self._engine is not None:
                        self._engine.compat_regraft(ev.link_id)
                    else:
                        self.st.regraft_reset_to_carry(
                            CARRY_LINK, ev.link_id
                        )
                elif self._engine is not None:
                    # interior re-graft (or first join): residual =
                    # carry + anything added since the consume —
                    # attach-by-diff recomputes against live values,
                    # so the two-step consume/attach loses nothing
                    carry, snap = self._engine.take_carry_and_snapshot()
                    if carry is not None:
                        self._engine.new_link_diff(
                            ev.link_id, np.asarray(snap - carry, "<f4")
                        )
                    else:
                        self._engine.new_link(ev.link_id, seed=False)
                else:
                    carry, _ = self.st.take_link_and_snapshot(
                        CARRY_LINK
                    )
                    self.st.new_link(
                        ev.link_id, seed=False, residual=carry
                    )
                if self._engine is not None:
                    self._engine_links.add(ev.link_id)
            else:
                self._start_join(ev.link_id)
        else:
            if self.config.transport.wire_compat:
                # reference join: seed the child with the full replica
                # through the codec stream (src/sharedtensor.c:379-381)
                if self._engine is not None:
                    self._engine.new_link(ev.link_id, seed=True)
                    self._engine_links.add(ev.link_id)
                else:
                    self.st.new_link(ev.link_id, seed=True)
            else:
                # native: wait for the child's SYNC snapshot before
                # opening the codec link
                self._pending[ev.link_id] = bytearray()
    def _on_membership_event(self, ev) -> None:
        if ev.kind == EventKind.LINK_DOWN:
            # r12: a child dying mid-barrier must not hang the cut — its
            # subtree's shards are simply absent (recorded as an error;
            # the root's verdict then fails honestly instead of stalling)
            op = self._lc_op
            if op is not None and ev.link_id in op["waiting"]:
                op["waiting"].discard(ev.link_id)
                op["errors"].append(
                    f"{self.node_name}: child link {ev.link_id} died "
                    f"mid-barrier"
                )
            self._pending.pop(ev.link_id, None)
            self._engine_links.discard(ev.link_id)
            self._rx_scratch.pop(ev.link_id, None)
            self._staleness.pop(ev.link_id, None)
            self._stale_origin.pop(ev.link_id, None)
            self._child_digests.pop(ev.link_id, None)
            # a dead subscriber link carries NO residual forward: a
            # read-only leaf owes the tree nothing, and a re-joining
            # subscriber re-seeds from scratch anyway
            self._sub_links.pop(ev.link_id, None)
            self._sub_fresh.pop(ev.link_id, None)
            self._sub_mask_ver.pop(ev.link_id, None)
            self._pending_sub.pop(ev.link_id, None)
            with self._ack_mu:
                purged = self._unacked.pop(ev.link_id, ())
                self._tx_seq.pop(ev.link_id, None)
                self._acked.pop(ev.link_id, None)
                self._rx_count.pop(ev.link_id, None)
                self._ack_sent.pop(ev.link_id, None)
                self._ack_progress.pop(ev.link_id, None)
                self._retx_rounds.pop(ev.link_id, None)
            self._release_slots(purged)
            if ev.is_uplink:
                # Keep undelivered upward updates for the re-grafted
                # uplink — in a LIVE carry slot that continues to absorb
                # add()/flood mass while we are orphaned (see
                # CARRY_LINK). If the parent died mid-handshake the
                # codec link never existed; everything we owe the tree
                # is then replica - sent_snapshot, computed LAZILY at
                # re-join time so orphan-period adds are included.
                if self._engine is not None:
                    stashed = self._engine.stash_carry(ev.link_id)
                else:
                    # one lock: a concurrent add() must find either the
                    # dying link or the carry slot, never neither
                    stashed = self.st.stash_carry(ev.link_id, CARRY_LINK)
                if not stashed and self._sent_snapshot is not None:
                    self._mid_handshake_base = self._sent_snapshot
                self._sent_snapshot = None
                self._uplink = None
                if self.config.transport.wire_compat:
                    # The reference protocol cannot express a stateful
                    # re-graft: the new parent will re-seed us with its
                    # FULL replica (no diff handshake exists), so
                    # retained state would double. A LEAF therefore
                    # zeroes its replica — but only AT the re-graft
                    # (LINK_UP below), never here: rejoin may instead
                    # end in BECAME_MASTER, where our retained state IS
                    # the authoritative seed and zeroing it would serve
                    # an empty tree. With children the reset would
                    # double THEM (their state stays while our
                    # seed-refill floods down), so an interior node
                    # keeps state and accepts the documented
                    # double-count — still strictly better than the
                    # reference, which kills the whole tree (quirk Q8).
                    # (the carry pseudo-slot is not a real link)
                    real = [l for l in self.st.link_ids if l >= 0]
                    if not real:
                        self._compat_reset_on_regraft = True
                    else:
                        log.warning(
                            "wire-compat interior node lost its uplink:"
                            " re-seeded state may double (the reference"
                            " protocol has no diff handshake)"
                        )
            else:
                self.st.drop_link(ev.link_id)
        elif ev.kind == EventKind.BECAME_MASTER:
            # our parent died and rejoin found nobody: we claimed the
            # rendezvous and are the new root (native master failover);
            # whatever state we hold is now the authoritative seed —
            # including in wire-compat, where a pending re-graft reset
            # must be cancelled (zeroing the new root would serve an
            # empty tree). The carry is DROPPED: its mass is already in
            # our (now-authoritative) replica, a root never re-joins
            # upward, and a live-but-unconsumable carry would cost an
            # extra O(total) pass on every add/apply forever.
            if self._engine is not None:
                self._engine.drop_carry()
            else:
                self.st.take_link_and_snapshot(CARRY_LINK)
            self._mid_handshake_base = None
            self._compat_reset_on_regraft = False
            self._uplink = None
            self.is_master = True
            self._error = None
            self._ready.set()
        elif ev.kind == EventKind.REJOIN_FAILED:
            # Status, not a sentence: the native layer keeps cycling
            # join-then-claim-rendezvous forever; under detection skew a
            # sibling may claim the rendezvous seconds after this fires,
            # and the next LINK_UP/BECAME_MASTER clears the error.
            self._error = ConnectionError(
                "uplink lost and rejoin failed; node is isolated "
                "(still retrying in the background)"
            )
            self._ready.set()  # unblock wait_ready, which re-raises

    def _attach_diff(self, link: int, snap) -> None:
        """Open the codec link with residual = replica - snap. In engine mode
        the attach hands the link's data plane to the native engine, seeded
        with the cumulative message count Python acked during the handshake
        (so the ACK stream stays monotonic across the handoff)."""
        if self._engine is not None:
            self._engine.new_link_diff(
                link, np.asarray(snap, "<f4"), rx_init=self._rx_count.get(link, 0)
            )
            self._engine_links.add(link)
        else:
            self.st.new_link_diff(link, snap)
        self._arm_sign2(link)

    def _shm_ring_bytes(self) -> int:
        """Ring bytes per direction for this table: TWICE the max traced
        sign2 burst (the largest wire message the engine can emit), so
        the lane always pipelines >= 2 messages — floored at 1 MiB and
        capped by TransportConfig.shm_ring_bytes. Sizing to the table
        matters both ways on one memory system: a ring much smaller than
        a burst runs the lane in lockstep, while one much larger than
        needed cycles through DRAM instead of staying cache-resident
        (measured at 1 Mi: a 16 MiB ring beats a 64 MiB one by ~8%)."""
        want = 2 * (
            wire.HDR_V3
            + wire.burst_frames_cap(self.st.spec)
            * wire.frame_payload2_bytes(self.st.spec)
            + 64
        )
        # the user's cap is the OUTER bound (a memory-tight box setting
        # 128 KiB must get 128 KiB rings, not the floor): floor first,
        # cap last
        return min(
            self.config.transport.shm_ring_bytes, max(1 << 20, want)
        )

    def _arm_sign2(self, link: int) -> None:
        """r11: arm the adaptive-precision governor for this link iff BOTH
        ends advertised sign2 (ours is config/env-gated via self._sign2)."""
        if (
            self._engine is not None
            and self._sign2
            and self._peer_sign2.pop(link, False)
        ):
            self._engine.link_allow_sign2(link)
        # r14: an r14 peer decodes the aligned v3 framing — emission to it
        # may drop the repack copy from ITS receive path (same consume-at-
        # attach discipline as the sign2 flag above)
        if self._engine is not None and self._peer_r14.pop(link, False):
            self._engine.link_wire_v3(link)

    def _attach_sub(self, link: int, rng: Optional[tuple[int, int]]) -> None:
        """Attach — or RE-seed, the resync path — a read-only subscriber
        link (r10 serving tier). Order matters throughout:

        - a resync DETACHES first (discarding the old residual — the
          snapshot about to ship supersedes it) so the sender produces
          nothing in the window;
        - the wire seq restarts at 1 so the subscriber's post-seed gap
          detector has a deterministic base;
        - ``_sub_links`` is set BEFORE the codec link opens, so the send
          loop can never take the ledgered path for it (an unacked entry
          on a never-ACKing link would black-hole it);
        - WELCOME + snapshot CHUNKs + DONE + FRESH are enqueued BEFORE the
          attach (per-link FIFO ⇒ the subscriber finishes seeding before
          any codec DATA arrives — the same rationale as the writer join
          path).

        On the engine tier, attach and subscriber mode are ONE atomic
        native call (st_engine_attach_sub) for the same no-ledgered-window
        reason."""
        self._peer_sign2.pop(link, None)  # subscriber links stay 1-bit
        self._peer_shm.pop(link, None)  # ...and keep TCP (no shm offer)
        self._peer_r14.pop(link, None)  # ...and v2 framing
        resync = link in self._sub_links
        if resync:
            if self._engine is not None:
                self._engine.drop_link(link)
            else:
                self.st.drop_link(link)
        with self._ack_mu:
            purged = self._unacked.pop(link, ())
            self._tx_seq.pop(link, None)
            self._acked.pop(link, None)
            self._ack_progress.pop(link, None)
            self._retx_rounds.pop(link, None)
        self._release_slots(purged)
        wlo, wcnt = rng if rng is not None else (0, 0)
        self._sub_links[link] = rng
        self._sub_fresh[link] = 0.0
        # The seed rides the CONTROL plane: WELCOME, then our replica
        # snapshot (the subscribed pages only) as CHUNKs + DONE + a FRESH
        # mark stamped at snapshot time, and only THEN the codec link
        # opens (residual = whatever landed between snapshot and attach).
        # Rationale: subscriber links are unledgered, so a codec-stream
        # seed is only as reliable as every one of its messages — under
        # sustained loss a multi-message drain essentially never completes
        # gap-free, and the subscriber would resync forever (measured in
        # the r10 chaos arm). Control traffic is outside the chaos classes
        # by the r06 rule (chaos exercises recovery, never wedges a
        # handshake), so a re-seed completes DETERMINISTICALLY and the
        # codec stream carries only steady-state deltas.
        t_snap = self._now_ns()
        vals = np.asarray(self.st.snapshot_flat(), np.float32)
        self._send_blocking(link, bytes([wire.WELCOME]))
        sl = vals[wlo * 32 : (wlo + wcnt) * 32] if rng is not None else vals
        for chunk in wire.encode_snapshot_chunks(sl):
            self._send_blocking(link, chunk)
        # last_seq 0: the post-seed stream hasn't started (seqs restart at
        # 1 below), and the subscriber has applied exactly 0 of it
        self._send_blocking(link, wire.encode_fresh(t_snap, 0))
        if self._engine is not None:
            self._engine.new_link_sub(
                link,
                vals,
                rx_init=self._rx_count.get(link, 0),
                word_lo=wlo,
                word_cnt=wcnt,
                fresh_interval_sec=self.config.serve.fresh_interval_sec,
            )
            self._engine_links.add(link)
        else:
            # residual = values_now - vals: exactly the adds/floods that
            # raced the snapshot transfer (usually zero); _send_sub
            # range-masks it per pass
            self.st.new_link_diff(link, vals)
        if self._obs is not None:
            self._obs.event(
                "sub_resync" if resync else "sub_attach",
                self.node.obs_id, link, wcnt,
            )
        log.info(
            "link %d attached read-only%s%s", link,
            f" (words [{wlo}, {wlo + wcnt}))" if rng else " (full table)",
            " — resync re-seed" if resync else "",
        )

    def _attach_zero(self, link: int) -> None:
        if self._engine is not None:
            self._engine.new_link(
                link, seed=False, rx_init=self._rx_count.get(link, 0)
            )
            self._engine_links.add(link)
        else:
            self.st.new_link(link, seed=False)
        self._arm_sign2(link)

    # native-mode join handshake, child side
    def _start_join(self, uplink: int) -> None:
        # Consume the carry ATOMICALLY with the replica snapshot (one lock
        # in the state layer): an add() racing between the two would appear
        # in the snapshot but not the carry — presented to the parent as
        # tree-known state and erased tree-wide by its diff seed.
        if self._engine is not None:
            carry, snap = self._engine.take_carry_and_snapshot()
        else:
            carry, snap = self.st.take_link_and_snapshot(CARRY_LINK)
        if carry is None and self._mid_handshake_base is not None:
            # parent died before the handshake finished: everything we owe
            # is values - base, including orphan-period adds (lazy compute)
            carry = snap - self._mid_handshake_base
        self._mid_handshake_base = None
        if carry is not None:
            # exclude updates we still owe the tree, else the parent's diff
            # seed would subtract them from us while our carried residual
            # re-delivers them upward — a permanent divergence of exactly
            # the carried amount
            snap = snap - carry
            # the carry rides the NEW uplink: seeded at WELCOME as
            # values_now - sent_snapshot, which is exactly carry + whatever
            # lands during the handshake (the live slot keeps absorbing)
        self._sent_snapshot = snap
        from ..compat import SYNC_FLAG_SHM, SYNC_FLAG_SIGN2

        # r14: advertise the same-host shm lane (flag + our host identity
        # in the tolerant SYNC tail); a pre-r14 or cross-host parent just
        # ignores it and the link stays on TCP
        sflags = SYNC_FLAG_SIGN2 if self._sign2 else 0
        if self._shm_ok:
            sflags |= SYNC_FLAG_SHM
        self._send_blocking(
            uplink,
            wire.encode_sync(
                self.st.spec,
                self._wire_version,
                flags=sflags,
                shm_host=self._shm_host,
            ),
        )
        # crash point: SYNC sent, snapshot not — the parent holds a pending
        # handshake buffer for a child that just died mid-walk
        self._fault_point("mid-join-walk")
        for chunk in wire.encode_snapshot_chunks(np.asarray(snap, dtype="<f4")):
            if not self._send_blocking(uplink, chunk):
                return  # uplink died mid-handshake; LINK_DOWN re-derives carry
        # WELCOME (handled in _on_message) opens the codec link

    def _on_message(self, link: int, payload: bytes) -> None:
        kind = payload[0]
        if kind == wire.DATA:
            # same go-back-N acceptance as the recv-loop data path (this
            # branch serves stray DATA routed through the control plane);
            # expected seq masked to the wire field's u32 wrap
            if wire.data_seq(payload, self.st.spec) != (
                self._rx_count.get(link, 0) + 1
            ) & 0xFFFFFFFF:
                return  # dup/gap: discard unapplied, await retransmission
            self.st.receive_frame(link, wire.decode_frame(payload, self.st.spec))
            self._ack_received(link, 1)
            self._wake.set()  # flood refills other links' residuals
        elif kind == wire.ACK:
            # cumulative ACK = last in-order wire seq the peer accepted;
            # every unacked entry at or below it is delivered — its pool
            # slot returns to the ring (slot lifecycle: acked -> free)
            count = wire.decode_ack(payload)
            popped = []
            with self._ack_mu:
                self._acked[link] = count
                q = self._unacked.get(link, [])
                while q and q[0][1] <= count:
                    popped.append(q.pop(0))
                if popped:
                    # delivery progressed: reset the go-back-N timer
                    self._ack_progress[link] = time.monotonic()
                    self._retx_rounds.pop(link, None)
            self._release_slots(popped)
            if self._obs is not None and popped:
                now = time.monotonic()
                for entry in popped:
                    # entry[4] = ledger-append time (see _register_data)
                    self._obs.ack_rtt.observe(now - entry[4])
            for entry in popped:
                self.st.ack_frame(link, entry[0])
        elif kind == wire.SYNC:
            k, n, digest = wire.decode_sync(payload)
            ver = wire.sync_wire_version(payload)
            if ver != self._wire_version:
                # framing skew is fine (decoders accept both) but worth a
                # line: a tree stuck on v1 emission has no trace telemetry
                log.info(
                    "link %d joins with wire framing v%d (ours: v%d) — "
                    "interop ok; trace coverage follows the emitter",
                    link, ver, self._wire_version,
                )
            mine = self.st.spec
            if digest != mine.layout_digest():
                log.warning(
                    "rejecting link %d: table layout differs "
                    "(theirs: %d leaves / %d elems; ours: %d / %d)",
                    link, k, n, mine.num_leaves, mine.total_n,
                )
                self._send_blocking(
                    link,
                    wire.encode_reject(
                        f"table layout mismatch: yours ({k} leaves, {n} elems)"
                        f" is not byte-compatible with ours"
                        f" ({mine.num_leaves}, {mine.total_n})"
                    ),
                )
                self.node.drop_link_flushed(link)
                self._pending.pop(link, None)
                self._pending_sub.pop(link, None)
            else:
                from ..compat import (
                    SYNC_FLAG_READ_ONLY,
                    SYNC_FLAG_SHM,
                    SYNC_FLAG_SIGN2,
                )

                # r11: remember the joiner's sign2 decode capability for
                # the attach that follows DONE
                self._peer_sign2[link] = bool(
                    wire.sync_flags(payload) & SYNC_FLAG_SIGN2
                )
                # r14: same-host shm candidacy — the joiner advertised the
                # lane AND its host identity matches ours (consumed at
                # WELCOME time, when we serve the segment). The flag alone
                # (host match or not) marks the peer r14 — it decodes the
                # aligned v3 framing. Gated on OUR _shm_ok too: ST_SHM=0
                # must pin this node to pre-r14 behavior END TO END (v2
                # emission included — the documented A/B escape hatch).
                self._peer_r14[link] = bool(
                    self._shm_ok
                    and wire.sync_flags(payload) & SYNC_FLAG_SHM
                )
                self._peer_shm[link] = bool(
                    self._shm_ok
                    and wire.sync_shm_host(payload) == self._shm_host
                )
                if wire.sync_flags(payload) & SYNC_FLAG_READ_ONLY:
                    # r10 read-only subscriber handshake — possibly a
                    # RESYNC on a live link (seq gap repair): a RANGE
                    # message may follow before DONE
                    self._pending_sub[link] = None
                    log.info(
                        "link %d joins read-only (subscriber handshake)",
                        link,
                    )
                self._pending[link] = bytearray(self.st.spec.total * 4)
        elif kind == wire.RANGE:
            wlo, wcnt = wire.decode_range(payload)
            words = self.st.spec.total // 32
            if link not in self._pending_sub:
                log.warning(
                    "ignoring RANGE on link %d outside a subscriber "
                    "handshake", link,
                )
            elif not (0 <= wlo and 0 < wcnt and wlo + wcnt <= words):
                self._send_blocking(
                    link,
                    wire.encode_reject(
                        f"range [{wlo}, {wlo + wcnt}) outside the "
                        f"{words}-word table"
                    ),
                )
                self.node.drop_link_flushed(link)
                self._pending.pop(link, None)
                self._pending_sub.pop(link, None)
            else:
                self._pending_sub[link] = (wlo, wcnt)
        elif kind == wire.CHUNK:
            buf = self._pending.get(link)
            if buf is not None:
                wire.decode_chunk_into(payload, buf)
        elif kind == wire.DONE:
            buf = self._pending.pop(link, None)
            if buf is not None and link in self._pending_sub:
                # r10 subscriber attach / resync re-seed (the subscriber's
                # handshake carries no snapshot upload — the parent pushes
                # ITS snapshot down the control plane instead)
                self._attach_sub(link, self._pending_sub.pop(link))
                self._wake.set()
            elif buf is not None:
                # tier-native: numpy on the host tier (no backend init)
                snap = self.st._asarray(np.frombuffer(bytes(buf), "<f4"))
                # WELCOME is enqueued BEFORE the codec link opens: per-link
                # FIFO then guarantees the child sees WELCOME before any
                # DATA. In the reverse order the sender (native engine:
                # microseconds after attach) can put DATA on the wire first;
                # the child applies it pre-WELCOME AND counts it again in
                # its attach diff (residual = values_now - sent_snapshot) —
                # echoing the mass back upward, a permanent +M divergence.
                # An add() landing between the two calls is safe: it's in
                # `values` by attach time, so the diff seed carries it.
                # The WELCOME carries OUR capability flags (r11 trailing
                # byte — pre-r11 children dispatch on the kind byte alone
                # and ignore it) and, r14, the same-host shm segment
                # offer: the segment is SERVED (created + mapped, rx ring
                # armed) before the WELCOME ships, so the name the child
                # reads is guaranteed to exist when it joins. A failed
                # serve (no /dev/shm space, compat mode) degrades to a
                # plain WELCOME — the link keeps TCP.
                from ..compat import SYNC_FLAG_SHM, SYNC_FLAG_SIGN2

                wflags = SYNC_FLAG_SIGN2 if self._sign2 else 0
                shm_offer = None
                # the flag marks US as r14 (the child may then emit the
                # aligned v3 framing toward us) even when no segment
                # offer follows (cross-host r14 tree, serve failure)
                if self._shm_ok:
                    wflags |= SYNC_FLAG_SHM
                if self._peer_shm.pop(link, False):
                    served = self.node.shm_serve(
                        link, self._shm_ring_bytes()
                    )
                    if served is not None:
                        shm_offer = (self._shm_host, served[1], served[0])
                self._send_blocking(
                    link, wire.encode_welcome(wflags, shm_offer)
                )
                self._attach_diff(link, snap)
                self._wake.set()
        elif kind == wire.WELCOME:
            from ..compat import SYNC_FLAG_SIGN2

            # r11: the parent's capability flags ride the WELCOME tail (a
            # pre-r11 parent's bare WELCOME reads back as 0 — the uplink
            # then stays 1-bit)
            self._peer_sign2[link] = bool(
                wire.welcome_flags(payload) & SYNC_FLAG_SIGN2
            )
            # r14: the parent's flag marks it r14 (v3-framing decoder);
            # gated on OUR _shm_ok so ST_SHM=0 pins v2 emission too (the
            # documented pre-r14 escape hatch is end-to-end)
            from ..compat import SYNC_FLAG_SHM

            self._peer_r14[link] = bool(
                self._shm_ok
                and wire.welcome_flags(payload) & SYNC_FLAG_SHM
            )
            # ...and a same-host parent offered its shm segment — join it
            # (map + token-validate); ANY failure keeps the uplink on TCP
            # with a shm_fallback timeline event recording why
            offer = wire.welcome_shm(payload)
            if offer is not None and self._shm_ok:
                o_host, o_token, o_name = offer
                if o_host == self._shm_host:
                    if not self.node.shm_join(link, o_name, o_token):
                        log.info(
                            "shm attach on uplink %d failed — keeping TCP "
                            "(see the shm_fallback timeline event)", link,
                        )
            snap = self._sent_snapshot
            self._sent_snapshot = None
            if snap is not None:
                # everything we hold that the snapshot didn't claim — the
                # carried residual plus adds/floods during the handshake —
                # is owed upward
                self._attach_diff(link, snap)
            else:  # duplicate WELCOME; be tolerant
                self._attach_zero(link)
            self._ready.set()
            self._wake.set()
        elif kind == wire.DIGEST:
            # r09 in-band aggregation: a subtree's bounded metrics digest.
            # Latest-wins per link; merged lazily at the next build. Engine
            # links route here too (the C receiver defers every non-data
            # kind to poll_ctrl).
            self._child_digests[link] = wire.decode_digest(payload)
            if self._obs is not None:
                self._obs.digest_in.inc()
        elif kind == wire.CLOCK:
            # r18 clock plane: a child's four-stamp offset probe (answer
            # synchronously down the SAME link — the turnaround time is
            # inside the child's measured RTT either way), or our own
            # uplink's reply (fold into the estimator). Chaos-exempt
            # control traffic, the r06 rule.
            doc = wire.decode_clock(payload)
            if doc.get("op") == "probe":
                try:
                    self.node.send(
                        link,
                        wire.encode_clock(self._clock.reply_payload(doc)),
                        timeout=0.05,
                    )
                except BrokenPipeError:
                    pass  # prober died; nothing to answer
            elif doc.get("op") == "reply" and link == self._uplink:
                self._clock.on_reply(doc)
        elif kind == wire.SNAP:
            # r12 lifecycle barrier marker from our parent: per-link FIFO
            # means every pre-pause data message on this link was applied
            # before this handler runs — the consistent-cut property
            self._lc_begin(wire.decode_lifecycle(payload), link)
        elif kind == wire.SNAP_ACK:
            doc = wire.decode_lifecycle(payload)
            op = self._lc_op
            if op is None or str(doc.get("id")) != op["id"]:
                log.warning(
                    "stray SNAP_ACK on link %d (id %s)", link, doc.get("id")
                )
                return
            op["waiting"].discard(link)
            op["entries"].extend(doc.get("nodes", []))
            op["errors"].extend(doc.get("errors", []))
            self._snap_acks += max(1, len(doc.get("nodes", [])))
        elif kind == wire.RESUME:
            doc = wire.decode_lifecycle(payload)
            op = self._lc_op
            if op is not None and str(doc.get("id")) != op["id"]:
                # a RESUME for a barrier we never joined (we NACKed its
                # SNAP, so our subtree never saw it either): releasing on
                # it would unpause this node mid-cut of the barrier we ARE
                # in. Our own barrier's RESUME — or the pause deadline —
                # releases us.
                log.warning(
                    "ignoring RESUME for foreign barrier %s (active: %s)",
                    doc.get("id"), op["id"],
                )
                return
            # release the subtree FIRST: children must never stay paused
            # because of our own state
            for child in self._lc_children(exclude=link):
                self._send_blocking(child, payload)
            self._lc_op = None
            self._set_paused(False)
        elif kind == wire.CTL:
            self._handle_ctl_msg(wire.decode_lifecycle(payload), link)
        elif kind == wire.REJECT:
            self._error = SpecMismatch(wire.decode_reject(payload))
            self._ready.set()  # unblock wait_ready, which re-raises
        else:
            raise ValueError(f"unknown message kind {kind}")

    def _decode_compat(self, link: int, payload: bytes):
        """Decode one reference-wire frame; returns a TableFrame to batch, or
        None for idle keepalives (which still count for readiness)."""
        frame = wire.decode_compat_frame(payload, self.st.spec)
        if link == self._uplink and not self._ready.is_set():
            # Readiness = the parent's stream is flowing. Counting zero-scale
            # keepalives too fixes the reference's all-zero-tensor hang
            # (quirk Q4): an idle parent still proves liveness within 1s.
            self._ready.set()
        return frame  # None = reference idle keepalive (quirk Q2)


def create_or_fetch(
    host: str,
    port: int,
    template: Any,
    config: Config | None = None,
    timeout: float = 30.0,
) -> SharedTensorPeer:
    """The reference's entry point (``sharedtensor.createOrFetch``,
    src/sharedtensor.c:347): create the shared tensor at ``host:port`` if
    nobody owns it yet (becoming master, seeded from ``template``), else join
    the existing tree (``template`` supplies only the table layout).

    Blocks until the node is ready — master immediately, joiner after the
    state-transfer handshake.
    """
    peer = SharedTensorPeer(host, port, template, config)
    try:
        peer.wait_ready(timeout)
    except BaseException:
        peer.close()
        raise
    return peer
