"""Deterministic fault injection at the wire boundary (both tiers).

The reference's entire failure story is ``exit(-1)`` on any socket error
(quirk Q8); this framework instead claims ledger rollback, re-graft carry,
master failover, quarantine and bounded-time joins — claims that are only
worth anything if the paths are *exercised deterministically*, not just
described. This module is the chaos source that does so:

- :class:`FaultPlan` wraps a frozen
  :class:`~shared_tensor_tpu.config.FaultConfig` with a seeded RNG and
  per-link frame counters. The peer engine consults it at its send boundary
  (``peer._send_blocking``) and at named protocol points
  (``peer._fault_point``). Everything is a pure function of
  (seed, per-link frame sequence): the same plan over the same traffic
  replays the same chaos.
- :func:`to_env` renders the same config into the ``ST_FAULT_PLAN`` /
  ``ST_FAULT_CRASH`` environment strings the NATIVE tier parses
  (sttransport.cpp's per-link fault table; stengine.cpp / sttransport.cpp
  crash points), so both data planes face identical fault classes. The env
  table is read per ``st_node_create`` — set it before creating one node's
  transport and only that node is chaotic.

Fault classes and which recovery path each drives:

==================  =======================================================
fault               recovery path exercised
==================  =======================================================
drop / stall        sender's unacked ledger grows; the go-back-N delivery
                    timer retransmits the tail byte-identical (exact
                    recovery), or link death rolls it into the re-graft
                    carry (at-least-once)
duplicate           receiver's wire-seq dedup discards the echo —
                    exactly-once (wire.py tx_seq discipline)
truncate            receiver decode guard rejects the sheared message
                    WITHOUT consuming its seq; retransmission re-delivers
                    it whole — exact recovery
corrupt             receiver decode guard (non-finite scales zeroed) —
                    bounded per-frame loss
delay               reordering pressure on drain()/ACK retry logic
sever               transport LINK_DOWN -> rollback -> carry -> re-graft
crash points        process death at the worst instants: mid-join-walk,
                    mid-burst (ledgered, unsent), between apply and ACK
                    (the two-generals window)
quarantine (cfg)    a stalled-but-open peer is torn down after N
                    consecutive failed sends instead of retried hot
==================  =======================================================

Frames only: faults never touch handshake (SYNC/CHUNK/WELCOME/REJECT) or
ACK traffic, so injected chaos exercises recovery instead of wedging a
join the protocol has no retry for.
"""

from __future__ import annotations

import logging
import os
import random
import threading
from collections import Counter
from typing import Callable, Optional

from .. import obs as _obs
from ..config import FaultConfig

log = logging.getLogger("shared_tensor_tpu.faults")

#: Exit status used by default crash actions (native tier uses the same via
#: _exit(17)), so a soak harness can tell an injected kill from a real one.
CRASH_EXIT_CODE = 17

#: The named protocol points a plan may kill a peer at.
CRASH_POINTS = ("mid-join-walk", "mid-burst", "between-apply-and-ack")


class FaultPlan:
    """One peer's live fault state: the frozen config + seeded RNG +
    per-link counters. Thread-safe (the peer's send and recv threads both
    consult it). ``counts`` tallies every injected event for soak-bound
    accounting (a convergence bound must scale with the chaos actually
    injected, not the probabilities requested)."""

    def __init__(
        self,
        config: FaultConfig,
        on_crash: Optional[Callable[[str], None]] = None,
        scale_bytes: int = 0,
        wire_compat: bool = False,
        trace_bytes: int = 0,
    ):
        if config.crash_point and config.crash_point not in CRASH_POINTS:
            raise ValueError(
                f"unknown crash point {config.crash_point!r} "
                f"(valid: {CRASH_POINTS})"
            )
        self.cfg = config
        #: Bytes of scale prefix per frame (4 * num_leaves) — lets corrupt()
        #: land its bit flips in the packed sign words of DATA *and* BURST
        #: payloads (a burst interleaves scales between frames, so a
        #: geometry-blind flip could hit a later frame's scale exponent —
        #: unbounded chaos; see corrupt()). 0 = geometry unknown.
        self.scale_bytes = scale_bytes
        #: Bytes of r09 trace context in the peer's DATA/BURST headers (13
        #: on a v2-emitting peer, 0 on v1): corrupt() must skip them too —
        #: a flip in the origin_ns field would only garble telemetry, but
        #: one in a scale EXPONENT rescales a whole frame by up to 2^127
        #: (the unbounded class this injector exists to avoid).
        self.trace_bytes = trace_bytes
        #: Wire-compat links carry the reference's fixed-size raw frames:
        #: no seqs, no ACKs, no retransmission. Truncation would shear the
        #: fixed-size re-framing (every later frame misparsed) and a
        #: duplicate would double-apply with no dedup — chaos with NO
        #: recovery path, which this layer never injects (module
        #: docstring); both classes are skipped on compat links. The C
        #: injector gates identically (sttransport.cpp link_sender_loop).
        self.wire_compat = wire_compat
        self._rng = random.Random(config.seed)
        self._sent: dict[int, int] = {}  # link -> data frames seen
        self._point_hits: dict[str, int] = {}
        self._mu = threading.Lock()
        self._on_crash = on_crash
        self.counts: Counter = Counter()
        # every injected event also lands on the cross-tier timeline (the
        # r08 flight recorder) under the same names the NATIVE injector
        # emits (obs/events.py fault codes) — a chaos run's timeline must
        # account for every hit, whichever tier injected it
        self._hub = _obs.hub() if _obs.obs_enabled() else None

    def _event(self, name: str, link: int, arg: int = 0) -> None:
        if self._hub is not None:
            self._hub.emit(name, link=link, arg=arg)

    @property
    def active(self) -> bool:
        return self.cfg.enabled

    def on_send(
        self, link: int, payload: bytes
    ) -> tuple[list[bytes], float, bool]:
        """Decide one outgoing DATA/BURST message's fate. Returns
        ``(payloads, delay_sec, sever)``: the caller sleeps ``delay_sec``,
        sends each payload in order (possibly none — the frame vanished on
        the wire, exactly what the ledger exists to survive — or two), and
        tears the link down after when ``sever`` is set."""
        cfg = self.cfg
        if not cfg.enabled:
            return [payload], 0.0, False
        if cfg.only_link > 0 and link != cfg.only_link:
            return [payload], 0.0, False
        with self._mu:
            n = self._sent[link] = self._sent.get(link, 0) + 1
            r = self._rng
            if cfg.sever_after_frames > 0 and n >= cfg.sever_after_frames:
                self.counts["severed"] += 1
                self._event("fault_sever", link, n)
                return [], 0.0, True
            if cfg.stall_after_frames >= 0 and n > cfg.stall_after_frames:
                self.counts["stalled"] += 1
                self._event("fault_stall", link, n)
                return [], 0.0, False
            delay = 0.0
            if cfg.delay_pct > 0 and r.random() < cfg.delay_pct:
                self.counts["delayed"] += 1
                self._event("fault_delay", link, int(cfg.delay_sec * 1e3))
                delay = cfg.delay_sec
            if cfg.drop_pct > 0 and r.random() < cfg.drop_pct:
                self.counts["dropped"] += 1
                self._event("fault_drop", link, n)
                return [], delay, False
            out = payload
            if (
                cfg.corrupt_pct > 0
                and len(payload) > 1
                and r.random() < cfg.corrupt_pct
            ):
                self.counts["corrupted"] += 1
                self._event("fault_corrupt", link, n)
                out = corrupt(out, r, self.scale_bytes, self.trace_bytes)
            if (
                cfg.truncate_pct > 0
                and not self.wire_compat  # would shear the fixed framing
                and len(out) > 2
                and r.random() < cfg.truncate_pct
            ):
                self.counts["truncated"] += 1
                self._event("fault_truncate", link, n)
                out = out[: r.randrange(1, len(out))]
            if (
                cfg.dup_pct > 0
                and not self.wire_compat  # compat has no dedup
                and r.random() < cfg.dup_pct
            ):
                self.counts["duplicated"] += 1
                self._event("fault_dup", link, n)
                return [out, out], delay, False
            return [out], delay, False

    def point(self, name: str) -> None:
        """A named protocol point was reached; kill the peer here when the
        plan says so. Default action is ``os._exit`` — the point of a
        crash fault is that NOTHING below it runs (no drain, no seal, no
        destructor), exactly like SIGKILL. Tests pass ``on_crash`` to
        observe the hit in-process instead."""
        cfg = self.cfg
        if not cfg.enabled or cfg.crash_point != name:
            return
        with self._mu:
            hits = self._point_hits[name] = self._point_hits.get(name, 0) + 1
            if hits < max(1, cfg.crash_after):
                return
            self.counts["crashed"] += 1
        self._event("crash_point", 0, hits)
        if self._on_crash is not None:
            self._on_crash(name)
            return
        log.warning("fault plan killing peer at protocol point %r", name)
        # last act before the kill: dump the flight recorder (merged
        # native+Python timeline + registry snapshots), so the "worst
        # instant" chaos leaves an explainable trace instead of just a
        # corpse. os._exit follows REGARDLESS of the dump's fate — the
        # crash semantics (nothing below the point runs) stay exact.
        if self._hub is not None:
            self._hub.poll_native()
            self._hub.dump(f"crash_point:{name}")
        os._exit(CRASH_EXIT_CODE)


def corrupt(
    payload: bytes, rng: random.Random, scale_bytes: int = 0,
    trace_bytes: int = 0,
) -> bytes:
    """Flip one random bit in the packed SIGN WORDS of one frame: past the
    kind byte (the message still routes as DATA/BURST), past the r09
    trace context when the emitter stamps one (``trace_bytes`` = 13 on a
    v2 peer), and past every scale prefix. A flipped sign bit mis-applies
    one element by 2*scale — bounded, which is what lets the chaos soak
    assert convergence-within-bound. A flipped scale-EXPONENT bit would
    instead multiply a whole frame's mass by up to 2^127 while remaining
    protocol-legal (finite scales up to 2^127 are inside the wire's trust
    domain — see wire.decode_frame), i.e. chaos no recovery path can
    bound; the codec has no scale authentication by design. Bursts
    interleave a scale prefix before EVERY frame, so the word spans are
    computed from the payload's own framing (``scale_bytes`` = 4 *
    num_leaves, from the peer's spec); with the geometry unknown
    (scale_bytes=0) the flip falls back to the last 3/4 of the payload —
    sign words for single-frame DATA, best-effort otherwise."""
    b = bytearray(payload)
    lo, hi = 0, 0
    data_hdr = 5 + trace_bytes  # [kind][u32 seq][trace?]
    burst_hdr = 6 + trace_bytes  # [kind][u32 seq][u8 k][trace?]
    rdata_hdr = 13 + trace_bytes  # [kind][u32 seq][u32 lo][u32 cnt][trace?]
    if scale_bytes > 0 and b[0] == 0 and len(b) > data_hdr + scale_bytes:
        # DATA: one frame after the header
        lo, hi = data_hdr + scale_bytes, len(b)
    elif scale_bytes > 0 and b[0] == 11 and len(b) > rdata_hdr + scale_bytes:
        # RDATA (r10 range-filtered frame): one frame's scales then the
        # sliced words after the range header — same bounded-flip rule
        # (never the seq/range fields, never a scale exponent)
        lo, hi = rdata_hdr + scale_bytes, len(b)
    elif scale_bytes > 0 and b[0] == 7 and len(b) > burst_hdr:
        k = b[5]
        per = (len(b) - burst_hdr) // k if k else 0
        if k and per > scale_bytes and burst_hdr + k * per == len(b):
            f = rng.randrange(k)  # one frame's words span
            lo = burst_hdr + f * per + scale_bytes
            hi = burst_hdr + (f + 1) * per
    if not lo:
        lo, hi = max(1, len(b) // 4), len(b)
    i = rng.randrange(lo, hi)
    b[i] ^= 1 << rng.randrange(8)
    return bytes(b)


def to_env(cfg: FaultConfig) -> dict[str, str]:
    """Render a FaultConfig into the native tier's environment hook table:
    ``ST_FAULT_PLAN`` (per-link wire faults, parsed by st_node_create — set
    it around ONE node's creation to make only that node chaotic) and
    ``ST_FAULT_CRASH`` (process-wide crash point, parsed once per process
    by the .so). Keys whose value is the default are omitted, so an
    all-default config renders to {} (no injection). Caveat: the native
    injector's ``corrupt`` is geometry-blind (FaultConfig.corrupt_pct) —
    unlike this module's :func:`corrupt` it may hit seq/scale bytes, so
    treat native corruption as survival chaos, not bounded chaos."""
    if not cfg.enabled:
        return {}
    parts = [f"seed={cfg.seed}"]
    if cfg.drop_pct > 0:
        parts.append(f"drop={cfg.drop_pct}")
    if cfg.dup_pct > 0:
        parts.append(f"dup={cfg.dup_pct}")
    if cfg.truncate_pct > 0:
        parts.append(f"trunc={cfg.truncate_pct}")
    if cfg.corrupt_pct > 0:
        parts.append(f"corrupt={cfg.corrupt_pct}")
    if cfg.delay_pct > 0:
        parts.append(f"delay_pct={cfg.delay_pct}")
        parts.append(f"delay_ms={cfg.delay_sec * 1000.0}")
    if cfg.stall_after_frames >= 0:
        parts.append(f"stall_after={cfg.stall_after_frames}")
    if cfg.sever_after_frames > 0:
        parts.append(f"sever_after={cfg.sever_after_frames}")
    if cfg.only_link > 0:
        parts.append(f"only_link={cfg.only_link}")
    if cfg.only_stripe >= 0:
        parts.append(f"only_stripe={cfg.only_stripe}")
    env = {"ST_FAULT_PLAN": ",".join(parts)}
    if cfg.crash_point:
        env["ST_FAULT_CRASH"] = f"{cfg.crash_point}:{max(1, cfg.crash_after)}"
    return env
