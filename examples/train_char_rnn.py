"""char-rnn async-DP training demo (BASELINE config 2: "char-rnn param sync,
4 peers, approximate-delta compression on").

Two modes:

- pod (default): N peers as devices on one mesh, compressed sync over ICI —
  `python examples/train_char_rnn.py corpus.txt --peers 4`
  (on CPU, prefix JAX_PLATFORMS=cpu and the 8-device XLA flag; on a v5e-8
  each peer is a real chip).
- peer: one process per worker over the TCP tree, reference-style —
  `python examples/train_char_rnn.py corpus.txt --peer 127.0.0.1:50000`
  run in multiple terminals; first becomes master.
"""

import argparse
import pathlib
import sys
import time

import jax
import jax.numpy as jnp

from shared_tensor_tpu.models import char_rnn as m


def train_pod(text: bytes, cfg, args) -> None:
    from shared_tensor_tpu.parallel.mesh import make_mesh
    from shared_tensor_tpu.train import PodTrainer

    n = args.peers
    mesh = make_mesh(n, 1)
    params = m.init_params(jax.random.key(0), cfg)
    tr = PodTrainer(
        mesh, params, lambda p, b: m.loss_fn(p, b, cfg), overlap=args.overlap
    )
    data = m.encode_corpus(text)
    print(f"{cfg.param_count} params, {n} peers, backend={jax.default_backend()}")
    t0 = time.perf_counter()
    for i in range(args.steps):
        batch = tr.shard_batch(
            m.make_batches(data, args.batch, args.seq, jax.random.key(i), n_peer=n)
        )
        losses, scales = tr.step(batch, lr=args.lr)
        if i % 20 == 0 or i == args.steps - 1:
            toks = (i + 1) * n * args.batch * args.seq
            print(
                f"step {i:4d} loss {float(jnp.mean(losses)):.3f} "
                f"spread {tr.replica_spread():.2e} "
                f"tok/s {toks / (time.perf_counter() - t0):.0f}"
            )
    prompt = jnp.frombuffer(text[:16], dtype=jnp.uint8).astype(jnp.int32)
    out = m.sample(tr.read(0), jax.random.key(1), prompt, cfg, length=200, temperature=0.8)
    print("--- sample ---")
    print((text[:16] + bytes(int(t) % 256 for t in out)).decode(errors="replace"))


def train_peer(text: bytes, cfg, args) -> None:
    from shared_tensor_tpu.comm.peer import create_or_fetch

    host, port = args.peer.rsplit(":", 1)
    params = m.init_params(jax.random.key(0), cfg)
    data = m.encode_corpus(text)
    grad = jax.jit(jax.grad(lambda p, b: m.loss_fn(p, b, cfg)))
    with create_or_fetch(host, int(port), params) as st:
        t0 = time.perf_counter()
        for i in range(args.steps):
            params = st.read()
            batch = m.make_batches(data, args.batch, args.seq, jax.random.key(i))
            g = grad(params, batch)
            st.add(jax.tree.map(lambda x: -args.lr * x, g))
            if i % 20 == 0:
                loss = float(m.loss_fn(params, batch, cfg))
                print(f"step {i:4d} loss {loss:.3f} {st.metrics(canonical=True)}")
        print(f"done in {time.perf_counter() - t0:.1f}s; final metrics {st.metrics(canonical=True)}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("corpus", nargs="?", help="text file (default: built-in pangram)")
    ap.add_argument("--peers", type=int, default=4)
    ap.add_argument("--peer", help="host:port — join/seed the TCP tree instead of a pod mesh")
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument(
        "--overlap", action="store_true",
        help="schedule the ICI sync collective under the backward pass",
    )
    args = ap.parse_args()

    if args.corpus:
        text = pathlib.Path(args.corpus).read_bytes()
    else:
        text = b"The quick brown fox jumps over the lazy dog. " * 2000
    if len(text) < args.seq + 2:
        sys.exit("corpus too small for --seq")

    cfg = m.CharRNNConfig(hidden=args.hidden, layers=args.layers)
    if args.peer:
        train_peer(text, cfg, args)
    else:
        train_pod(text, cfg, args)


if __name__ == "__main__":
    main()
