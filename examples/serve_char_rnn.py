"""char-rnn SERVING demo (r10): a read-only subscriber replica serves text
generation while trainer peers stream weight updates through the tree.

The read-path twin of train_char_rnn.py's peer mode, and the shape of an
inference fleet on this system:

- N trainer peers (writers) join the tree at the rendezvous and run
  async-SGD, each ``add()``-ing its own gradient steps;
- one SUBSCRIBER joins as a read-only leaf (it never adds — writers keep
  zero ledger/ACK state for it), and a :class:`serve.ServingHandle`
  hot-swaps verified snapshots into the sampling loop;
- every swap VERIFIES its staleness bound against the r09 origin stamps /
  FRESH drain marks — a violation raises StalenessError instead of
  serving stale weights (run it under chaos and watch the refusals).

Single-process demo by default (trainers on background threads, the
subscriber serving from the main thread); pass --peer/--serve to split
across real processes:

  # terminal 1..n: trainers (writers)
  python examples/serve_char_rnn.py --peer 127.0.0.1:50000
  # terminal n+1: the serving replica
  python examples/serve_char_rnn.py --serve 127.0.0.1:50000
"""

import argparse
import os
import pathlib
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from shared_tensor_tpu import serve
from shared_tensor_tpu.models import char_rnn as m


def run_trainer(host, port, cfg, text, args, stop=None, tag="trainer",
                ready=None):
    from shared_tensor_tpu.comm.peer import create_or_fetch

    params = m.init_params(jax.random.key(0), cfg)
    data = m.encode_corpus(text)
    grad = jax.jit(jax.grad(lambda p, b: m.loss_fn(p, b, cfg)))
    with create_or_fetch(host, port, params) as st:
        if ready is not None:
            ready.set()  # the tree exists: joiners/subscribers may start
        for i in range(args.steps):
            if stop is not None and stop.is_set():
                break
            params = st.read()
            batch = m.make_batches(data, args.batch, args.seq, jax.random.key(i))
            g = grad(params, batch)
            st.add(jax.tree.map(lambda x: -args.lr * x, g))
            if i % 20 == 0:
                print(f"[{tag}] step {i:4d} "
                      f"loss {float(m.loss_fn(params, batch, cfg)):.3f}")
        st.drain(timeout=30.0, tol=1e-30)


def run_server(host, port, cfg, text, args, stop=None):
    """The serving loop: subscribe read-only, hot-swap verified weights,
    sample. Every ``refresh`` is a verified bounded-staleness read — the
    only way stale weights could be served is loudly, as an exception."""
    template = m.init_params(jax.random.key(0), cfg)
    sub = serve.subscribe(host, port, template, timeout=60.0)
    handle = sub.serving_handle(max_staleness=args.max_staleness)
    served = refused = 0
    prompt = jnp.frombuffer(text[:8], dtype=jnp.uint8).astype(jnp.int32)
    try:
        deadline = time.monotonic() + args.serve_seconds
        while time.monotonic() < deadline:
            if stop is not None and stop.is_set() and served:
                break
            try:
                handle.refresh()
            except serve.StalenessError as e:
                refused += 1
                print(f"[serve] REFUSED: {e}")
                time.sleep(0.25)
                continue
            out = m.sample(
                handle.params(), jax.random.key(served), prompt, cfg,
                length=args.sample_len, temperature=0.8,
            )
            txt = (text[:8] + bytes(int(t) % 256 for t in out)).decode(
                errors="replace"
            )
            served += 1
            print(
                f"[serve] v{handle.version} "
                f"staleness {handle.staleness:.3f}s (bound "
                f"{args.max_staleness}s): {txt[:72]!r}"
            )
            time.sleep(args.serve_interval)
    finally:
        mtr = sub.metrics()
        print(
            f"[serve] served {served} samples, {refused} refused; "
            f"reads ok/stale = {mtr['st_read_total']:.0f}/"
            f"{mtr['st_read_stale_total']:.0f}, "
            f"resyncs {mtr['st_sub_resyncs_total']:.0f}"
        )
        sub.close()
    if served == 0:
        sys.exit("[serve] nothing served — were the trainers up?")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("corpus", nargs="?", help="text file (default: pangram)")
    ap.add_argument("--peer", help="host:port — run ONE trainer process")
    ap.add_argument("--serve", help="host:port — run ONE serving process")
    ap.add_argument("--port", type=int, default=50310)
    ap.add_argument("--trainers", type=int, default=2)
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=48)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--hidden", type=int, default=96)
    ap.add_argument("--layers", type=int, default=1)
    ap.add_argument("--max-staleness", type=float, default=1.0)
    ap.add_argument("--serve-seconds", type=float, default=30.0)
    ap.add_argument("--serve-interval", type=float, default=0.5)
    ap.add_argument("--sample-len", type=int, default=64)
    args = ap.parse_args()

    if args.corpus:
        text = pathlib.Path(args.corpus).read_bytes()
    else:
        text = b"The quick brown fox jumps over the lazy dog. " * 500
    cfg = m.CharRNNConfig(hidden=args.hidden, layers=args.layers)

    if args.peer:
        host, port = args.peer.rsplit(":", 1)
        run_trainer(host, int(port), cfg, text, args)
        return
    if args.serve:
        host, port = args.serve.rsplit(":", 1)
        run_server(host, int(port), cfg, text, args)
        return

    # single-process demo: trainers on threads, serving on the main thread
    host, port = "127.0.0.1", args.port
    stop = threading.Event()
    master_up = threading.Event()
    # demo trainers train for the WHOLE serving window (stop ends them);
    # --steps only bounds the split-process mode
    t_args = argparse.Namespace(**{**vars(args), "steps": 10**9})
    trainers = [
        threading.Thread(
            target=run_trainer,
            args=(host, port, cfg, text, t_args, stop, f"trainer{i}"),
            kwargs={"ready": master_up if i == 0 else None},
            daemon=True,
        )
        for i in range(args.trainers)
    ]
    trainers[0].start()
    if not master_up.wait(120.0):  # model init + jit happen before the join
        sys.exit("trainer 0 never claimed the rendezvous")
    for t in trainers[1:]:
        t.start()
    try:
        run_server(host, port, cfg, text, args, stop)
    finally:
        stop.set()
        for t in trainers:
            t.join(timeout=60.0)


if __name__ == "__main__":
    main()
