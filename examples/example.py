"""The reference's example.lua, 1:1 program shape (BASELINE config 1).

Run once to become master at 127.0.0.1:50000; run more copies (same command,
other terminals) to join the tree. Every process adds 1s each second and
prints its replica — watch the values converge across processes as updates
flood through (reference example.lua:1-26, README.md:8-19).

Usage:  python examples/example.py [host] [port] [--steps N]

Tip: run with JAX_PLATFORMS=cpu for multi-process demos on one machine; the
single TPU chip can only be claimed by one process at a time.
"""

import argparse
import time

import jax.numpy as jnp

from shared_tensor_tpu.comm.peer import create_or_fetch


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("host", nargs="?", default="127.0.0.1")
    ap.add_argument("port", nargs="?", type=int, default=50000)
    ap.add_argument("--steps", type=int, default=0, help="0 = run forever")
    args = ap.parse_args()

    # torch.range(1,4):float()  (example.lua:4)
    x = jnp.arange(1.0, 5.0, dtype=jnp.float32)

    with create_or_fetch(args.host, args.port, x) as a:
        step = 0
        while args.steps == 0 or step < args.steps:
            x = a.read()  # a:copyToTensor(x)

            # do something computationally intensive with x
            results = jnp.ones_like(x)

            # Add our updates into a, which will be asynchronously
            # propagated to all other connected programs.
            a.add(results)  # a:addFromTensor(results)

            print(x)
            time.sleep(1)  # just so you can see what's going on
            step += 1


if __name__ == "__main__":
    main()
