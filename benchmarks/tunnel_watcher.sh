#!/bin/bash
# Tunnel heal watcher: probes the axon TPU tunnel every 5 minutes with a
# bounded, SIGTERM-only probe (one process touches the chip at a time; a
# wedged probe dies cleanly and leaves no grant held). On the first probe
# that completes a real device matmul, runs the on-chip runbook
# (chip_runbook.sh) exactly once and exits. All output goes to
# /tmp/tunnel_watch.log; runbook output to /tmp/chip_runbook.log.
#
# Round-3 context: the tunnel was wedged for half of round 3 and all of the
# first round-4 session (ARTIFACTS.md item 1); this watcher exists so a heal
# is never missed while other work proceeds.
set -u
LOG=/tmp/tunnel_watch.log
cd /root/repo
echo "watcher start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if PYTHONPATH=/root/repo:/root/.axon_site timeout 150 python - >> "$LOG" 2>&1 <<'EOF'
import time
t0 = time.time()
import jax
ds = jax.devices()
x = jax.numpy.ones((128, 128))
s = float((x @ x).sum())
assert s == 128.0 * 128 * 128, s
print(f"HEALED {time.strftime('%FT%TZ', time.gmtime())} devices={ds} probe_s={time.time()-t0:.1f}", flush=True)
EOF
  then
    echo "tunnel healed; running chip_runbook $(date -u +%FT%TZ)" >> "$LOG"
    # Outer bound ~= the sum of the runbook's own per-step timeouts: a chip
    # that re-wedges MID-runbook must not leave this watcher holding the
    # (single) chip grant forever — the exact contract the probe keeps.
    timeout --signal=TERM -k 60 4200 \
      bash benchmarks/chip_runbook.sh > /tmp/chip_runbook.log 2>&1
    echo "runbook done rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    exit 0
  fi
  echo "probe failed (wedged) $(date -u +%FT%TZ)" >> "$LOG"
  sleep 300
done
