"""Read-QPS × staleness Pareto under full write load (r10 acceptance
artifact).

Two writers (master + one trainer peer) hammer ``add()`` on an
N-element table — full write load on the engine data plane — while one
read-only subscriber serves verified bounded-staleness reads. For each
staleness bound the bench measures:

- **read QPS** (``read_flat``: verification + lock-free snapshot acquire —
  the per-request cost an inference frontend pays);
- **observed staleness** p50/p99 across every read ATTEMPT (a refused read
  contributes its measured staleness too — refusals are the bound working,
  not missing data);
- **refused fraction** (reads that raised StalenessError instead of
  serving past the bound);

plus one hot-swap arm (ServingHandle: background refresher + ``params()``
reference reads — what a model server's request path actually does) and
the achieved write rate as context.

Gate (suite_load.sh): the per-repeat p99 staleness at the gate bound must
satisfy ``lower90 <= bound`` — mean − 1.645·SEM across repeats, the same
lower-90% discipline as the obs-overhead gate, per this box's 5–10%
loopback noise (BASELINE/ARTIFACTS). The write-path perf floor
(bench_gate.py) runs in the same suite invocation, so SERVE_r10.json is
only ever committed alongside a passing ≥ ~31 GB/s equiv floor.

Run:  JAX_PLATFORMS=cpu python benchmarks/serve_bench.py SERVE_r10.json
Knobs: ST_SERVE_N (default 65536), ST_SERVE_SECONDS (3), ST_SERVE_REPEATS
(3), ST_SERVE_GATE_BOUND (1.0), ST_SERVE_BOUNDS ("0.05,0.25,1.0"),
ST_SERVE_ADD_HZ (100), ST_SERVE_READ_HZ (2000).
"""

import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N = int(os.environ.get("ST_SERVE_N", str(1 << 16)))
SECONDS = float(os.environ.get("ST_SERVE_SECONDS", "3"))
REPEATS = int(os.environ.get("ST_SERVE_REPEATS", "3"))
GATE_BOUND = float(os.environ.get("ST_SERVE_GATE_BOUND", "1.0"))
#: Adds/sec per writer. PACED, not a tight loop: two unthrottled engine
#: writers produce frames far faster than one python-tier subscriber can
#: absorb (that asymmetry is the engine's whole point — BENCH_r* measures
#: it), so an unpaced arm measures only queue growth. 100 Hz × 2 writers
#: on a 64 Ki table keeps the codec streaming continuously — a *serving*
#: fleet's write load — while the staleness numbers stay about the
#: pipeline, not about an unbounded backlog.
ADD_HZ = float(os.environ.get("ST_SERVE_ADD_HZ", "100"))
#: Read attempts/sec for the verification arms. Paced like a request
#: frontend, NOT a spin loop: an unthrottled pure-python refusal loop
#: monopolizes the GIL and starves the subscriber's own apply thread —
#: measuring self-inflicted starvation, not the pipeline. The unpaced
#: hot-path number is the hot_swap arm's params_qps (reference reads).
READ_HZ = float(os.environ.get("ST_SERVE_READ_HZ", "2000"))
BOUNDS = [
    float(x)
    for x in os.environ.get("ST_SERVE_BOUNDS", "0.05,0.25,1.0").split(",")
]


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _pct(sorted_xs, q):
    if not sorted_xs:
        return None
    i = min(len(sorted_xs) - 1, int(q * (len(sorted_xs) - 1)))
    return sorted_xs[i]


def main() -> int:
    art_path = sys.argv[1] if len(sys.argv) > 1 else "SERVE_r10.json"
    import numpy as np

    from shared_tensor_tpu import serve
    from shared_tensor_tpu.comm.peer import create_or_fetch

    port = _free_port()
    rng = np.random.default_rng(0)
    template = np.zeros(N, np.float32)
    writers = [
        create_or_fetch("127.0.0.1", port, template, timeout=60.0)
        for _ in range(2)
    ]
    sub = serve.subscribe("127.0.0.1", port, template, timeout=60.0)

    stop = threading.Event()
    adds = [0, 0]

    def writer_loop(i):
        d = rng.uniform(-0.1, 0.1, N).astype(np.float32)
        period = 1.0 / ADD_HZ if ADD_HZ > 0 else 0.0
        nxt = time.monotonic()
        while not stop.is_set():
            writers[i].add(d)
            adds[i] += 1
            if period:
                nxt += period
                lag = nxt - time.monotonic()
                if lag > 0:
                    time.sleep(lag)
                else:
                    nxt = time.monotonic()

    threads = [
        threading.Thread(target=writer_loop, args=(i,), daemon=True)
        for i in range(2)
    ]

    out = {
        "bench": "serve_bench",
        "n": N,
        "writers": 2,
        "add_hz_per_writer": ADD_HZ,
        "seconds_per_arm": SECONDS,
        "repeats": REPEATS,
        "engine_tier": all(w._engine is not None for w in writers),
        "gate_bound_sec": GATE_BOUND,
        "pareto": [],
    }
    try:
        for t in threads:
            t.start()
        t_load = time.monotonic()
        # let the write load reach steady state before measuring
        while time.monotonic() - t_load < 1.0:
            time.sleep(0.05)

        gate_p99s = []
        for bound in BOUNDS:
            rows = []
            for _rep in range(REPEATS):
                reads = refused = 0
                stal = []
                lat = []
                period = 1.0 / READ_HZ if READ_HZ > 0 else 0.0
                t0 = time.monotonic()
                nxt = t0
                while time.monotonic() - t0 < SECONDS:
                    ta = time.perf_counter()
                    try:
                        _flat, s, _ver = sub.read_flat(bound)
                        reads += 1
                        stal.append(s)
                    except serve.StalenessError as e:
                        refused += 1
                        if math.isfinite(e.staleness):
                            stal.append(e.staleness)
                    lat.append(time.perf_counter() - ta)
                    if period:
                        nxt += period
                        lag = nxt - time.monotonic()
                        if lag > 0:
                            time.sleep(lag)
                        else:
                            nxt = time.monotonic()
                dt = time.monotonic() - t0
                lat.sort()
                stal.sort()
                rows.append(
                    {
                        "read_qps": round(reads / dt, 1),
                        "refused": refused,
                        "read_latency_p99_us": (
                            round(_pct(lat, 0.99) * 1e6, 1) if lat else None
                        ),
                        "staleness_p50": _pct(stal, 0.50),
                        "staleness_p99": _pct(stal, 0.99),
                    }
                )
                if bound == GATE_BOUND and rows[-1]["staleness_p99"] is not None:
                    gate_p99s.append(rows[-1]["staleness_p99"])
            out["pareto"].append({"max_staleness_sec": bound, "repeats": rows})

        # hot-swap arm: a background refresher + pure params() reads — the
        # request-path cost of the double-buffered ServingHandle
        handle = sub.serving_handle(max_staleness=GATE_BOUND)
        hstop = threading.Event()

        def refresher():
            while not hstop.is_set():
                try:
                    handle.refresh()
                except serve.StalenessError:
                    pass
                time.sleep(0.02)

        rt = threading.Thread(target=refresher, daemon=True)
        rt.start()
        warm_deadline = time.monotonic() + 30.0
        while handle.params() is None and time.monotonic() < warm_deadline:
            time.sleep(0.01)
        if handle.params() is None:
            out["hot_swap"] = {"error": "never verified fresh within 30s"}
        else:
            pr = 0
            t0 = time.monotonic()
            while time.monotonic() - t0 < SECONDS:
                # spin in chunks: a pure-python spin would starve the
                # refresher/apply threads of the GIL (same rationale as
                # READ_HZ) — 10k reference reads per 1 ms breath still
                # measures the hot path
                for _ in range(10_000):
                    _p = handle.params()
                pr += 10_000
                time.sleep(0.001)
            out["hot_swap"] = {
                "params_qps": round(pr / SECONDS, 1),
                "swaps": handle.swaps,
                "staleness_at_last_swap": round(handle._staleness, 4),
            }
        hstop.set()
        rt.join()
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        wrote = time.monotonic() - t_load
        out["write_load"] = {
            "adds_per_sec": round(sum(adds) / max(wrote, 1e-9), 1),
            "adds_total": sum(adds),
        }
        sub_metrics = sub.metrics()
        out["subscriber"] = {
            k: sub_metrics.get(k)
            for k in (
                "st_read_total", "st_read_stale_total",
                "st_sub_resyncs_total", "st_sub_gap_discards_total",
                "st_sub_fresh_marks_total",
            )
        }
        sub.close()
        for w in writers:
            w.close()

    # gate: lower-90% bound of per-repeat p99 staleness at the gate bound
    k = len(gate_p99s)
    if k == 0:
        out["gate"] = {"error": "no successful gate-bound repeats"}
        out["pass"] = False
    else:
        mean = sum(gate_p99s) / k
        var = (
            sum((x - mean) ** 2 for x in gate_p99s) / (k - 1) if k > 1 else 0.0
        )
        sem = math.sqrt(var / k)
        lower90 = mean - 1.645 * sem
        out["gate"] = {
            "p99_mean_sec": round(mean, 4),
            "p99_sem_sec": round(sem, 4),
            "p99_lower90_sec": round(lower90, 4),
            "bound_sec": GATE_BOUND,
        }
        out["pass"] = bool(lower90 <= GATE_BOUND)

    doc = json.dumps(out, indent=2)
    print(doc)
    if not os.path.isabs(art_path):
        art_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            art_path,
        )
    with open(art_path, "w") as f:
        f.write(doc + "\n")
    g = out.get("gate", {})
    print(
        f"serve_bench: p99 staleness {g.get('p99_mean_sec')}s "
        f"(lower90 {g.get('p99_lower90_sec')}s) vs bound {GATE_BOUND}s -> "
        f"{'PASS' if out['pass'] else 'FAIL'}",
        file=sys.stderr,
    )
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
