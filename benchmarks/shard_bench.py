#!/usr/bin/env python
"""Shard-plane FWD throughput: python-tier vs engine-tier, per link.

The r17 tentpole's acceptance number. A 2-node sharded pair on loopback:
node 0 (master, owns shard 0) is the WRITER — every add() lands entirely
in shard 1's range, so all mass drains as owner-routed FWD frames over
the one link — and node 1 is the OWNER applying them. Per-link FWD
throughput is reported the way every bench here reports the data plane:

    GB/s equiv = applied FWD frames x slice f32 bytes / wall

(each 1-bit frame conveys a full-slice update against per-leaf scales —
the same fp32-equivalent convention as bench.py's headline.)

Arms (fresh pair per repeat, ShardConfig.engine_lane pins the plane):
  - python: the r16 correctness-first plane (the semantic reference);
  - engine: the r17 native plane (outbox quantize into tx slots,
    verbatim relay, owner-side dedup+apply in C).

Gate (suite_load.sh "shard-perf"): engine lower-90 (mean - 1.645*SEM
across repeats — the obs/serve-gate discipline; this box's loopback
noise is 5-10%) must clear the ratcheted floor from the newest committed
SHARD_BENCH_r*.json (floor_locked = max(prior floor, 0.9 x prior
headline), monotone non-decreasing), AND the engine/python mean ratio
must hold the r17 acceptance bar (>= 5x).

Usage: python benchmarks/shard_bench.py [SHARD_BENCH_r17.json]
"""

from __future__ import annotations

import glob
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from shared_tensor_tpu.config import (  # noqa: E402
    Config, ShardConfig, TransportConfig,
)
from shared_tensor_tpu.ops.table import make_spec  # noqa: E402
from shared_tensor_tpu.shard import create_or_fetch_sharded  # noqa: E402
from tests._ports import free_port  # noqa: E402

N = int(os.environ.get("ST_SHARD_BENCH_N", 1 << 19))  # elements (f32)
REPEATS = int(os.environ.get("ST_SHARD_BENCH_REPEATS", 3))
WARM_S = float(os.environ.get("ST_SHARD_BENCH_WARM_S", 1.0))
MEASURE_S = float(os.environ.get("ST_SHARD_BENCH_MEASURE_S", 4.0))
RATIO_BAR = 5.0  # the r17 acceptance criterion

TMPL = {"t": np.zeros(N, np.float32)}
SPEC = make_spec(TMPL)


def _cfg(idx: int, engine: bool) -> Config:
    return Config(
        shard=ShardConfig(n_shards=2, shard_index=idx, engine_lane=engine),
        transport=TransportConfig(
            peer_timeout_sec=20.0, ack_timeout_sec=0.4
        ),
    )


def run_arm(engine: bool) -> float:
    """One fresh writer->owner pair; returns GB/s equiv on the link."""
    port = free_port()
    h0 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(0, engine), timeout=30.0
    )
    h1 = create_or_fetch_sharded(
        "127.0.0.1", port, TMPL, _cfg(1, engine), timeout=30.0
    )
    try:
        lane = h0.node._lane is not None
        assert lane == engine, (
            f"arm wanted engine={engine} but lane={lane} — is the native "
            f"lib missing?"
        )
        m = h0.node.map
        elo, ehi = m.element_range(1)  # shard 1's slice: all out-of-shard
        slice_el = ehi - elo
        rng = np.random.default_rng(42)
        stop = threading.Event()

        def writer():
            # fresh mass into the remote shard's range every pass: the
            # outbox never goes idle, the pump stays saturated (a single
            # "t" leaf of a 32-multiple N has no padding, so the padded
            # element range IS the template index range). Deltas are
            # PRE-GENERATED — rng.uniform over the slice costs ~ms and
            # would meter the producer, not the plane under test.
            width = min(ehi, N) - elo
            deltas = []
            for _ in range(8):
                full = np.zeros(N, np.float32)
                full[elo:elo + width] = rng.uniform(
                    -0.1, 0.1, width
                ).astype(np.float32)
                deltas.append(full)
            i = 0
            while not stop.is_set():
                h0.add({"t": deltas[i % len(deltas)]})
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        time.sleep(WARM_S)
        f0 = h1.node.metrics().get("st_shard_fwd_frames_in_total", 0)
        t0 = time.monotonic()
        time.sleep(MEASURE_S)
        f1 = h1.node.metrics().get("st_shard_fwd_frames_in_total", 0)
        wall = time.monotonic() - t0
        stop.set()
        t.join(timeout=5.0)
        frames = int(f1) - int(f0)
        gbps = frames * slice_el * 4 / wall / 1e9
        return gbps
    finally:
        h1.close()
        h0.close()


def lower90(xs: list[float]) -> float:
    if len(xs) < 2:
        return xs[0] if xs else 0.0
    m = float(np.mean(xs))
    sem = float(np.std(xs, ddof=1)) / (len(xs) ** 0.5)
    return m - 1.645 * sem


def prior_floor(out_path: str) -> tuple[float, str]:
    """Newest committed SHARD_BENCH artifact by ROUND NUMBER (numeric —
    lexicographic sort misorders r99/r100), never the run's own output
    (the bench_gate discipline: ratcheting against a same-round artifact
    would demand 0.9x of our own lower-90 again inside the box's 5-10%
    noise)."""
    import re

    own = os.path.basename(out_path)
    best: tuple[int, str] | None = None
    for p in glob.glob(os.path.join(REPO, "SHARD_BENCH_r*.json")):
        name = os.path.basename(p)
        if name == own:
            continue
        m = re.match(r"SHARD_BENCH_r(\d+)\.json$", name)
        if not m:
            continue
        r = int(m.group(1))
        if best is None or r > best[0]:
            best = (r, p)
    if best is None:
        return 0.0, ""
    try:
        with open(best[1]) as f:
            doc = json.load(f)
        return float(doc.get("floor_locked", 0.0)), os.path.basename(best[1])
    except Exception:
        return 0.0, ""


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SHARD_BENCH_r17.json"
    if not os.path.isabs(out_path):
        out_path = os.path.join(REPO, out_path)
    res: dict[str, list[float]] = {"python": [], "engine": []}
    for r in range(REPEATS):
        for arm in ("python", "engine"):
            gbps = run_arm(arm == "engine")
            res[arm].append(gbps)
            print(
                f"repeat {r + 1}/{REPEATS} {arm}: {gbps:.3f} GB/s equiv",
                file=sys.stderr,
            )
    py_mean = float(np.mean(res["python"]))
    en_mean = float(np.mean(res["engine"]))
    en_l90 = lower90(res["engine"])
    ratio = en_mean / py_mean if py_mean > 0 else float("inf")
    floor, floor_src = prior_floor(out_path)
    new_floor = max(floor, 0.9 * en_l90)  # monotone ratchet
    ok = en_l90 >= floor and ratio >= RATIO_BAR
    doc = {
        "bench": "shard_bench",
        "n": N,
        "slice_elements": None,  # filled below for the record
        "repeats": REPEATS,
        "warm_s": WARM_S,
        "measure_s": MEASURE_S,
        "python_gbps": res["python"],
        "engine_gbps": res["engine"],
        "python_mean": py_mean,
        "engine_mean": en_mean,
        "engine_lower90": en_l90,
        "ratio": ratio,
        "ratio_bar": RATIO_BAR,
        "prior_floor": floor,
        "prior_floor_source": floor_src,
        "floor_locked": new_floor,
        "pass": bool(ok),
        "note": (
            "GB/s equiv = applied FWD frames x slice f32 bytes / wall; "
            "box loopback noise is 5-10%, lower-90 discipline per the "
            "obs/serve gates"
        ),
    }
    from shared_tensor_tpu.shard.map import ShardMap

    elo, ehi = ShardMap(SPEC.total // 32, 2).element_range(1)
    doc["slice_elements"] = ehi - elo
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(
        f"shard_bench: python {py_mean:.3f} / engine {en_mean:.3f} GB/s "
        f"equiv (lower90 {en_l90:.3f}, floor {floor:.3f}) ratio "
        f"{ratio:.1f}x (bar {RATIO_BAR}x) -> "
        f"{'PASS' if ok else 'FAIL'}",
        file=sys.stderr,
    )
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
