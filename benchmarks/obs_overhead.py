"""Obs-overhead gate (r08 satellite, r09 trace arm): telemetry must cost
<2% on the hot path — INCLUDING r09's per-message trace stamping.

Measures the r07 zero-copy engine loopback (the BENCH_r07 hot path) with
the obs subsystem ON vs OFF. Four arms:

- **engine arm (gate)** — ONE warm loopback pair built with the v1 (r08,
  untraced) wire framing, master streaming adds, with ``obs.set_enabled``
  flipped every interval: K paired (on, off) throughput samples over the
  same sockets/threads/caches, so slow drift cancels and only
  per-interval scheduler noise remains (measured ~4% per pair on this box
  — loopback throughput across FRESH pairs varies 5-10%, documented in
  MEMORY/BASELINE, hopeless for a 2% resolution). The per-pair overheads
  o_i = 1 - on_i/off_i aggregate to mean +/- stderr, and the gate FAILS
  only when the mean's lower 90% confidence bound exceeds the 2% budget.
- **trace arm (gate, r09)** — the SAME paired within-run design on a pair
  built with trace stamping enabled (v2 framing): the native engine keys
  its per-message trace bookkeeping (clock reads, hops/staleness atomics,
  trace_apply ring events) off the same ``st_obs_set_enabled`` flag, so
  each (on, off) pair isolates exactly the toggleable r08+r09 telemetry
  cost on a traced data plane. Same lower-90% discipline, same budget —
  the fresh-pair 5-10% noise never reaches the verdict because no
  cross-pair comparison is made.
- **health arm (gate, r18)** — the same paired design on a traced pair
  with fast digest beats (0.25 s) and the root-side fleet-health
  analyzer live (time-series ingest, heat/SLO scoring, clock beats,
  health.json writes). The runtime obs flag pauses the whole
  housekeeping beat, so each (on, off) pair isolates digest+health cost
  on top of the r08+r09 telemetry. Same lower-90% discipline and budget.
- **python arm (informational)** — fresh pairs per arm on the fallback
  tier at 4 Ki, where the per-message histograms observe live.

Toggle scope caveat (recorded in the artifact): ``set_enabled`` flips the
native ring emission, the r09 trace bookkeeping and every Python-side
call site, but not the ~50 ns of unconditional per-message engine work
(one CLOCK_MONOTONIC read at ledger push + two atomic adds at ACK pop)
nor the 13 wire bytes of a v2 header (~0.0003% of a 1 Mi message) —
bounded by inspection at <0.01% of the ~1 ms/message hot path at 1 Mi.

Emits one JSON document and writes it to argv[1] (default OBS_r09.json).
Run:  JAX_PLATFORMS=cpu python benchmarks/obs_overhead.py OBS_r09.json
"""

import json
import math
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N = int(os.environ.get("ST_OBS_BENCH_N", str(1 << 20)))
PAIRS = int(os.environ.get("ST_OBS_BENCH_PAIRS", "8"))
INTERVAL_S = float(os.environ.get("ST_OBS_BENCH_INTERVAL_S", "2.5"))
GATE_PCT = float(os.environ.get("ST_OBS_GATE_PCT", "2"))
PY_N = int(os.environ.get("ST_OBS_BENCH_PY_N", "4096"))
PY_S = float(os.environ.get("ST_OBS_BENCH_PY_S", "4"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _loopback_pair(n: int, engine: bool, trace: bool = True,
                   health: bool = False):
    import jax.numpy as jnp
    import numpy as np

    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import Config, ObsConfig, TransportConfig

    obs_kw = {}
    if health:
        # r18 health arm: fast digest beats + the root-side analyzer
        # (time-series ingest, heat/SLO scoring, health.json writes) so
        # the paired A/B isolates the full fleet-health housekeeping cost
        obs_kw = dict(
            digest_interval_sec=0.25,
            health_json_path=os.path.join(
                os.environ.get("TMPDIR", "/tmp"),
                f"st_obs_bench_health_{os.getpid()}.json",
            ),
        )
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=30.0),
        native_engine=engine,
        obs=ObsConfig(trace_wire=trace, **obs_kw),
    )
    port = _free_port()
    seed = jnp.zeros((n,), jnp.float32)
    m = create_or_fetch("127.0.0.1", port, seed, cfg)
    c = create_or_fetch("127.0.0.1", port, seed, cfg)
    stop = threading.Event()
    delta = jnp.asarray(
        np.random.default_rng(0).standard_normal(n).astype(np.float32)
    )
    period = max(0.002, n / (1 << 20) * 0.005)

    def adder():
        while not stop.is_set():
            m.add(delta)
            stop.wait(period)

    t = threading.Thread(target=adder, daemon=True)
    t.start()

    def fps(seconds: float) -> float:
        f0 = c.metrics(canonical=True)["st_frames_in_total"]
        t0 = time.monotonic()
        time.sleep(seconds)
        f1 = c.metrics(canonical=True)["st_frames_in_total"]
        return (f1 - f0) / max(time.monotonic() - t0, 1e-9)

    def close():
        stop.set()
        t.join(timeout=10.0)
        m.close()
        c.close()

    return fps, close


def engine_arm(trace: bool = False, health: bool = False) -> dict:
    """Paired within-run A/B: alternate the obs flag on one warm pair.
    ``trace=True`` builds the pair on the v2 (traced) framing — the obs
    flag then also gates the engine's per-message trace bookkeeping, so
    the pairs measure the full r08+r09 toggleable cost. ``health=True``
    (r18) additionally runs fast digest beats with the root-side health
    analyzer live; the runtime obs flag pauses the whole housekeeping
    beat, so each pair isolates digest+health+clock cost too."""
    from shared_tensor_tpu import obs

    fps, close = _loopback_pair(N, engine=True, trace=trace, health=health)
    on, off = [], []
    try:
        time.sleep(2.0)  # warmup: links hot, pools warm, codec threads up
        for _ in range(PAIRS):
            obs.set_enabled(True)
            on.append(fps(INTERVAL_S))
            obs.set_enabled(False)
            off.append(fps(INTERVAL_S))
    finally:
        close()
        obs.set_enabled(True)  # never leave the process half-disabled
    overheads = [100.0 * (1.0 - a / b) for a, b in zip(on, off) if b > 0]
    k = len(overheads)
    dropped_pairs = len(on) - k
    if k == 0:
        # every off-arm sample was zero: the loopback wedged — fail with a
        # diagnosable artifact instead of a ZeroDivision traceback
        return {
            "n": N, "pairs": PAIRS, "interval_s": INTERVAL_S,
            "trace_wire": trace, "health": health,
            "fps_obs_on": on, "fps_obs_off": off,
            "error": "all obs-off samples were 0 (loopback wedged)",
            "overhead_pct_mean": None, "overhead_pct_sem": None,
            "overhead_pct_lower90": None, "pass": False,
        }
    mean = sum(overheads) / k
    var = sum((o - mean) ** 2 for o in overheads) / max(k - 1, 1)
    sem = math.sqrt(var / k)
    lower90 = mean - 1.645 * sem
    return {
        "dropped_pairs": dropped_pairs,
        "n": N,
        "pairs": PAIRS,
        "interval_s": INTERVAL_S,
        "trace_wire": trace,
        "health": health,
        "fps_obs_on": on,
        "fps_obs_off": off,
        "overhead_pct_pairs": [round(o, 3) for o in overheads],
        "overhead_pct_mean": round(mean, 3),
        "overhead_pct_sem": round(sem, 3),
        "overhead_pct_lower90": round(lower90, 3),
        # fail only when the data supports "a real drop beyond the budget"
        "pass": bool(lower90 <= GATE_PCT),
    }


def python_arm() -> dict:
    """Fresh-pair A/B on the Python fallback tier (informational)."""
    from shared_tensor_tpu import obs

    out = {}
    try:
        for key, enabled in (("fps_obs_on", True), ("fps_obs_off", False)):
            obs.set_enabled(enabled)
            fps, close = _loopback_pair(PY_N, engine=False)
            try:
                time.sleep(1.0)
                out[key] = fps(PY_S)
            finally:
                close()
    finally:
        obs.set_enabled(True)
    out["n"] = PY_N
    out["overhead_pct"] = round(
        100.0 * (1.0 - out["fps_obs_on"] / max(out["fps_obs_off"], 1e-9)), 3
    )
    return out


def main() -> int:
    art_path = sys.argv[1] if len(sys.argv) > 1 else "OBS_r09.json"
    import jax

    jax.config.update("jax_platforms", "cpu")

    eng = engine_arm(trace=False)
    trc = engine_arm(trace=True)
    hlt = engine_arm(trace=True, health=True)
    py = python_arm()
    out = {
        "bench": "obs_overhead",
        "gate_pct": GATE_PCT,
        "gate_rule": (
            "fail iff lower-90%-confidence overhead > gate_pct on ANY "
            "paired arm (untraced engine_arm, traced trace_arm, r18 "
            "health_arm with digest+analyzer beats live); paired "
            "within-run A/B — the 5-10% fresh-pair loopback noise on this "
            "box never reaches the verdict. See the module docstring for "
            "the toggle scope."
        ),
        "engine_arm": eng,
        "trace_arm": trc,
        "health_arm": hlt,
        "python_arm_informational": py,
        "pass": bool(eng["pass"] and trc["pass"] and hlt["pass"]),
    }
    doc = json.dumps(out, indent=2)
    print(doc)
    if not os.path.isabs(art_path):
        art_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            art_path,
        )
    with open(art_path, "w") as f:
        f.write(doc + "\n")
    for label, arm in (
        ("obs gate", eng), ("trace gate", trc), ("health gate", hlt)
    ):
        if arm["overhead_pct_mean"] is None:
            print(f"{label}: FAIL ({arm.get('error')})", file=sys.stderr)
        else:
            print(
                f"{label}: {arm['overhead_pct_mean']:+.2f}% +/- "
                f"{arm['overhead_pct_sem']:.2f}% hot-path overhead "
                f"(lower90 {arm['overhead_pct_lower90']:+.2f}%) vs "
                f"{GATE_PCT}% budget -> "
                f"{'PASS' if arm['pass'] else 'FAIL'}",
                file=sys.stderr,
            )
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
