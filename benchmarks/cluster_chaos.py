"""7-node cluster observability chaos run (r09 acceptance artifact).

Builds a 7-node loopback tree (binary fan-out, native-engine tier), puts a
deterministic ST_FAULT_PLAN drop schedule under ONE node's C sender, and
streams multi-origin updates (root + the chaotic deep leaf) through the
chaos. After exact reconvergence and a full drain, it asserts the r09
acceptance bar:

- **trace-path contiguity**: >= 99% of delivered update generations
  reconstruct a contiguous hop path from the trace_apply records (a node
  only re-stamps hop k+1 after applying hop k, so a gap means lost
  telemetry — ring overflow, which the artifact also reports);
- **digest exactness**: after bottom-up digest pushes at the quiesced
  instant, the root's cluster totals equal the SUM of the 7 per-node
  registries EXACTLY for every quiesce-stable counter;
- chaos actually fired (injected drops >= 1) and was repaired
  (retransmits >= 1, exact convergence).

Also exports the run's merged timeline as a Perfetto-loadable Chrome
trace (the committed TRACE artifact rides profile_trace.py instead; this
one is optional via ST_CLUSTER_TRACE_OUT).

r10 ``--subscribers N`` arm: N read-only serve-tier leaves graft DIRECTLY
under the chaotic node (whose drop schedule then covers their unledgered
links too — ``only_link=0``). The serving contract under chaos: reads
either verify their ``max_staleness`` bound or raise (never silently
stale), a swallowed delta is a seq gap repaired by resync, and the WRITER
tree is never wedged by any of it (exact convergence + full drain with the
subscribers attached). Emits the subscriber tallies alongside the r09
telemetry checks.

r12 ``--kill-restore`` arm (the cluster-lifecycle acceptance artifact):
mid-soak — updates still in flight under the chaotic node's 25% drop
schedule — the root takes a consistent-cut snapshot (the barrier
completes THROUGH the chaos: markers/acks ride the control plane, which
the r06 rule keeps outside every chaos class), then the WHOLE tree is
killed, restarted from its shards (one node deliberately restarted with
v1 wire emission — the version-skew chaos arm: old and new nodes must
interop mid-upgrade), soaked further under the same chaos, and compared
against an UNINTERRUPTED arm that applies the identical add schedule.
Gates: the restored tree re-converges to the pre-kill mass inside
ST_RESTORE_BUDGET_S (default 45 s), both arms' final replicas agree
within the chaos-proportional bound (drop chaos + go-back-N converge
exactly, so the bound is float-accumulation slack), the snapshot barrier
itself stays sub-budget, chaos fired and was repaired in the restored
tree, and the version skew was real (mixed st_wire_version mid-restart).
Writes CHAOS_r12.json; wired into suite_load.sh as the lifecycle gate.

r11 ``--stripes N`` arm: every link in the tree runs striped over N
sockets, and the chaotic node's plan SEVERS ONE STRIPE SOCKET of its
uplink mid-stream (``only_stripe`` + ``sever_after_frames`` on top of the
drop schedule) — the striping contract under chaos: the link must degrade
to the surviving stripes (stripe_stats deaths >= 1 with the link still
converging) or, if reassembly wedged on a swallowed stripe seq, take the
clean go-back-N black-hole teardown into carry/re-graft — either way the
tree reaches the exact total; a wedged link shows up as a convergence
timeout and fails the run. Stripe telemetry (deaths, reroutes, live vs
negotiated counts) is tallied in the artifact.

r14 ``--shm`` arm (implies kill-restore): the 7-node tree runs with every
writer link's data plane on same-host SHARED-MEMORY rings (the r14 lane —
the normal state of a loopback cluster), under the same 25% drop schedule
and whole-tree kill-restore. On top of the r12 gates it asserts the lanes
were actually LIVE (st_shm_active == 2 at both ends of every writer link,
real ring traffic) before the kill AND after the restart's from-scratch
re-negotiation, and that the root's in-band digest is EXACT at the
post-restore quiesce — the lane sits below the wire-seq layer, so no
counter the digest aggregates may drift because of it.

Emits one JSON document and writes it to argv[1] (default CHAOS_r09.json).
Run:  JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py CHAOS_r09.json
      JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py CHAOS_r10.json \
          --subscribers 2
      JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py CHAOS_r11.json \
          --stripes 4
      JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py CHAOS_r14.json \
          --shm
Knobs: ST_CLUSTER_NODES (default 7), ST_CLUSTER_N (2048),
ST_CLUSTER_ADDS (40), ST_CLUSTER_SEED (9), ST_CLUSTER_SUBSCRIBERS (0),
ST_CLUSTER_STRIPES (1), ST_CLUSTER_SHM (0).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NODES = int(os.environ.get("ST_CLUSTER_NODES", "7"))
N = int(os.environ.get("ST_CLUSTER_N", "2048"))
ADDS = int(os.environ.get("ST_CLUSTER_ADDS", "40"))
SEED = int(os.environ.get("ST_CLUSTER_SEED", "9"))
SUBS = int(os.environ.get("ST_CLUSTER_SUBSCRIBERS", "0"))
if "--subscribers" in sys.argv:
    i = sys.argv.index("--subscribers")
    SUBS = int(sys.argv[i + 1])
    del sys.argv[i : i + 2]
STRIPES = int(os.environ.get("ST_CLUSTER_STRIPES", "1"))
if "--stripes" in sys.argv:
    i = sys.argv.index("--stripes")
    STRIPES = int(sys.argv[i + 1])
    del sys.argv[i : i + 2]
KILL_RESTORE = os.environ.get("ST_CLUSTER_KILL_RESTORE", "0") == "1"
if "--kill-restore" in sys.argv:
    KILL_RESTORE = True
    sys.argv.remove("--kill-restore")
# r14 ``--shm`` arm: the kill-restore chaos run additionally ASSERTS the
# same-host shm lanes are live across the whole tree (every writer link's
# data plane on rings, real shm message traffic), and that the root's
# in-band digest is EXACT at the post-restore quiesce — the lane must be
# invisible to every counter the digest aggregates. Implies kill-restore.
SHM_ARM = os.environ.get("ST_CLUSTER_SHM", "0") == "1"
if "--shm" in sys.argv:
    SHM_ARM = True
    sys.argv.remove("--shm")
if SHM_ARM:
    KILL_RESTORE = True
# r16 ``--sharded`` arm: the 7-node tree runs the CLUSTER-SHARDED tensor
# (shared_tensor_tpu/shard — one shard per node, owner-routed FWD frames
# instead of the flood) under the same 25% drop schedule, kill-restore
# included via the sharded checkpoint path. The acceptance bar it gates:
# a model >= ST_SHARD_FACTOR x bigger than any single node's allowance
# converges EXACTLY under the chaos (the per-node alloc bound is
# enforced at every sample throughout the soak), and per-node
# steady-state resident memory is ~1/N of the full-replica arm's
# (structurally: a full replica is the whole table per node).
SHARDED_ARM = os.environ.get("ST_CLUSTER_SHARDED", "0") == "1"
if "--sharded" in sys.argv:
    SHARDED_ARM = True
    sys.argv.remove("--sharded")
#: Sharded-arm table size (elements) and the memory factor: the model is
#: FACTOR x bigger than the per-node alloc allowance (the ISSUE's N >= 2).
SHARD_N = int(os.environ.get("ST_SHARD_N", "16384"))
SHARD_FACTOR = int(os.environ.get("ST_SHARD_FACTOR", "2"))
#: Wall-clock budget for the full-cluster restore: first restarted create
#: to every node re-converged on the pre-kill mass.
RESTORE_BUDGET_S = float(os.environ.get("ST_RESTORE_BUDGET_S", "45"))
#: Snapshot-barrier budget (marker flood + drain-to-quiesce + shard I/O).
SNAP_BUDGET_S = float(os.environ.get("ST_SNAP_BUDGET_S", "30"))
# frames the chaotic node's targeted stripe carries before its sever fires
# (one constant: both the injected FaultConfig and the artifact cite it)
SEVER_AFTER = 4
#: Staleness bound subscriber reads must verify (or raise) under chaos.
SUB_BOUND = float(os.environ.get("ST_CLUSTER_SUB_BOUND", "0.75"))

STABLE_COUNTERS = (
    "st_frames_out_total", "st_frames_in_total", "st_updates_total",
    "st_msgs_out_total", "st_msgs_in_total",
    "st_retransmit_msgs_total", "st_dedup_discards_total",
    "st_traced_msgs_in_total",
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _conformance(hub) -> dict:
    """r15 trace-conformance gate: drain the native ring one last time
    and replay the run's merged timeline through the protocol specs'
    trace acceptors (tools/protospec). The explorer proves the model;
    this proves the live run still matches the model — a violation here
    fails the chaos arm exactly like a convergence failure would.
    ST_CLUSTER_TIMELINE_OUT additionally pins the raw timeline to a
    file (the committed conformance regression fixtures)."""
    sys.path.insert(
        0,
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools",
        ),
    )
    from protospec.conformance import check_timeline

    hub.poll_native()
    timeline_out = os.environ.get("ST_CLUSTER_TIMELINE_OUT", "")
    if timeline_out:
        hub.export_timeline(timeline_out)
    report = check_timeline(hub.recorder.timeline())
    if timeline_out:
        report["timeline_out"] = timeline_out
    return report


def run_kill_restore(art_path: str) -> int:
    """The r12 lifecycle acceptance arm (module docstring)."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from shared_tensor_tpu import obs
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import (
        Config, FaultConfig, LifecycleConfig, ObsConfig, TransportConfig,
    )

    # full-run event capture for the r15 trace-conformance gate (the
    # default postmortem window would roll early barrier events out and
    # fake pause/resume imbalances)
    hub = obs.hub()
    hub.poll_native()
    hub.recorder.clear()
    hub.recorder.set_capacity(500_000)

    chaos_idx = NODES - 1
    skew_idx = 1  # restarted with v1 emission (the version-skew arm)
    seed = jnp.zeros((N,), jnp.float32)
    env = faults.to_env(
        FaultConfig(enabled=True, seed=SEED, drop_pct=0.25, only_link=1)
    )
    rng = np.random.default_rng(SEED)
    # ONE add schedule, shared by both arms: phase 1 (pre-snapshot) and
    # phase 2 (post-restore). The uninterrupted arm applies the identical
    # deltas, so "same mass as an uninterrupted run" is a pairwise replica
    # comparison, not just totals.
    p1 = [rng.uniform(-0.5, 0.5, N).astype(np.float32) for _ in range(ADDS)]
    p2 = [
        rng.uniform(-0.5, 0.5, N).astype(np.float32)
        for _ in range(max(4, ADDS // 2))
    ]
    total1 = np.sum(p1, axis=0, dtype=np.float64)
    total_all = total1 + np.sum(p2, axis=0, dtype=np.float64)

    def cfg(i: int, restore: str = "", skew: bool = False) -> Config:
        return Config(
            lifecycle=LifecycleConfig(
                node_name=f"n{i}", restore_path=restore,
            ),
            transport=TransportConfig(
                peer_timeout_sec=20.0, ack_timeout_sec=0.4
            ),
            obs=ObsConfig(digest_interval_sec=0.2, trace_wire=not skew),
        )

    def build(port, restore_dir=None, skew=False):
        peers = []
        for i in range(NODES):
            if i == chaos_idx:
                os.environ["ST_FAULT_PLAN"] = env["ST_FAULT_PLAN"]
            try:
                peers.append(
                    create_or_fetch(
                        "127.0.0.1", port, seed,
                        cfg(
                            i,
                            restore=(
                                os.path.join(restore_dir, f"shard_n{i}.npz")
                                if restore_dir
                                else ""
                            ),
                            skew=skew and i == skew_idx,
                        ),
                        timeout=60.0,
                    )
                )
            finally:
                os.environ.pop("ST_FAULT_PLAN", None)
        return peers

    def soak(peers, deltas, origin_a=0, origin_b=chaos_idx):
        for i, d in enumerate(deltas):
            peers[origin_a if i % 2 else origin_b].add(jnp.asarray(d))
            time.sleep(0.015)

    def converge(peers, total, budget):
        deadline = time.time() + budget
        while time.time() < deadline:
            if all(
                np.allclose(np.asarray(p.read()), total, atol=1e-3)
                for p in peers
            ):
                return True
            time.sleep(0.05)
        return False

    out = {
        "bench": "cluster_chaos_kill_restore",
        "nodes": NODES,
        "n": N,
        "adds": {"phase1": len(p1), "phase2": len(p2)},
        "seed": SEED,
        "chaos": {"drop_pct": 0.25, "node_index": chaos_idx},
        "skew_node": skew_idx,
        "budgets": {
            "restore_sec": RESTORE_BUDGET_S, "snapshot_sec": SNAP_BUDGET_S,
        },
    }
    def shm_tally(peers):
        """(links_live, msgs, fallbacks) across the tree — a link counts
        once per endpoint whose data plane is on the rings (state 2)."""
        live, msgs = 0, 0
        for p in peers:
            m = p.metrics(canonical=True)
            live += sum(
                1 for k, v in m.items()
                if k.startswith("st_shm_active") and v == 2
            )
            msgs += int(m.get("st_shm_msgs_out_total", 0))
        return live, msgs

    snapdir = tempfile.mkdtemp(prefix="st_snap_r12_")
    # ---- kill-restore arm -------------------------------------------------
    peers = build(_free_port())
    try:
        out["engine_tier"] = all(p._engine is not None for p in peers)
        soak(peers, p1)
        if SHM_ARM:
            live, msgs = shm_tally(peers)
            out["shm"] = {"pre_kill_lanes_live": live, "pre_kill_msgs": msgs}
        # snapshot MID-SOAK: in-flight residual mass under active drop
        # chaos — the barrier must drain and capture through it
        t0 = time.monotonic()
        res = peers[0].snapshot_cluster(snapdir, timeout=SNAP_BUDGET_S)
        snap_dur = time.monotonic() - t0
        out["snapshot"] = {
            "ok": res["ok"], "nodes": res["nodes"],
            "duration_sec": snap_dur,
        }
    finally:
        for p in peers:
            p.close()  # the whole-cluster kill
    t0 = time.monotonic()
    peers = build(_free_port(), restore_dir=snapdir, skew=True)
    try:
        restored = converge(peers, total1, RESTORE_BUDGET_S)
        restore_dur = time.monotonic() - t0
        out["restore"] = {
            "reconverged_pre_kill_mass": restored,
            "duration_sec": restore_dur,
        }
        # version skew is live mid-restart: one v1 emitter among v2 peers
        versions = sorted({p._wire_version for p in peers})
        out["restore"]["wire_versions"] = versions
        soak(peers, p2)
        kr_converged = converge(peers, total_all, 120.0)
        kr_final = np.asarray(peers[0].read(), np.float64)
        drained = all(p.drain(timeout=30.0, tol=1e-30) for p in peers)
        snaps = [p.metrics(canonical=True) for p in peers]
        retx = sum(int(s.get("st_retransmit_msgs_total", 0)) for s in snaps)
        out["restored_arm"] = {
            "converged": kr_converged,
            "drained": drained,
            "retransmits": retx,
            "restore_total": sum(
                int(s.get("st_restore_total", 0)) for s in snaps
            ),
        }
        if SHM_ARM:
            # the RESTARTED tree re-negotiated its lanes from scratch, and
            # the root's in-band digest must be EXACT at this quiesced
            # instant — the lane is below the wire-seq layer, so no
            # counter the digest aggregates may drift because of it
            live, msgs = shm_tally(peers)
            out["shm"]["restored_lanes_live"] = live
            out["shm"]["restored_msgs"] = msgs
            for _ in range(4):
                for p in peers:
                    if p._uplink is not None:
                        p.push_digest()
                time.sleep(0.4)
            cluster = peers[0].metrics(cluster=True)
            snaps = [p.metrics(canonical=True) for p in peers]
            digest_exact = len(cluster["nodes"]) == NODES
            dig = {}
            for name in STABLE_COUNTERS:
                want = sum(s.get(name, 0) for s in snaps)
                got = cluster["counters"].get(name, 0)
                dig[name] = {"cluster": got, "sum_of_registries": want}
                digest_exact = digest_exact and got == want
            out["shm"]["digest_exact_at_quiesce"] = bool(digest_exact)
            out["shm"]["digest_counters"] = dig
    finally:
        for p in peers:
            p.close()
    # ---- uninterrupted arm (identical schedule, no kill) ------------------
    peers = build(_free_port())
    try:
        soak(peers, p1)
        soak(peers, p2)
        un_converged = converge(peers, total_all, 120.0)
        un_final = np.asarray(peers[0].read(), np.float64)
        out["uninterrupted_arm"] = {"converged": un_converged}
    finally:
        for p in peers:
            p.close()
    # ---- verdict ----------------------------------------------------------
    # drop chaos + go-back-N converge EXACTLY, so the arms' bound is float
    # accumulation slack, not a chaos allowance (chaos_soak's corrupt-class
    # bounds don't apply — no corrupt faults here)
    conf = _conformance(hub)
    out["conformance"] = conf
    dev = float(np.max(np.abs(kr_final - un_final)))
    out["arms_max_deviation"] = dev
    out["bound"] = 1e-3
    out["pass"] = bool(
        conf["pass"]
        # >= 1 ROUTED event: a timeline none of whose events reaches an
        # acceptor (e.g. after an event rename) verifies nothing
        and conf["routed_events"] >= 1
        and out["snapshot"]["ok"]
        and out["snapshot"]["duration_sec"] <= SNAP_BUDGET_S
        and out["restore"]["reconverged_pre_kill_mass"]
        and out["restore"]["duration_sec"] <= RESTORE_BUDGET_S
        and len(out["restore"]["wire_versions"]) == 2  # skew was real
        and out["restored_arm"]["converged"]
        and out["restored_arm"]["drained"]
        and out["restored_arm"]["retransmits"] >= 1  # chaos repaired
        and out["uninterrupted_arm"]["converged"]
        and dev <= out["bound"]
    )
    if SHM_ARM:
        # every writer link's data plane on rings at BOTH ends (2 per
        # link), before the kill and again after the restart's fresh
        # negotiation; real lane traffic; digest exact at quiesce
        want_lanes = 2 * (NODES - 1)
        out["shm"]["want_lanes"] = want_lanes
        out["pass"] = bool(
            out["pass"]
            and out["shm"]["pre_kill_lanes_live"] >= want_lanes
            and out["shm"]["restored_lanes_live"] >= want_lanes
            and out["shm"]["pre_kill_msgs"] >= 1
            and out["shm"]["restored_msgs"] >= 1
            and out["shm"]["digest_exact_at_quiesce"]
        )
        out["bench"] = "cluster_chaos_kill_restore_shm"
    doc = json.dumps(out, indent=2)
    print(doc)
    if not os.path.isabs(art_path):
        art_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            art_path,
        )
    with open(art_path, "w") as f:
        f.write(doc + "\n")
    print(
        f"cluster_chaos --kill-restore: snapshot "
        f"{out['snapshot']['duration_sec']:.2f}s, restore "
        f"{out['restore']['duration_sec']:.2f}s, arms max dev {dev:.2e}, "
        f"conformance {conf['events']} events/"
        f"{len(conf['violations'])} violations -> "
        f"{'PASS' if out['pass'] else 'FAIL'}",
        file=sys.stderr,
    )
    return 0 if out["pass"] else 1


def run_sharded(art_path: str) -> int:
    """The r16 cluster-sharded acceptance arm (module docstring): 7-node
    sharded tree, 25% drop chaos on the deep node's uplink (the native
    injector's is_data set covers wire.FWD), whole-tree kill-restore
    through the sharded checkpoint path, a per-node alloc bound enforced
    at every soak sample, and the steady-state memory ratio against the
    full-replica baseline recorded."""
    import tempfile

    import numpy as np

    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.config import (
        Config, FaultConfig, LifecycleConfig, ShardConfig, TransportConfig,
    )
    from shared_tensor_tpu.ops.table import make_spec
    from shared_tensor_tpu.shard import ShardGather, create_or_fetch_sharded
    from shared_tensor_tpu.utils import checkpoint as ckpt

    tmpl = {"t": np.zeros(SHARD_N, np.float32)}
    spec = make_spec(tmpl)
    full_bytes = spec.total * 4  # any full-replica node's model floor
    bound = full_bytes // SHARD_FACTOR  # per-node allowance (model is
    # SHARD_FACTOR x bigger than one node — the harness enforces this at
    # EVERY sample below, chaos included)
    chaos_idx = NODES - 1
    env = faults.to_env(
        FaultConfig(enabled=True, seed=SEED, drop_pct=0.25, only_link=1)
    )

    def cfg(i: int, restore: str = "") -> Config:
        return Config(
            shard=ShardConfig(
                n_shards=NODES, shard_index=i, restore_dir=restore
            ),
            lifecycle=LifecycleConfig(node_name=f"s{i}"),
            transport=TransportConfig(
                peer_timeout_sec=20.0, ack_timeout_sec=0.4
            ),
        )

    def build(port, restore_dir=""):
        handles = []
        for i in range(NODES):
            if i == chaos_idx:
                os.environ["ST_FAULT_PLAN"] = env["ST_FAULT_PLAN"]
            try:
                handles.append(
                    create_or_fetch_sharded(
                        "127.0.0.1", port, tmpl, cfg(i, restore_dir),
                        timeout=60.0,
                    )
                )
            finally:
                os.environ.pop("ST_FAULT_PLAN", None)
        return handles

    # SPARSE adds (embedding-style windows spanning ~one shard): the
    # whole point of the sharded tensor is that no single writer needs
    # the full table resident — a dense delta would itself be O(full)
    rng = np.random.default_rng(SEED)
    win = max(64, SHARD_N // NODES)

    def mk_deltas(count):
        out = []
        for _ in range(count):
            lo = int(rng.integers(0, SHARD_N - win))
            d = np.zeros(SHARD_N, np.float32)
            d[lo : lo + win] = rng.uniform(-0.5, 0.5, win).astype(np.float32)
            out.append(d)
        return out

    p1 = mk_deltas(ADDS)
    p2 = mk_deltas(max(4, ADDS // 2))
    total1 = np.sum(p1, axis=0, dtype=np.float64)
    total_all = total1 + np.sum(p2, axis=0, dtype=np.float64)

    alloc = {"violations": 0, "peak": 0, "samples": 0, "stalls": 0}
    # one shard slice's resident bytes — the admission unit below
    slice_bytes = (spec.total // NODES + 32) * 4

    def soak(handles, deltas):
        for i, d in enumerate(deltas):
            h = handles[0 if i % 2 else chaos_idx]
            # flow control: a writer ADMITS a new update only once its
            # resident state has room for another outbox slice — the
            # backpressure a training step's sync point provides. Without
            # it a producer outrunning the chaotic link's drain would
            # accumulate one outbox per remote shard and the "model
            # bigger than the node" bound would be unachievable by ANY
            # implementation that keeps error feedback per target shard.
            deadline = time.time() + 30.0
            while (
                # room for TWO slices: a window can straddle a shard
                # boundary and allocate two outboxes in one add
                h.node.alloc_bytes() > bound - 2 * slice_bytes
                and time.time() < deadline
            ):
                alloc["stalls"] += 1
                time.sleep(0.005)
            h.add({"t": d})
            for hh in handles:
                b = hh.node.alloc_bytes()
                alloc["samples"] += 1
                alloc["peak"] = max(alloc["peak"], b)
                if b > bound:
                    alloc["violations"] += 1
            time.sleep(0.015)

    def gathered(handles, total, budget, atol=1e-3):
        deadline = time.time() + budget
        while time.time() < deadline:
            if all(h.node.drained() for h in handles):
                with ShardGather(handles[0].node, tmpl) as g:
                    got = np.asarray(g.read_tree(max_staleness=60.0)["t"])
                if np.allclose(got, total, atol=atol):
                    return True, float(np.max(np.abs(got - total)))
            time.sleep(0.25)
        with ShardGather(handles[0].node, tmpl) as g:
            got = np.asarray(g.read_tree(max_staleness=60.0)["t"])
        return False, float(np.max(np.abs(got - total)))

    out = {
        "bench": "cluster_chaos_sharded",
        "nodes": NODES,
        "n_shards": NODES,
        "n": SHARD_N,
        "adds": {"phase1": len(p1), "phase2": len(p2)},
        "seed": SEED,
        "chaos": {"drop_pct": 0.25, "only_link": 1, "node_index": chaos_idx},
        "memory_model": {
            # the harness-enforced contract: the model is FACTOR x bigger
            # than any node's allowance, checked at every soak sample
            "full_replica_bytes_per_node": full_bytes,
            "per_node_alloc_bound": bound,
            "model_over_node_factor": SHARD_FACTOR,
        },
    }
    from shared_tensor_tpu import obs

    hub = obs.hub()
    hub.poll_native()
    hub.recorder.clear()
    hub.recorder.set_capacity(500_000)

    snapdir = tempfile.mkdtemp(prefix="st_snap_r16_")
    handles = build(_free_port())
    try:
        assert all(h.sharded for h in handles), "a join fell back"
        soak(handles, p1)
        ok1, dev1 = gathered(handles, total1, 120.0)
        out["pre_kill"] = {"converged": ok1, "max_dev": dev1}
        # steady state: outboxes drained AND FREED — resident is the
        # owned slice (+ empty maps); the 1/N memory claim is measured
        # here, not mid-soak. The gather's subscriber legs tear down
        # ASYNCHRONOUSLY (each owner drops the sub residual when its loop
        # processes the LINK_DOWN), so wait for the teardown to settle —
        # sampling immediately can catch owned slice + one lingering sub
        # residual and trip the 2/N gate with no real regression
        settle = time.time() + 5.0
        while time.time() < settle:
            steady = max(h.node.alloc_bytes() for h in handles)
            if steady <= full_bytes * 2.0 / NODES:
                break
            time.sleep(0.05)
        out["memory_model"]["steady_state_max_bytes"] = steady
        out["memory_model"]["steady_over_full_ratio"] = steady / full_bytes
        owned = sorted(
            (i, h.node.owned_shards()) for i, h in enumerate(handles)
        )
        out["ownership_pre_kill"] = {str(i): s for i, s in owned}
        entries = [
            e
            for e in (h.node.save_shards(snapdir) for h in handles)
            if e is not None
        ]
        ckpt.write_manifest(snapdir, "chaos-r16", entries)
        coverage = ckpt.verify_shard_coverage(snapdir, NODES)
        out["snapshot"] = {
            "nodes": len(entries), "coverage_problems": coverage,
        }
    finally:
        for h in handles:
            h.close()  # the whole-cluster kill
    t0 = time.monotonic()
    handles = build(_free_port(), restore_dir=snapdir)
    try:
        ok_r, dev_r = gathered(handles, total1, RESTORE_BUDGET_S)
        out["restore"] = {
            "reconverged_pre_kill_mass": ok_r,
            "max_dev": dev_r,
            "duration_sec": time.monotonic() - t0,
        }
        soak(handles, p2)
        ok2, dev2 = gathered(handles, total_all, 120.0)
        out["restored_arm"] = {"converged": ok2, "max_dev": dev2}
        owned = sorted(
            (i, h.node.owned_shards()) for i, h in enumerate(handles)
        )
        out["ownership_restored"] = {str(i): s for i, s in owned}
        snaps = [h.node.metrics() for h in handles]
        out["fwd"] = {
            k: int(sum(s.get(k, 0) for s in snaps))
            for k in (
                "st_shard_fwd_msgs_out_total",
                "st_shard_fwd_msgs_in_total",
                "st_shard_fwd_relayed_total",
                "st_shard_fwd_dedup_total",
                "st_shard_park_drops_total",
            )
        }
        hub.poll_native()
        counts = hub.recorder.counts
        out["injected"] = {"fault_drop": counts.get("fault_drop", 0)}
        out["alloc"] = dict(alloc)
    finally:
        for h in handles:
            h.close()
    conf = _conformance(hub)
    out["conformance"] = conf
    out["pass"] = bool(
        conf["pass"]
        and out["pre_kill"]["converged"]
        and out["snapshot"]["coverage_problems"] == []
        and out["snapshot"]["nodes"] == NODES
        and out["restore"]["reconverged_pre_kill_mass"]
        and out["restore"]["duration_sec"] <= RESTORE_BUDGET_S
        and out["restored_arm"]["converged"]
        # every node re-owned its pre-kill shards after the restore
        and out["ownership_restored"] == out["ownership_pre_kill"]
        # chaos actually fired (the injector's is_data set covers FWD)
        and out["injected"]["fault_drop"] >= 1
        and out["fwd"]["st_shard_fwd_msgs_out_total"] >= 1
        and out["fwd"]["st_shard_park_drops_total"] == 0  # no silent loss
        # the memory contract: bound held at EVERY sample (model
        # FACTOR x bigger than one node), steady state ~1/N of the
        # full-replica arm (2x slack for padding + dict overheads)
        and alloc["violations"] == 0
        and out["memory_model"]["steady_over_full_ratio"] <= 2.0 / NODES
    )
    doc = json.dumps(out, indent=2)
    print(doc)
    if not os.path.isabs(art_path):
        art_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            art_path,
        )
    with open(art_path, "w") as f:
        f.write(doc + "\n")
    print(
        f"cluster_chaos --sharded: steady/full "
        f"{out['memory_model']['steady_over_full_ratio']:.3f} "
        f"(bound {2.0 / NODES:.3f}), alloc violations "
        f"{alloc['violations']}/{alloc['samples']}, drops "
        f"{out['injected']['fault_drop']}, fwd dedup "
        f"{out['fwd']['st_shard_fwd_dedup_total']} -> "
        f"{'PASS' if out['pass'] else 'FAIL'}",
        file=sys.stderr,
    )
    return 0 if out["pass"] else 1


def main() -> int:
    art_path = sys.argv[1] if len(sys.argv) > 1 else "CHAOS_r09.json"
    if SHARDED_ARM:
        return run_sharded(
            sys.argv[1] if len(sys.argv) > 1 else "CHAOS_r16.json"
        )
    if KILL_RESTORE:
        return run_kill_restore(
            sys.argv[1] if len(sys.argv) > 1 else "CHAOS_r12.json"
        )
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from shared_tensor_tpu import obs
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import (
        Config, FaultConfig, ObsConfig, TransportConfig,
    )
    from shared_tensor_tpu.obs import trace_export

    hub = obs.hub()
    hub.poll_native()
    hub.recorder.clear()
    hub.recorder.set_capacity(500_000)

    cfg = Config(
        transport=TransportConfig(
            peer_timeout_sec=20.0, ack_timeout_sec=0.4,
            stripe_count=max(1, min(8, STRIPES)),
        ),
        obs=ObsConfig(digest_interval_sec=0.2),
    )
    port = _free_port()
    seed = jnp.zeros((N,), jnp.float32)
    chaos_idx = NODES - 1  # the deep leaf that also originates adds
    # with subscribers attached, the chaotic node's drop schedule covers
    # ALL its links (only_link=0) so the unledgered subscriber links face
    # the same 25% drops as its uplink; the r09-compatible run keeps the
    # original uplink-only schedule. The r11 striped arm additionally
    # SEVERS one stripe socket of the chaotic node's uplink mid-stream —
    # the per-stripe chaos the satellite task names.
    env = faults.to_env(
        FaultConfig(
            enabled=True, seed=SEED, drop_pct=0.25,
            only_link=0 if SUBS > 0 else 1,
            only_stripe=STRIPES - 1 if STRIPES > 1 else -1,
            sever_after_frames=SEVER_AFTER if STRIPES > 1 else 0,
        )
    )
    peers = []
    for i in range(NODES):
        if i == chaos_idx:
            os.environ["ST_FAULT_PLAN"] = env["ST_FAULT_PLAN"]
        try:
            peers.append(
                create_or_fetch("127.0.0.1", port, seed, cfg, timeout=60.0)
            )
        finally:
            os.environ.pop("ST_FAULT_PLAN", None)

    # r10 subscriber arm: read-only leaves grafted DIRECTLY under the
    # chaotic node, so every delta they receive crosses its drop schedule
    subs = []
    if SUBS > 0:
        from shared_tensor_tpu import serve

        chaos_port = peers[chaos_idx].node.listen_port
        for _ in range(SUBS):
            subs.append(
                serve.subscribe(
                    "127.0.0.1", chaos_port, seed, cfg, timeout=60.0
                )
            )

    out = {
        "bench": "cluster_chaos",
        "nodes": NODES,
        "n": N,
        "adds": ADDS,
        "seed": SEED,
        "engine_tier": all(p._engine is not None for p in peers),
        "chaos": {"drop_pct": 0.25, "only_link": 1, "node_index": chaos_idx},
    }
    if SUBS > 0:
        out["chaos"]["only_link"] = 0
        out["subscribers"] = {
            "count": SUBS, "max_staleness_sec": SUB_BOUND,
        }
    if STRIPES > 1:
        out["chaos"]["severed_stripe"] = STRIPES - 1
        out["chaos"]["sever_after_frames"] = SEVER_AFTER
        out["stripes"] = {"count": STRIPES}
    try:
        from shared_tensor_tpu.serve import StalenessError

        reads_ok = reads_refused = 0  # mid-chaos tallies (the adds loop)
        q_ok = q_refused = 0  # post-quiesce convergence-loop tallies
        total = np.zeros(N, np.float64)
        rng = np.random.default_rng(0)
        for i in range(ADDS):
            d = rng.uniform(-0.5, 0.5, N).astype(np.float32)
            peers[0 if i % 2 else chaos_idx].add(jnp.asarray(d))
            total += d
            # the serving contract, exercised mid-chaos: every read either
            # verifies its bound or raises — silent staleness is
            # structurally impossible, and this tallies which happened
            for s in subs:
                try:
                    s.read(max_staleness=SUB_BOUND)
                    reads_ok += 1
                except StalenessError:
                    reads_refused += 1
            time.sleep(0.015)

        deadline = time.time() + 120.0
        converged = [False] * NODES
        while time.time() < deadline and not all(converged):
            for i, p in enumerate(peers):
                if not converged[i]:
                    converged[i] = bool(
                        np.allclose(np.asarray(p.read()), total, atol=1e-4)
                    )
            time.sleep(0.05)
        drained = all(p.drain(timeout=30.0, tol=1e-30) for p in peers)

        # subscriber convergence: once the writers quiesce, every
        # subscriber's VERIFIED read must reach the same total (resyncs
        # repair whatever the chaos swallowed; FRESH marks — control
        # plane, outside the chaos classes — keep the bound verifiable
        # on the idle tree)
        sub_converged = [False] * len(subs)
        sub_deadline = time.time() + 90.0
        while time.time() < sub_deadline and not all(sub_converged):
            for i, s in enumerate(subs):
                if not sub_converged[i]:
                    try:
                        v = np.asarray(s.read(max_staleness=SUB_BOUND))
                        sub_converged[i] = bool(
                            np.allclose(v, total, atol=1e-3)
                        )
                        q_ok += 1
                    except StalenessError:
                        q_refused += 1
            time.sleep(0.05)

        hub.poll_native()
        timeline = hub.recorder.timeline()
        paths = trace_export.trace_paths(timeline)
        stats = trace_export.path_stats(paths)
        counts = hub.recorder.counts

        # quiesced-instant digest: push bottom-up rounds so every level's
        # exact totals reach the root regardless of the tree's shape
        for _ in range(4):
            for p in peers:
                if p._uplink is not None:
                    p.push_digest()
            time.sleep(0.4)
        cluster = peers[0].metrics(cluster=True)
        snaps = [p.metrics(canonical=True) for p in peers]
        digest = {"nodes_seen": len(cluster["nodes"]), "counters": {}}
        # writers must all be visible; subscriber digests ride the same
        # control plane but on their own beat, so their visibility is
        # recorded, not required, at the quiesce instant
        digest_exact = NODES <= len(cluster["nodes"]) <= NODES + len(subs)
        for name in STABLE_COUNTERS:
            want = sum(s.get(name, 0) for s in snaps)
            got = cluster["counters"].get(name, 0)
            digest["counters"][name] = {
                "cluster": got, "sum_of_registries": want,
            }
            digest_exact = digest_exact and got == want

        staleness = [
            v for s in snaps for k, v in s.items()
            if k.startswith("st_staleness_seconds")
        ]
        # r11 striped arm: the sever killed ONE socket of the chaotic
        # node's uplink. Acceptable outcomes, both of which the exact
        # convergence above already survived: (a) the link DEGRADED to
        # the survivors — some live link reports deaths >= 1 with
        # live < negotiated; (b) reassembly wedged on a stripe seq the
        # dead socket swallowed and go-back-N tore the LINK down into
        # carry/re-graft (stripe_down/link_down in the ring, the
        # re-grafted link reporting a full stripe set). A wedged link is
        # the one outcome that cannot reach this point (convergence
        # times out and fails the run first).
        if STRIPES > 1:
            per_link = []
            for i, p in enumerate(peers):
                for link in list(p.node.links or ()):
                    ss = p.node.stripe_stats(link)
                    if ss is not None and ss["stripes"] > 1:
                        per_link.append({"node": i, "link": link, **ss})
            deaths = sum(s["deaths"] for s in per_link)
            reroutes = sum(s["reroutes"] for s in per_link)
            degraded = [
                s for s in per_link if s["deaths"] >= 1
                and s["live"] == s["stripes"] - s["deaths"]
            ]
            stripe_down_events = counts.get("stripe_down", 0)
            teardowns = counts.get("blackhole_teardown", 0)
            out["stripes"].update(
                links_striped=len(per_link),
                deaths=deaths,
                reroutes=reroutes,
                degraded_links=len(degraded),
                stripe_down_events=stripe_down_events,
                gbn_teardowns=teardowns,
                outcome=(
                    "degraded-to-survivors" if degraded
                    else "gbn-teardown-regraft" if teardowns >= 1
                    else "none-observed"
                ),
            )
        if subs:
            sm = [s.metrics() for s in subs]
            out["subscribers"].update(
                converged_all=all(sub_converged),
                reads_ok_mid_chaos=reads_ok,
                reads_refused_mid_chaos=reads_refused,
                reads_ok_at_quiesce=q_ok,
                reads_refused_at_quiesce=q_refused,
                resyncs=sum(int(m["st_sub_resyncs_total"]) for m in sm),
                gap_discards=sum(
                    int(m["st_sub_gap_discards_total"]) for m in sm
                ),
                stale_reads_raised=sum(
                    int(m["st_read_stale_total"]) for m in sm
                ),
            )
        out.update(
            converged_all=all(converged),
            drained_all=drained,
            injected={
                "fault_drop": counts.get("fault_drop", 0),
                "retransmit": counts.get("retransmit", 0),
            },
            trace_paths=stats,
            trace_events=counts.get("trace_apply", 0),
            native_ring_dropped=int(
                next(iter(snaps), {}).get("st_obs_events_dropped_total", 0)
            ),
            staleness_seconds={
                "max": max(staleness, default=0.0),
                "observed_links": len(staleness),
            },
            digest=digest,
            digest_exact=digest_exact,
        )
        trace_out = os.environ.get("ST_CLUSTER_TRACE_OUT", "")
        if trace_out:
            trace_export.export_file(trace_out, timeline)
            out["trace_export"] = trace_out
        conf = _conformance(hub)
        out["conformance"] = conf
        out["pass"] = bool(
            conf["pass"]
            # >= 1 ROUTED event: a timeline none of whose events
            # reaches an acceptor (after an event rename, say)
            # verifies nothing
            and conf["routed_events"] >= 1
            and all(converged)
            and drained
            and out["injected"]["fault_drop"] >= 1
            and out["injected"]["retransmit"] >= 1
            and stats["paths"] >= ADDS // 2
            and stats["contiguous_frac"] >= 0.99
            and digest_exact
            # r10 arm: the writer tree was never wedged (the criteria
            # above, evaluated WITH subscribers attached), every
            # subscriber's verified read reached the exact total, and at
            # least one read VERIFIED somewhere in the run (mid-chaos
            # reads may legitimately all refuse under heavy drops — the
            # artifact records both tallies separately)
            and (not subs or (all(sub_converged) and reads_ok + q_ok >= 1))
            # r11 striped arm: the injected stripe sever must actually
            # have fired AND resolved into one of the two clean outcomes
            # (degrade-to-survivors or go-back-N teardown) — never a
            # wedged link (which the convergence deadline above catches)
            and (
                STRIPES <= 1
                or out["stripes"]["outcome"] != "none-observed"
            )
        )
    finally:
        for s in subs:
            s.close()
        for p in peers:
            p.close()

    doc = json.dumps(out, indent=2)
    print(doc)
    if not os.path.isabs(art_path):
        art_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            art_path,
        )
    with open(art_path, "w") as f:
        f.write(doc + "\n")
    print(
        f"cluster_chaos: {out.get('trace_paths', {}).get('paths', 0)} paths, "
        f"contiguous {out.get('trace_paths', {}).get('contiguous_frac', 0):.3f}, "
        f"digest_exact={out.get('digest_exact')}, conformance "
        f"{len(out.get('conformance', {}).get('violations', []))} "
        f"violations -> "
        f"{'PASS' if out['pass'] else 'FAIL'}",
        file=sys.stderr,
    )
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
