"""7-node cluster observability chaos run (r09 acceptance artifact).

Builds a 7-node loopback tree (binary fan-out, native-engine tier), puts a
deterministic ST_FAULT_PLAN drop schedule under ONE node's C sender, and
streams multi-origin updates (root + the chaotic deep leaf) through the
chaos. After exact reconvergence and a full drain, it asserts the r09
acceptance bar:

- **trace-path contiguity**: >= 99% of delivered update generations
  reconstruct a contiguous hop path from the trace_apply records (a node
  only re-stamps hop k+1 after applying hop k, so a gap means lost
  telemetry — ring overflow, which the artifact also reports);
- **digest exactness**: after bottom-up digest pushes at the quiesced
  instant, the root's cluster totals equal the SUM of the 7 per-node
  registries EXACTLY for every quiesce-stable counter;
- chaos actually fired (injected drops >= 1) and was repaired
  (retransmits >= 1, exact convergence).

Also exports the run's merged timeline as a Perfetto-loadable Chrome
trace (the committed TRACE artifact rides profile_trace.py instead; this
one is optional via ST_CLUSTER_TRACE_OUT).

Emits one JSON document and writes it to argv[1] (default CHAOS_r09.json).
Run:  JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py CHAOS_r09.json
Knobs: ST_CLUSTER_NODES (default 7), ST_CLUSTER_N (2048),
ST_CLUSTER_ADDS (40), ST_CLUSTER_SEED (9).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

NODES = int(os.environ.get("ST_CLUSTER_NODES", "7"))
N = int(os.environ.get("ST_CLUSTER_N", "2048"))
ADDS = int(os.environ.get("ST_CLUSTER_ADDS", "40"))
SEED = int(os.environ.get("ST_CLUSTER_SEED", "9"))

STABLE_COUNTERS = (
    "st_frames_out_total", "st_frames_in_total", "st_updates_total",
    "st_msgs_out_total", "st_msgs_in_total",
    "st_retransmit_msgs_total", "st_dedup_discards_total",
    "st_traced_msgs_in_total",
)


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main() -> int:
    art_path = sys.argv[1] if len(sys.argv) > 1 else "CHAOS_r09.json"
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_platforms", "cpu")

    from shared_tensor_tpu import obs
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import (
        Config, FaultConfig, ObsConfig, TransportConfig,
    )
    from shared_tensor_tpu.obs import trace_export

    hub = obs.hub()
    hub.poll_native()
    hub.recorder.clear()
    hub.recorder.set_capacity(500_000)

    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=20.0, ack_timeout_sec=0.4),
        obs=ObsConfig(digest_interval_sec=0.2),
    )
    port = _free_port()
    seed = jnp.zeros((N,), jnp.float32)
    chaos_idx = NODES - 1  # the deep leaf that also originates adds
    env = faults.to_env(
        FaultConfig(enabled=True, seed=SEED, drop_pct=0.25, only_link=1)
    )
    peers = []
    for i in range(NODES):
        if i == chaos_idx:
            os.environ["ST_FAULT_PLAN"] = env["ST_FAULT_PLAN"]
        try:
            peers.append(
                create_or_fetch("127.0.0.1", port, seed, cfg, timeout=60.0)
            )
        finally:
            os.environ.pop("ST_FAULT_PLAN", None)

    out = {
        "bench": "cluster_chaos",
        "nodes": NODES,
        "n": N,
        "adds": ADDS,
        "seed": SEED,
        "engine_tier": all(p._engine is not None for p in peers),
        "chaos": {"drop_pct": 0.25, "only_link": 1, "node_index": chaos_idx},
    }
    try:
        total = np.zeros(N, np.float64)
        rng = np.random.default_rng(0)
        for i in range(ADDS):
            d = rng.uniform(-0.5, 0.5, N).astype(np.float32)
            peers[0 if i % 2 else chaos_idx].add(jnp.asarray(d))
            total += d
            time.sleep(0.015)

        deadline = time.time() + 120.0
        converged = [False] * NODES
        while time.time() < deadline and not all(converged):
            for i, p in enumerate(peers):
                if not converged[i]:
                    converged[i] = bool(
                        np.allclose(np.asarray(p.read()), total, atol=1e-4)
                    )
            time.sleep(0.05)
        drained = all(p.drain(timeout=30.0, tol=1e-30) for p in peers)

        hub.poll_native()
        timeline = hub.recorder.timeline()
        paths = trace_export.trace_paths(timeline)
        stats = trace_export.path_stats(paths)
        counts = hub.recorder.counts

        # quiesced-instant digest: push bottom-up rounds so every level's
        # exact totals reach the root regardless of the tree's shape
        for _ in range(4):
            for p in peers:
                if p._uplink is not None:
                    p.push_digest()
            time.sleep(0.4)
        cluster = peers[0].metrics(cluster=True)
        snaps = [p.metrics(canonical=True) for p in peers]
        digest = {"nodes_seen": len(cluster["nodes"]), "counters": {}}
        digest_exact = len(cluster["nodes"]) == NODES
        for name in STABLE_COUNTERS:
            want = sum(s.get(name, 0) for s in snaps)
            got = cluster["counters"].get(name, 0)
            digest["counters"][name] = {
                "cluster": got, "sum_of_registries": want,
            }
            digest_exact = digest_exact and got == want

        staleness = [
            v for s in snaps for k, v in s.items()
            if k.startswith("st_staleness_seconds")
        ]
        out.update(
            converged_all=all(converged),
            drained_all=drained,
            injected={
                "fault_drop": counts.get("fault_drop", 0),
                "retransmit": counts.get("retransmit", 0),
            },
            trace_paths=stats,
            trace_events=counts.get("trace_apply", 0),
            native_ring_dropped=int(
                next(iter(snaps), {}).get("st_obs_events_dropped_total", 0)
            ),
            staleness_seconds={
                "max": max(staleness, default=0.0),
                "observed_links": len(staleness),
            },
            digest=digest,
            digest_exact=digest_exact,
        )
        trace_out = os.environ.get("ST_CLUSTER_TRACE_OUT", "")
        if trace_out:
            trace_export.export_file(trace_out, timeline)
            out["trace_export"] = trace_out
        out["pass"] = bool(
            all(converged)
            and drained
            and out["injected"]["fault_drop"] >= 1
            and out["injected"]["retransmit"] >= 1
            and stats["paths"] >= ADDS // 2
            and stats["contiguous_frac"] >= 0.99
            and digest_exact
        )
    finally:
        for p in peers:
            p.close()

    doc = json.dumps(out, indent=2)
    print(doc)
    if not os.path.isabs(art_path):
        art_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            art_path,
        )
    with open(art_path, "w") as f:
        f.write(doc + "\n")
    print(
        f"cluster_chaos: {out.get('trace_paths', {}).get('paths', 0)} paths, "
        f"contiguous {out.get('trace_paths', {}).get('contiguous_frac', 0):.3f}, "
        f"digest_exact={out.get('digest_exact')} -> "
        f"{'PASS' if out['pass'] else 'FAIL'}",
        file=sys.stderr,
    )
    return 0 if out["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
