"""Adaptive-precision A/B: does the r11 governor actually help? (AB_r11)

The r11 acceptance bar for the telemetry->data-plane loop, measured
directly: under a chaos-soak-class workload (a peer training through a
lossy uplink — the go-back-N retransmission storm is exactly the
"link falling behind" signature the governor watches for), the ADAPTIVE
arm must reach a LOWER final ``st_residual_norm`` than fixed 1-bit at
EQUAL wall-clock. Same seed, same fault schedule, same add cadence; the
only difference is ``CodecConfig.adaptive_precision``.

Why this is the right yardstick: ``st_residual_norm`` is the owed mass —
the L2 of every error-feedback residual (carry included). A 1-bit frame
moves each element +/-s; a sign2 frame moves +/-s or +/-3s for 2x the
bytes. When a link genuinely falls behind (retransmissions eating the
frame budget while adds keep landing), the governor's upshift spends
bytes where residuals say it matters and the owed mass drains faster;
the probe-and-revert rule keeps the same upshift from taxing a link
that is merely saturated. Each adaptive run must also record >= 1
upshift, otherwise the comparison is vacuous (governor never engaged).

A third arm pins the MIXED-TREE interop claim as an artifact (the unit
version lives in tests/test_sign2.py): a sign2-pinned master floods one
capable child (sign2 frames on the wire: ``st_frames2_in_total > 0``)
and one force-disabled child (never advertises decode, so emission
toward it stays 1-bit: ``frames2_in == 0``) — both converge to the same
state through the same flood.

Emits one JSON line. Run: python benchmarks/adaptive_ab.py > AB_r11.json
Knobs: ST_AB_N (default 65536), ST_AB_SECONDS (chaos window per run,
default 12), ST_AB_REPEATS (A/B pairs, default 3; arms interleave so box
drift hits both equally), ST_AB_SEED.
"""

import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N = int(os.environ.get("ST_AB_N", "65536"))
SECONDS = float(os.environ.get("ST_AB_SECONDS", "12"))
REPEATS = int(os.environ.get("ST_AB_REPEATS", "3"))
SEED = int(os.environ.get("ST_AB_SEED", "11"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


#: Uplink byte budget (token bucket, TransportConfig). The cap is what
#: makes "falling behind" REAL on loopback: an uncapped localhost socket
#: absorbs a 25%-drop storm without the residual ever growing (the
#: governor correctly probes and reverts — measured, r11), so an honest
#: A/B needs a link whose byte budget the owed mass can actually exceed.
#: 1 MiB/s (~120 1-bit frames/s at 64 Ki) sits between the two codecs'
#: drain capacities for this add schedule: 1-bit genuinely cannot keep
#: up (residual grows without bound), sign2 can — the regime the
#: governor exists for.
CAP_BPS = int(os.environ.get("ST_AB_CAP_BPS", str(1 << 20)))


def _cfg(adaptive: bool, capped: bool = False):
    from shared_tensor_tpu.config import CodecConfig, Config, TransportConfig

    return Config(
        transport=TransportConfig(
            peer_timeout_sec=30.0,
            ack_timeout_sec=1.0,
            bandwidth_cap_bytes_per_sec=CAP_BPS if capped else 0,
        ),
        codec=CodecConfig(adaptive_precision=adaptive),
        native_engine=True,
    )


def _run_chaos_arm(adaptive: bool, rep: int, np, jnp) -> dict:
    """One A/B run: master + a joiner whose C-tier uplink drops 25% of its
    sends (ST_FAULT_PLAN, parsed per st_node_create like chaos_soak's
    native arm — only the joiner injects) AND lives under a byte budget
    (token bucket). The joiner trains gaussian deltas @5 ms for SECONDS
    — mass arrives faster than the lossy capped 1-bit link can move it,
    so the fixed arm's residual grows without bound while the adaptive
    arm upshifts and holds it at a bounded sawtooth (measured: ~900 and
    climbing vs ~100-300 at t=16 s). ``final_residual_norm`` is the
    TIME-MEAN over the window's second half (the sawtooth makes a
    single endpoint sample a coin flip; the equal-wall-clock comparison
    is between equilibrium statistics), ``endpoint_residual_norm`` the
    last sample."""
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch
    from shared_tensor_tpu.config import FaultConfig

    port = _free_port()
    master = create_or_fetch(
        "127.0.0.1", port, jnp.zeros((N,), jnp.float32), _cfg(adaptive)
    )
    env = faults.to_env(FaultConfig(
        enabled=True, seed=SEED + rep, drop_pct=0.25, only_link=1,
    ))
    os.environ.update(env)
    try:
        child = SharedTensorPeer(
            "127.0.0.1", port, jnp.zeros((N,), jnp.float32),
            _cfg(adaptive, capped=True),
        )
    finally:
        for k in env:
            os.environ.pop(k, None)
    child.wait_ready(60.0)

    rng = np.random.default_rng(SEED + 100 + rep)
    t0 = time.time()
    t_end = t0 + SECONDS
    adds = 0
    samples = []  # (t, residual_norm) every ~0.5 s
    t_next = t0 + 0.5
    while True:
        now = time.time()
        if now >= t_end:
            break
        child.add((rng.standard_normal(N) * 0.1).astype(np.float32))
        adds += 1
        if now >= t_next:
            t_next += 0.5
            samples.append((
                round(now - t0, 2),
                child.metrics()[
                    "st_residual_norm"
                ],
            ))
        time.sleep(0.005)
    cm = child.metrics()
    samples.append((round(time.time() - t0, 2), cm["st_residual_norm"]))
    half = [rn for (t, rn) in samples if t >= SECONDS / 2]
    run = {
        "final_residual_norm": sum(half) / len(half),
        "endpoint_residual_norm": round(samples[-1][1], 3),
        "peak_residual_norm": round(max(rn for _, rn in samples), 3),
        "adds": adds,
        "upshifts": cm.get("st_precision_upshifts_total", 0),
        "downshifts": cm.get("st_precision_downshifts_total", 0),
        "frames2_out": cm.get("st_frames2_out_total", 0),
        "retransmits": cm.get("st_retransmit_msgs_total", 0),
    }
    # sanity epilogue (not part of the measurement): detach chaos, drain,
    # the delivery contract must still hold on both arms
    for p in (child, master):
        p._faults = None
    run["drained"] = bool(child.drain(timeout=180.0, tol=1e-30))
    child.close()
    master.close()
    return run


def _run_mixed_arm(np, jnp) -> dict:
    """Pinned-sign2 master -> capable child A (sign2 on the wire) +
    force-disabled child B (1-bit only), one flood, same final state."""
    from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch

    port = _free_port()
    os.environ["ST_SIGN2"] = "2"
    try:
        master = create_or_fetch(
            "127.0.0.1", port, jnp.zeros((N,), jnp.float32), _cfg(True)
        )
        child_a = SharedTensorPeer(
            "127.0.0.1", port, jnp.zeros((N,), jnp.float32), _cfg(True)
        )
        child_b = SharedTensorPeer(
            "127.0.0.1", port, jnp.zeros((N,), jnp.float32), _cfg(False)
        )
    finally:
        os.environ.pop("ST_SIGN2", None)
    child_a.wait_ready(60.0)
    child_b.wait_ready(60.0)

    rng = np.random.default_rng(SEED + 777)
    total = np.zeros(N, np.float64)
    for _ in range(200):
        d = (rng.standard_normal(N) * 0.1).astype(np.float32)
        total += d
        master.add(d)
        time.sleep(0.002)
    ok_drain = all(
        p.drain(timeout=120.0, tol=1e-30) for p in (master, child_a, child_b)
    )
    ra = np.asarray(child_a.read()).astype(np.float64)
    rb = np.asarray(child_b.read()).astype(np.float64)
    rm = np.asarray(master.read()).astype(np.float64)
    ma = child_a.metrics()
    mb = child_b.metrics()
    out = {
        "drained": ok_drain,
        "frames2_in_capable": ma.get("st_frames2_in_total", 0),
        "frames2_in_disabled": mb.get("st_frames2_in_total", 0),
        "max_dev_capable": float(np.abs(ra - rm).max()),
        "max_dev_disabled": float(np.abs(rb - rm).max()),
    }
    out["pass"] = bool(
        ok_drain
        and out["frames2_in_capable"] > 0        # sign2 really on the wire
        and out["frames2_in_disabled"] == 0      # emission gated per link
        # f32 accumulation-order noise only (the documented ~1-ulp
        # fused-apply divergence, accumulated over 200 floods)
        and out["max_dev_capable"] < 1e-4
        and out["max_dev_disabled"] < 1e-4
    )
    for p in (child_a, child_b, master):
        p.close()
    return out


def main() -> None:
    import numpy as np
    import jax.numpy as jnp

    arms = {"adaptive": [], "fixed1": []}
    for rep in range(REPEATS):
        # interleaved A/B pairs: slow-box drift lands on both arms alike
        arms["adaptive"].append(_run_chaos_arm(True, rep, np, jnp))
        arms["fixed1"].append(_run_chaos_arm(False, rep, np, jnp))
    mean = {
        k: sum(r["final_residual_norm"] for r in v) / len(v)
        for k, v in arms.items()
    }
    governor_engaged = all(r["upshifts"] >= 1 for r in arms["adaptive"])
    governor_quiet = all(r["upshifts"] == 0 for r in arms["fixed1"])
    mixed = _run_mixed_arm(np, jnp)
    verdict = (
        mean["adaptive"] < mean["fixed1"]
        and governor_engaged
        and governor_quiet
        and all(r["drained"] for v in arms.values() for r in v)
        and mixed["pass"]
    )
    print(json.dumps({
        "bench": "adaptive_precision_ab",
        "tier": "host-native-engine",
        "n_elements": N,
        "seconds_per_run": SECONDS,
        "repeats": REPEATS,
        "cap_bytes_per_sec": CAP_BPS,
        "workload": "joiner trains N(0,0.1) deltas @5ms through a 25%-drop"
                    " C-tier uplink (ST_FAULT_PLAN) under a "
                    f"{CAP_BPS} B/s token bucket; final = time-mean"
                    " residual norm over the window's 2nd half, chaos"
                    " attached throughout, drain only as epilogue",
        "arms": arms,
        "mean_final_residual_norm": {k: round(v, 3) for k, v in mean.items()},
        "adaptive_over_fixed": round(
            mean["adaptive"] / mean["fixed1"], 4
        ) if mean["fixed1"] else None,
        "mixed_tree": mixed,
        "pass": bool(verdict),
    }))
    sys.exit(0 if verdict else 1)


if __name__ == "__main__":
    main()
