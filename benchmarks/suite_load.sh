#!/usr/bin/env bash
# Loaded-suite discipline (round-4 practice, re-adopted r06): run the tier-1
# suite N consecutive times back-to-back and demand EVERY run green — the
# rendezvous/teardown races this repo keeps fixing only show up when ports,
# threads and the box are still warm from the previous run. Appends one
# result line per run plus a PASS/FAIL footer; commit the transcript as
# SUITE_LOAD_rXX.txt.
#
# Usage:  bash benchmarks/suite_load.sh [runs] [outfile]
#   runs     consecutive full-suite runs (default 3)
#   outfile  transcript path (default /dev/stdout)
set -u
cd "$(dirname "$0")/.."
RUNS="${1:-3}"
OUT="${2:-/dev/stdout}"
FAILED=0
for i in $(seq 1 "$RUNS"); do
  START=$(date -u +%H:%M:%SZ)
  LOG=$(mktemp)
  JAX_PLATFORMS=cpu timeout -k 10 870 \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly >"$LOG" 2>&1
  RC=$?
  # this environment's pytest -q emits only the dot-progress bar (no
  # summary line), so the transcript keeps the bars + a dot count — the
  # same evidence format as SUITE_LOAD_r03/r04
  DOTS=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG")
  PASSED=$(printf '%s' "$DOTS" | tr -cd . | wc -c)
  echo "=== run $i/$RUNS  start=$START  rc=$RC  dots_passed=$PASSED ===" >>"$OUT"
  printf '%s\n' "$DOTS" >>"$OUT"
  [ "$RC" -ne 0 ] && FAILED=1 && grep -aE '^FAILED|^ERROR' "$LOG" | sort -u >>"$OUT"
  rm -f "$LOG"
done
if [ "$FAILED" -eq 0 ]; then
  echo "PASS: $RUNS/$RUNS consecutive loaded runs green" >>"$OUT"
else
  echo "FAIL: at least one run red (see above)" >>"$OUT"
fi
exit "$FAILED"
