#!/usr/bin/env bash
# Loaded-suite discipline (round-4 practice, re-adopted r06): run the tier-1
# suite N consecutive times back-to-back and demand EVERY run green — the
# rendezvous/teardown races this repo keeps fixing only show up when ports,
# threads and the box are still warm from the previous run. Appends one
# result line per run plus a PASS/FAIL footer; commit the transcript as
# SUITE_LOAD_rXX.txt.
#
# Usage:  bash benchmarks/suite_load.sh [runs] [outfile]
#   runs     consecutive full-suite runs (default 3)
#   outfile  transcript path (default /dev/stdout)
set -u
cd "$(dirname "$0")/.."
RUNS="${1:-3}"
OUT="${2:-/dev/stdout}"
FAILED=0

# Static gate umbrella (r13 lints + analyze, r15 adds the protocol
# model checker and folds all three under ST_SUITE_STATIC), FIRST so a
# red gate fails in seconds, not after three 10-minute suite runs:
#  - cross-tier lints (tools/): ABI/ctypes signatures + counter widths,
#    wire kinds incl. the r14 v3/SWITCH/sendmmsg rows, obs event codes,
#    metric-name schema coverage + dynamic-name ban, python-tier lock
#    discipline (lint_locks);
#  - clang -Wthread-safety -Werror + .clang-tidy over the native tier
#    (ST_SUITE_ANALYZE=0 skips; auto-skips when clang is absent — this
#    image ships gcc only, CI images with clang get the full gate);
#  - the protospec model checker (tools/protospec/run_check.py): every
#    protocol spec explored exhaustively + every historical-bug
#    mutation re-found, counts committed as the MODEL artifact
#    (ST_SUITE_MODEL_OUT, default MODEL_r19.json; ST_SUITE_MODEL=0
#    skips; ST_SUITE_MODEL_JOBS shards per-spec units, default
#    min(4, nproc), with per-spec "gate model/<spec>" timing lines).
# Per-gate wall-clock is logged ("gate <name>: <sec>s rc=<rc>") — the
# r13/r14 notes say gate time is starting to matter, so the transcript
# now carries the numbers to watch.
gate_run() {  # gate_run <name> <cmd...>: append timing + rc, set FAILED
  local name="$1"; shift
  local t0 t1 rc
  t0=$(date +%s.%N)
  "$@" >>"$OUT" 2>&1; rc=$?
  t1=$(date +%s.%N)
  echo "gate $name: $(echo "$t1 $t0" | awk '{printf "%.2f", $1-$2}')s rc=$rc" >>"$OUT"
  [ "$rc" -ne 0 ] && FAILED=1
  return $rc
}
if [ "${ST_SUITE_STATIC:-1}" = "1" ]; then
  echo "--- static gate (lint / analyze / model checker) ---" >>"$OUT"
  if [ "${ST_SUITE_LINT:-1}" = "1" ]; then
    for l in lint_abi lint_wire lint_events lint_metrics lint_locks \
             lint_spec; do
      gate_run "$l" python "tools/$l.py" --repo .
    done
    [ "$FAILED" -ne 0 ] && { echo "FAIL: lint gate red" >>"$OUT"; exit 1; }
  fi
  if [ "${ST_SUITE_ANALYZE:-1}" = "1" ]; then
    if command -v "${CLANG:-clang}" >/dev/null 2>&1; then
      gate_run analyze make -C native analyze
      if command -v "${CLANG_TIDY:-clang-tidy}" >/dev/null 2>&1; then
        gate_run tidy make -C native tidy
      fi
      [ "$FAILED" -ne 0 ] && { echo "FAIL: analyze gate red" >>"$OUT"; exit 1; }
    elif python tools/analyze_clang.py --probe >/dev/null 2>&1; then
      # hermetic-or-honest (r19, closing the r13 debt): no clang driver
      # binary, but the pip libclang wheel IS a full front-end and
      # -Wthread-safety is a front-end analysis — run the same gate
      # through tools/analyze_clang.py (same flags as `make -C native
      # analyze`, -DST_ANALYZE_NO_SIMD selects the scalar reference
      # paths gcc's intrinsics headers would otherwise break).
      gate_run analyze python tools/analyze_clang.py --repo .
      [ "$FAILED" -ne 0 ] && { echo "FAIL: analyze gate red" >>"$OUT"; exit 1; }
    else
      # honesty over silence (r14): this is a SKIPPED verification, not a
      # passed one — the thread-safety annotations are unchecked prose on
      # this image. Provision the hermetic front-end with:
      #     python -m pip install libclang
      # (or install a real clang driver) and re-run for the real gate.
      echo "--- analyze gate: SKIPPED-no-clang (neither a clang driver" \
           "nor the libclang front-end is available — thread-safety" \
           "annotations are unverified on this image; provision with:" \
           "python -m pip install libclang) ---" >>"$OUT"
    fi
  fi
  if [ "${ST_SUITE_MODEL:-1}" = "1" ]; then
    MODEL_OUT="${ST_SUITE_MODEL_OUT:-MODEL_r19.json}"
    # run_check shards per-spec units across ST_SUITE_MODEL_JOBS worker
    # processes (default min(4, nproc)) and logs its own per-spec
    # "gate model/<spec>: <sec>s rc=<rc>" lines inside this umbrella
    gate_run model_check python tools/protospec/run_check.py --out "$MODEL_OUT"
    [ "$FAILED" -ne 0 ] && { echo "FAIL: model-checker gate red" >>"$OUT"; exit 1; }
  fi
fi

for i in $(seq 1 "$RUNS"); do
  START=$(date -u +%H:%M:%SZ)
  LOG=$(mktemp)
  JAX_PLATFORMS=cpu timeout -k 10 870 \
    python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors \
    -p no:cacheprovider -p no:xdist -p no:randomly >"$LOG" 2>&1
  RC=$?
  # this environment's pytest -q emits only the dot-progress bar (no
  # summary line), so the transcript keeps the bars + a dot count — the
  # same evidence format as SUITE_LOAD_r03/r04
  DOTS=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG")
  PASSED=$(printf '%s' "$DOTS" | tr -cd . | wc -c)
  echo "=== run $i/$RUNS  start=$START  rc=$RC  dots_passed=$PASSED ===" >>"$OUT"
  printf '%s\n' "$DOTS" >>"$OUT"
  [ "$RC" -ne 0 ] && FAILED=1 && grep -aE '^FAILED|^ERROR' "$LOG" | sort -u >>"$OUT"
  rm -f "$LOG"
done
if [ "$FAILED" -eq 0 ]; then
  echo "PASS: $RUNS/$RUNS consecutive loaded runs green" >>"$OUT"
else
  echo "FAIL: at least one run red (see above)" >>"$OUT"
fi

# TSan gate (r13; r14 shards it): the engine, striping/sign2, lifecycle
# and shm-lane suites under ThreadSanitizer (make -C native tsan +
# LD_PRELOAD libtsan; tests/test_sanitizers.py TSan arms). Ordered BEFORE
# the perf-floor gate: a data race is a correctness red, and the bench
# should never ride on top of one. Zero unsuppressed reports required;
# native/tsan.supp's target state is empty. r13 ran the three arms
# serially (~8 min of wall the box spends mostly waiting on TSan's
# single-test slowdowns); r14 runs all four CONCURRENTLY, one pytest
# process per arm with its own log, appended to the transcript in arm
# order after the barrier — same evidence, one arm's wall. The tsan
# build runs ONCE up front so the concurrent arms can't race `make`.
# ST_SUITE_TSAN=0 skips (the tests also skip cleanly on a box without
# the gcc TSan runtime).
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_TSAN:-1}" = "1" ]; then
  echo "--- TSan gate (engine | striping/sign2 | lifecycle | shm — 4 concurrent shards) ---" >>"$OUT"
  make -C native tsan >/dev/null 2>>"$OUT" || FAILED=1
  if [ "$FAILED" -eq 0 ]; then
    TSAN_ARMS="test_engine_suite_under_tsan test_striped_sign2_suite_under_tsan test_lifecycle_suite_under_tsan test_shm_suite_under_tsan"
    TSAN_PIDS=""
    for arm in $TSAN_ARMS; do
      JAX_PLATFORMS=cpu python -m pytest \
        "tests/test_sanitizers.py::$arm" \
        -m slow -q -p no:cacheprovider >"/tmp/st_tsan_$arm.log" 2>&1 &
      TSAN_PIDS="$TSAN_PIDS $!:$arm"
    done
    for pa in $TSAN_PIDS; do
      pid="${pa%%:*}"; arm="${pa#*:}"
      wait "$pid"; RC=$?
      echo "--- TSan shard: $arm (rc=$RC) ---" >>"$OUT"
      cat "/tmp/st_tsan_$arm.log" >>"$OUT"
      rm -f "/tmp/st_tsan_$arm.log"
      [ "$RC" -ne 0 ] && FAILED=1
    done
  fi
fi

# Perf-floor gate (r07): a green suite is necessary but not sufficient — a
# refactor that silently halves the data plane's throughput passes every
# functional test. After the green runs, run bench.py ONCE and fail if the
# headline metric (sync_bandwidth_equiv_fp32_per_link) regressed more than
# 10% against the newest committed BENCH_r*.json (fallback: the reference
# baseline, 1.01 GB/s). The run is recorded as an artifact for the round
# (ST_SUITE_BENCH_OUT, default BENCH_r07.json — later rounds pass their
# own name). ST_SUITE_BENCH=0 skips the gate (e.g. a red-suite debug loop).
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_BENCH:-1}" = "1" ]; then
  BENCH_OUT="${ST_SUITE_BENCH_OUT:-BENCH_r07.json}"
  ST_BENCH_BUDGET_S="${ST_BENCH_BUDGET_S:-240}" \
    python benchmarks/bench_gate.py "$BENCH_OUT" >>"$OUT" 2>&1 || FAILED=1
fi

# Obs-overhead gate (r08; r09 added the paired trace-stamping arm; r18
# adds the health arm — fast digest beats + the root-side fleet-health
# analyzer live under the same paired A/B): the unified telemetry —
# cross-hop trace stamping and digest+health housekeeping included —
# must stay <2% on the engine hot path (paired within-run A/B; fails
# only when the measured drop is statistically past the budget on any
# arm — benchmarks/obs_overhead.py). The run is recorded as the round's
# OBS artifact (ST_SUITE_OBS_OUT, default OBS_r18.json). ST_SUITE_OBS=0
# skips (e.g. red-suite debugging).
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_OBS:-1}" = "1" ]; then
  OBS_OUT="${ST_SUITE_OBS_OUT:-OBS_r18.json}"
  JAX_PLATFORMS=cpu python benchmarks/obs_overhead.py "$OBS_OUT" \
    >/dev/null 2>>"$OUT" || FAILED=1
fi

# Fleet-health gate (r18): the observability acceptance arm — a sharded
# fleet under zipf writes whose hot shard the root's health analyzer
# must NAME within 3 digest beats, a peer tree whose staleness-SLO page
# alert must FIRE during an injected writer stall and CLEAR after the
# resume, and a +/-50 ms simulated-skew pair whose control-plane offset
# estimates and offset-corrected staleness must agree with the injected
# skew within their own reported uncertainty
# (benchmarks/fleet_health.py -> the round's CHAOS_r18 artifact,
# ST_SUITE_HEALTH_OUT). ST_SUITE_HEALTH=0 skips.
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_HEALTH:-1}" = "1" ]; then
  HEALTH_OUT="${ST_SUITE_HEALTH_OUT:-CHAOS_r18.json}"
  gate_run fleet_health sh -c \
    "JAX_PLATFORMS=cpu python benchmarks/fleet_health.py '$HEALTH_OUT' \
     >/dev/null"
fi

# Serving-tier gate (r10): under full write load, a read-only subscriber's
# p99 verified staleness must stay inside the configured bound — lower-90%
# discipline across repeats (mean - 1.645*SEM), same as the obs gate, per
# this box's 5-10% loopback noise. Runs AFTER the perf-floor gate so the
# committed SERVE artifact always rides a passing write-path floor in the
# same suite run (benchmarks/serve_bench.py). ST_SUITE_SERVE=0 skips.
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_SERVE:-1}" = "1" ]; then
  SERVE_OUT="${ST_SUITE_SERVE_OUT:-SERVE_r10.json}"
  JAX_PLATFORMS=cpu python benchmarks/serve_bench.py "$SERVE_OUT" \
    >/dev/null 2>>"$OUT" || FAILED=1
fi

# Lifecycle gate (r12): the kill-and-restore chaos arm — consistent-cut
# snapshot mid-soak under drop chaos, whole-tree kill, restart from shards
# (one node version-skewed to v1 emission: the rolling-upgrade interop
# proof), and a final-replica comparison against an uninterrupted arm
# applying the identical add schedule. Fails the suite if the snapshot
# barrier or the restore blows its time budget (ST_SNAP_BUDGET_S /
# ST_RESTORE_BUDGET_S) or the arms diverge. Runs AFTER the perf-floor
# gate so the committed CHAOS artifact always rides a passing floor in
# the same suite run. ST_SUITE_LIFECYCLE=0 skips.
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_LIFECYCLE:-1}" = "1" ]; then
  LIFE_OUT="${ST_SUITE_LIFECYCLE_OUT:-CHAOS_r12.json}"
  # r14: the lifecycle chaos arm runs --shm by default — the shm lanes
  # ARE the loopback cluster's normal data plane now, and the arm
  # additionally asserts they were live at both ends of every writer
  # link (pre-kill and after the restart's fresh negotiation) with the
  # digest exact at quiesce. ST_SUITE_SHM=0 drops the flag (pure-TCP
  # lifecycle arm, the r12 shape).
  # r15: the arm is ALSO the live trace-conformance gate — it replays
  # its own flight-recorder timeline through the protospec trace
  # acceptors and fails on any forbidden ordering, closing the
  # spec<->implementation loop the model checker opened above.
  SHM_FLAG="--shm"
  [ "${ST_SUITE_SHM:-1}" = "0" ] && SHM_FLAG=""
  # stdout (the full JSON doc — it is the committed artifact) stays out
  # of the transcript; stderr's one-line verdict + timing go in
  gate_run lifecycle_chaos_conformance sh -c \
    "JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py '$LIFE_OUT' \
     --kill-restore $SHM_FLAG >/dev/null"
fi

# Sharded gate (r16): the cluster-sharded chaos arm — 7-node sharded
# tree (one shard per node, owner-routed FWD data plane) under the 25%
# drop schedule with kill-restore through the sharded checkpoint path.
# Gates the r16 acceptance bar alongside the lifecycle gate: a model
# ST_SHARD_FACTOR x bigger than any node's enforced alloc bound
# converges EXACTLY (bound checked at every soak sample), every node
# re-owns its shards after the restore, the manifest's
# exactly-one-owner coverage audit is clean, and steady-state per-node
# memory lands at ~1/N of a full replica. ST_SUITE_SHARD=0 skips.
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_SHARD:-1}" = "1" ]; then
  # r17: the arm runs on the ENGINE lane by default now (the shard FWD
  # plane's production path); ST_SHARD_ENGINE=0 pins the python-tier arm
  SHARD_OUT="${ST_SUITE_SHARD_OUT:-CHAOS_r17.json}"
  gate_run sharded_chaos sh -c \
    "JAX_PLATFORMS=cpu python benchmarks/cluster_chaos.py '$SHARD_OUT' \
     --sharded >/dev/null"
fi

# Shard-perf gate (r17): the engine-tier FWD plane must hold its
# ratcheted per-link throughput floor (lower-90 across repeats, the
# obs/serve-gate discipline per this box's 5-10% loopback noise) AND the
# r17 acceptance ratio — engine-tier >= 5x the python-tier plane — on
# the committed SHARD_BENCH artifact (benchmarks/shard_bench.py).
# ST_SUITE_SHARDBENCH=0 skips.
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_SHARDBENCH:-1}" = "1" ]; then
  SHARDBENCH_OUT="${ST_SUITE_SHARDBENCH_OUT:-SHARD_BENCH_r17.json}"
  gate_run shard_perf sh -c \
    "JAX_PLATFORMS=cpu python benchmarks/shard_bench.py '$SHARDBENCH_OUT' \
     >/dev/null"
fi

# Sanitizer arm (r11): striping + adaptive precision put new hot code in
# all three native libs (per-stripe sender/receiver threads + reassembly,
# sign2 pack/unpack + cascade kernels, the precision governor). Run the
# striped+adaptive sanitizer test (ASan+UBSan via make -C native sanitize;
# the sign2 suite + the per-stripe chaos tests) as part of the loaded
# suite so a latent memory bug in the new planes turns the suite red, not
# just the nightly. r12 adds the lifecycle sanitizer arm in the same
# invocation: the snapshot barrier's one-mutex bulk captures race the
# live codec threads — exactly ASan territory. ST_SUITE_SAN=0 skips
# (e.g. a box without the gcc sanitizer runtimes — the tests themselves
# also skip cleanly there).
if [ "$FAILED" -eq 0 ] && [ "${ST_SUITE_SAN:-1}" = "1" ]; then
  echo "--- sanitizer arm (striped+adaptive + lifecycle + shard engine) ---" >>"$OUT"
  JAX_PLATFORMS=cpu python -m pytest \
    tests/test_sanitizers.py::test_striped_adaptive_suite_under_asan_ubsan \
    tests/test_sanitizers.py::test_lifecycle_suite_under_asan_ubsan \
    tests/test_sanitizers.py::test_shard_engine_suite_under_asan_ubsan \
    -m slow -q -p no:cacheprovider >>"$OUT" 2>&1 || FAILED=1
fi
exit "$FAILED"
