"""ENGINE_SWEEP_r14 generator: the same-host shm lane vs the r12-shape
2-stripe TCP point, interleaved per repeat so the box's drift (5-10%
loopback noise, slow thermal/VM wander measured across this round) hits
both arms alike. Arms:

- shm:  the r14 default data plane (lane + aligned v3 framing +
  zero-repack receive), stripe_count 1 — extra TCP stripes only idle
  beneath a live lane;
- tcp2: ST_SHM=0, stripe_count 2 — the r11/r12 loopback sweet spot
  (striping saturated at 2 sockets on this box), on the SAME build, so
  the comparison isolates the lane + r14 framing rather than crediting
  them with r14's lane-independent gains (recv_zc, sendmmsg).

Emits one JSON document to argv[1] (default ENGINE_SWEEP_r14.json).
Run: JAX_PLATFORMS=cpu python benchmarks/engine_sweep_r14.py [out] [reps]
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SIZES = [4096, 65536, 1 << 19, 1 << 20, 1 << 21, 1 << 24]
REPS = int(sys.argv[2]) if len(sys.argv) > 2 else 2


def run_arm(sizes, shm: bool, stripes: int) -> list:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ST_ENGINE_BENCH_SIZES=",".join(str(s) for s in sizes),
        ST_ENGINE_BENCH_STRIPES=str(stripes),
    )
    if not shm:
        env["ST_SHM"] = "0"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "engine_bench.py")],
        capture_output=True, text=True, env=env, timeout=1200, cwd=REPO,
    )
    for line in reversed(r.stdout.strip().splitlines()):
        if line.startswith("{"):
            rows = json.loads(line)["rows"]
            for row in rows:
                row["arm"] = "shm" if shm else "tcp-2stripe"
            return rows
    raise RuntimeError(f"bench arm produced no JSON: {r.stderr[-500:]}")


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "ENGINE_SWEEP_r14.json"
    rows = []
    for rep in range(REPS):
        for shm, stripes in ((True, 1), (False, 2)):
            for row in run_arm(SIZES, shm, stripes):
                row["rep"] = rep
                rows.append(row)
            print(
                f"rep {rep} {'shm' if shm else 'tcp2'} done",
                file=sys.stderr, flush=True,
            )
    # per-size verdict: mean equiv GB/s per arm; shm_wins on the mean
    verdict = {}
    for n in SIZES:
        means = {}
        for arm in ("shm", "tcp-2stripe"):
            vals = [
                r["equiv_fp32_GBps"] for r in rows
                if r["n"] == n and r["arm"] == arm
            ]
            means[arm] = round(sum(vals) / len(vals), 3) if vals else 0.0
        verdict[str(n)] = {
            **means, "shm_wins": means["shm"] > means["tcp-2stripe"],
        }
    doc = {
        "bench": "engine_sweep_r14_shm_vs_tcp",
        "tier": "host-native-engine",
        "arms": {
            "shm": "r14 default: shm lane + v3 aligned framing, 1 stripe",
            "tcp-2stripe": "ST_SHM=0 (no lane, no r14 capability -> v2 "
                           "framing), 2 TCP stripes — the r11/r12 loopback "
                           "sweet spot on the same build",
        },
        "reps_per_point": REPS,
        "rows": rows,
        "verdict": verdict,
        "shm_wins_at_sizes": [n for n in SIZES if verdict[str(n)]["shm_wins"]],
    }
    path = out_path if os.path.isabs(out_path) else os.path.join(REPO, out_path)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(doc["verdict"], indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
