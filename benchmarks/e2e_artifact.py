"""Machine-generate the round's E2E artifact (E2E_r{NN}.json).

Every number in the artifact is the verbatim JSON line emitted by
benchmarks/e2e_sync.py for that arm — no hand-curated aggregates. The
headline ratios are the script's own per-direction fields
(vs_baseline_out / vs_baseline_in, against BASELINE.md's per-direction
reference rows) and their fair average vs_baseline; a bidirectional SUM is
never divided by a per-direction baseline (VERDICT r04 Weak #1).

Run: JAX_PLATFORMS=cpu python benchmarks/e2e_artifact.py > E2E_r05.json
Knobs: ST_E2E_ROUND (tag), ST_E2E_ARM_SECONDS (per-arm measure window),
ST_E2E_SKIP_C=1 (skip the compiled-C-peer interop arm).
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SYNC = os.path.join(REPO, "benchmarks", "e2e_sync.py")
ROUND = os.environ.get("ST_E2E_ROUND", "r05")
SECONDS = os.environ.get("ST_E2E_ARM_SECONDS", "10")


def run_arm(name: str, env_overrides: dict, timeout: float = 420.0):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        ST_E2E_SECONDS=SECONDS,
        **{k: str(v) for k, v in env_overrides.items()},
    )
    r = subprocess.run(
        [sys.executable, SYNC],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    repro = " ".join(
        f"{k}={v}" for k, v in sorted(env_overrides.items())
    ) + " python benchmarks/e2e_sync.py"
    if r.returncode != 0 or not r.stdout.strip():
        return {"arm": name, "status": "failed", "stderr": r.stderr[-500:],
                "repro": repro}
    row = json.loads(r.stdout.strip().splitlines()[-1])
    row["arm"] = name
    row["repro"] = repro
    return row


def main() -> None:
    arms = [
        ("host_bidir_4ki", {"ST_E2E_PARENT_PLATFORM": "cpu",
                            "ST_E2E_N": 4096}),
        ("host_bidir_1mi", {"ST_E2E_PARENT_PLATFORM": "cpu",
                            "ST_E2E_N": 1 << 20}),
        ("host_bidir_16mi", {"ST_E2E_PARENT_PLATFORM": "cpu",
                             "ST_E2E_N": 16 << 20}),
        ("compat_both_ours_1mi", {"ST_E2E_PARENT_PLATFORM": "cpu",
                                  "ST_E2E_N": 1 << 20,
                                  "ST_E2E_COMPAT": 1}),
    ]
    if os.environ.get("ST_E2E_SKIP_C") != "1":
        arms.append(
            ("wire_compat_vs_compiled_C_peer",
             {"ST_E2E_PARENT_PLATFORM": "cpu", "ST_E2E_N": 1 << 20,
              "ST_E2E_CHILD": "c"})
        )
    rows = [run_arm(name, envo) for name, envo in arms]
    out = {
        "bench": f"e2e_peer_sync_{ROUND}",
        "note": (
            "2-process E2E through the full peer stack; every row is the "
            "verbatim e2e_sync.py output for that arm (see each row's "
            "repro). Ratios are PER-DIRECTION vs BASELINE.md's "
            "per-direction reference rows (vs_baseline_out/in), "
            "vs_baseline = their fair average. Both peers stream "
            "full-duplex, as does the reference."
        ),
        "arms": rows,
        "produced_by": "benchmarks/e2e_artifact.py (machine-generated)",
    }
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
