"""Training-throughput benchmark: char-rnn async-DP step time, tokens/s, MFU,
and sync overhead (VERDICT.md round-1 item 4; BASELINE config 2 workload).

Four arms of the SAME fused training step (train/async_sgd.py), differing
only in the sync tail:

- ``sync_off``   — pure local SGD, no communication (isolation baseline);
- ``compressed`` — the framework's 1-bit error-feedback codec sync (the
  reference's semantics, reference README.md:13-19);
- ``compressed_overlap`` — same codec, collective scheduled under the
  backward pass (async overlap mode, train/async_sgd.py ``overlap=True``);
- ``exact``      — uncompressed delta exchange (the allreduce comparison arm,
  BASELINE config 4).

Sync overhead = (t_arm - t_sync_off) / t_sync_off: what fraction of a
training step the parameter sync costs, the in-step analog of the
reference's codec-CPU bottleneck (SURVEY.md §6: one core fully saturated).

MFU uses analytic matmul FLOPs (fwd 2N, bwd 4N per token, N = matmul
params/token) against the chip's peak (ST_PEAK_FLOPS env override; default
197e12 = v5e bf16 peak when on TPU, none on CPU — MFU is then null).

Steps are chained device-side with a dynamic-trip-count fori_loop (one
compile per arm, tunnel latency amortized — utils/timing.py rationale).
Prints ONE JSON line with all arms; hard wall-clock budget via
ST_TRAIN_BENCH_BUDGET_S (default 600 s), emitting whatever completed.
"""

from __future__ import annotations

import json
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BUDGET_S = float(os.environ.get("ST_TRAIN_BENCH_BUDGET_S", "600"))
_T0 = time.monotonic()


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _T0)


def flops_per_token(cfg) -> int:
    """Analytic matmul FLOPs per token for one training step (fwd+bwd).

    Matmul params N/token: per layer (d*4H input proj + H*4H recurrent),
    plus H*V output proj; embedding lookup is a gather (no FLOPs). Forward
    = 2N, backward = 4N (standard approximation), total 6N.
    """
    n = 0
    d = cfg.embed
    for _ in range(cfg.layers):
        n += d * 4 * cfg.hidden + cfg.hidden * 4 * cfg.hidden
        d = cfg.hidden
    n += cfg.hidden * cfg.vocab
    return 6 * n


def bench_arm(
    jnp,
    jax,
    trainer,
    batch,
    lr: float,
    target_seconds: float,
    budget_s: float,
) -> float:
    """Seconds per training step, measured on a device-side chain of steps
    (same batch every step — throughput, not convergence)."""
    deadline = time.monotonic() + budget_s
    step_fn = trainer._step  # the compiled fused step

    losses0 = jnp.zeros((trainer.n_peer,), jnp.float32)

    @partial(jax.jit, donate_argnums=(0,))
    def chain(state, k):
        def body(_, carry):
            st, losses = carry
            st, _, losses, _ = step_fn(st, trainer.opt_state, batch, lr)
            return (st, losses)

        st, losses = jax.lax.fori_loop(0, k, body, (state, losses0))
        return st, losses, losses[0]

    def timed(k: int) -> float:
        state = trainer.state
        t0 = time.perf_counter()
        state, _, probe = chain(state, jnp.int32(k))
        float(probe)  # forces completion through the tunnel
        trainer.state = state  # keep ownership after donation
        return time.perf_counter() - t0

    k = 2
    timed(k)  # warmup/compile
    t = timed(k)
    while t < target_seconds and k < 100_000:
        if time.monotonic() > deadline:
            break
        est = max(t / k, 1e-9)
        k = min(100_000, max(k * 2, int(target_seconds / est)))
        t = timed(k)
    return t / k


def main() -> None:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None, help="force a jax platform (e.g. cpu)")
    ap.add_argument("--peers", type=int, default=None, help="peer-axis size (default: all devices)")
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--tiny", action="store_true", help="tiny model (CI smoke)")
    args = ap.parse_args()

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import jax.numpy as jnp

    from shared_tensor_tpu.models import char_rnn as m
    from shared_tensor_tpu.ops import codec_pallas
    from shared_tensor_tpu.parallel.mesh import make_mesh
    from shared_tensor_tpu.train.async_sgd import PodTrainer

    on_tpu = not codec_pallas._interpret()
    peak = float(os.environ.get("ST_PEAK_FLOPS", "197e12")) if on_tpu else None

    if args.tiny:
        cfg = m.CharRNNConfig(vocab=64, embed=32, hidden=64, layers=2)
    else:
        cfg = m.CharRNNConfig()  # flagship: 2-layer LSTM 512, byte vocab
    n_peer = args.peers or len(jax.devices())
    mesh = make_mesh(n_peer, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)

    text = (b"the quick brown fox jumps over the lazy dog. " * 200)
    batch = m.make_batches(
        text, batch=args.batch, seq=args.seq, key=jax.random.key(1),
        n_peer=n_peer, vocab=cfg.vocab,
    )

    arms = [
        ("sync_off", dict(sync=False)),
        ("compressed", dict(sync=True, compressed=True)),
        # collective scheduled under the backward pass (async overlap mode,
        # train/async_sgd.py overlap=True) — the arm that should drive
        # sync_overhead_pct toward zero on hardware with real ICI latency
        ("compressed_overlap", dict(sync=True, compressed=True, overlap=True)),
        ("exact", dict(sync=True, compressed=False)),
    ]
    tokens_per_step = n_peer * args.batch * args.seq
    fpt = flops_per_token(cfg)
    out: dict = {
        "metric": "train_step_bench",
        "model": "char_rnn",
        "config": {
            "vocab": cfg.vocab, "embed": cfg.embed, "hidden": cfg.hidden,
            "layers": cfg.layers, "params": cfg.param_count,
            "n_peer": n_peer, "batch": args.batch, "seq": args.seq,
        },
        "backend": jax.default_backend(),
        "on_tpu": on_tpu,
        "flops_per_token": fpt,
        "arms": {},
    }
    t_base = None
    for name, kw in arms:
        slice_budget = _remaining() / max(1, len(arms) - len(out["arms"]))
        if slice_budget < 20:
            out["arms"][name] = {"error": "budget exhausted"}
            continue
        try:
            trainer = PodTrainer(mesh, params, loss, **kw)
            batch_sh = trainer.shard_batch(batch)
            t_step = bench_arm(
                jnp, jax, trainer, batch_sh, 0.05,
                target_seconds=2.0, budget_s=slice_budget,
            )
            tok_s = tokens_per_step / t_step
            arm: dict = {
                "step_ms": round(t_step * 1e3, 3),
                "tokens_per_s": round(tok_s, 1),
                "mfu": round(fpt * tok_s / peak, 4) if peak else None,
            }
            if name == "sync_off":
                t_base = t_step
            elif t_base:
                arm["sync_overhead_pct"] = round((t_step - t_base) / t_base * 100, 1)
            out["arms"][name] = arm
        except Exception as e:  # an arm failure must not kill the artifact
            import traceback

            traceback.print_exc(file=sys.stderr)
            out["arms"][name] = {"error": f"{type(e).__name__}: {e}"}
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
