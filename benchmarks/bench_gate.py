"""Perf-floor gate for the loaded-suite harness (suite_load.sh, r07).

Runs bench.py once, records the JSON result as the round's BENCH artifact
(argv[1]), and exits nonzero when ``sync_bandwidth_equiv_fp32_per_link``
falls below the RATCHETED floor — so a data-plane refactor that passes
every functional test but halves throughput turns the suite red.

r11 ratchet ("raise the floor, don't just pass it", ROADMAP item 4): the
floor is ``max(prior round's locked floor, (1 - pct) * prior headline)``
— monotone non-decreasing across rounds, so a round that lands a big gain
LOCKS IT IN via the ``floor_locked`` field its artifact records (=
``max(floor used, (1 - pct) * measured value)``); a later regression back
to the pre-gain level fails even if it is within 10% of the most recent
(already-regressed) round. Pre-r11 artifacts carry no ``floor_locked``,
so the first ratcheted round degrades to the old newest-headline rule.

The comparison value is the newest prior round's ``parsed.value`` (the
driver's artifact shape) or top-level ``value`` (raw bench.py output);
with no prior artifact the reference baseline (1.01 GB/s, BASELINE.md)
is the floor's base. Caveat recorded in the artifact: bench.py's arm
ladder means a round measured on a degraded arm (chip wedged worse than
usual) can trip the gate spuriously — the artifact keeps the arm trail
(detail.attempts) so a red gate is diagnosable at a glance, and the box's
5-10% loopback noise is why pct stays 10 rather than 0.
"""

import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_GBPS = 1.01  # BASELINE.md E2E yardstick (bench.py BASELINE_GBPS)


def _prior_value(exclude: str):
    """(value, locked_floor, source_path) from the newest committed
    BENCH_r*.json. ``locked_floor`` is that round's recorded ratchet
    (0.0 when the artifact predates r11)."""
    best = None
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        name = os.path.basename(p)
        if name == os.path.basename(exclude):
            continue  # never ratchet against our own output
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if not m:
            continue
        rnd = int(m.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, p)
    if best is None:
        return REFERENCE_GBPS, 0.0, "BASELINE.md reference"
    try:
        with open(best[1]) as f:
            doc = json.load(f)
        parsed = doc.get("parsed", doc)
        v = float(parsed["value"])
        locked = float(doc.get("floor_locked", 0.0))
        return v, locked, os.path.basename(best[1])
    except Exception:
        return REFERENCE_GBPS, 0.0, "BASELINE.md reference (prior unparseable)"


def main() -> int:
    art_path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_gate.json"
    if not os.path.isabs(art_path):
        art_path = os.path.join(REPO, art_path)
    pct = float(os.environ.get("ST_BENCH_GATE_PCT", "10"))
    prior, locked, source = _prior_value(art_path)
    floor = max(locked, prior * (1.0 - pct / 100.0))
    floor_from = (
        f"max({source} floor_locked {locked:.2f}, "
        f"{source} value * (1 - {pct}%))"
        if locked > prior * (1.0 - pct / 100.0)
        else f"{source} * (1 - {pct}%)"
    )

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, cwd=REPO,
    )
    result = None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    value = float(result.get("value", 0.0)) if result else 0.0
    ok = result is not None and value >= floor

    artifact = {
        "gate": "suite_load perf floor (ratcheted, r11)",
        "metric": "sync_bandwidth_equiv_fp32_per_link",
        "floor_gbps": round(floor, 3),
        "floor_from": floor_from,
        # the ratchet the NEXT round inherits: this round's gain, locked
        "floor_locked": round(max(floor, value * (1.0 - pct / 100.0)), 3),
        "pass": ok,
        "parsed": result,
    }
    with open(art_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(
        f"bench gate: {value:.2f} GB/s vs floor {floor:.2f} GB/s "
        f"({source}) -> {'PASS' if ok else 'FAIL'} "
        f"[artifact {os.path.basename(art_path)}]"
    )
    if not ok and proc.stderr:
        print(proc.stderr[-1000:])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
