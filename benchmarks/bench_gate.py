"""Perf-floor gate for the loaded-suite harness (suite_load.sh, r07).

Runs bench.py once, records the JSON result as the round's BENCH artifact
(argv[1]), and exits nonzero when ``sync_bandwidth_equiv_fp32_per_link``
regressed more than the tolerance (default 10%, ST_BENCH_GATE_PCT) against
the newest *committed* BENCH_r*.json — so a data-plane refactor that
passes every functional test but halves throughput turns the suite red.

The comparison value is the best prior round's ``parsed.value`` (the
driver's artifact shape) or top-level ``value`` (raw bench.py output);
with no prior artifact the reference baseline (1.01 GB/s, BASELINE.md)
is the floor's base. Caveat recorded in the artifact: bench.py's arm
ladder means a round measured on a degraded arm (chip wedged worse than
usual) can trip the gate spuriously — the artifact keeps the arm trail
(detail.attempts) so a red gate is diagnosable at a glance.
"""

import glob
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_GBPS = 1.01  # BASELINE.md E2E yardstick (bench.py BASELINE_GBPS)


def _prior_value(exclude: str):
    """(value, source_path) from the newest committed BENCH_r*.json."""
    best = None
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        name = os.path.basename(p)
        if name == os.path.basename(exclude):
            continue  # never ratchet against our own output
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if not m:
            continue
        rnd = int(m.group(1))
        if best is None or rnd > best[0]:
            best = (rnd, p)
    if best is None:
        return REFERENCE_GBPS, "BASELINE.md reference"
    try:
        with open(best[1]) as f:
            doc = json.load(f)
        parsed = doc.get("parsed", doc)
        v = float(parsed["value"])
        return v, os.path.basename(best[1])
    except Exception:
        return REFERENCE_GBPS, "BASELINE.md reference (prior unparseable)"


def main() -> int:
    art_path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_gate.json"
    if not os.path.isabs(art_path):
        art_path = os.path.join(REPO, art_path)
    pct = float(os.environ.get("ST_BENCH_GATE_PCT", "10"))
    prior, source = _prior_value(art_path)
    floor = prior * (1.0 - pct / 100.0)

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, cwd=REPO,
    )
    result = None
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                result = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    value = float(result.get("value", 0.0)) if result else 0.0
    ok = result is not None and value >= floor

    artifact = {
        "gate": "suite_load perf floor",
        "metric": "sync_bandwidth_equiv_fp32_per_link",
        "floor_gbps": round(floor, 3),
        "floor_from": f"{source} * (1 - {pct}%)",
        "pass": ok,
        "parsed": result,
    }
    with open(art_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(
        f"bench gate: {value:.2f} GB/s vs floor {floor:.2f} GB/s "
        f"({source}) -> {'PASS' if ok else 'FAIL'} "
        f"[artifact {os.path.basename(art_path)}]"
    )
    if not ok and proc.stderr:
        print(proc.stderr[-1000:])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
