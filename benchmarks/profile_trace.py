"""Capture a jax.profiler trace of the flagship training step (verdict r2
item 6: a committed trace artifact attributing step time).

Runs a few warm steps, then traces a short chained run of each arm
(sync_off / compressed / compressed_overlap) into ``--out`` (default
PROFILE_TRACE_r03/). The trace directory is the artifact; load it with
TensorBoard's profile plugin or xprof.

Usage: python benchmarks/profile_trace.py [--out DIR] [--steps 20]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="PROFILE_TRACE_r03")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=256)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from shared_tensor_tpu.models import char_rnn as m
    from shared_tensor_tpu.parallel.mesh import make_mesh
    from shared_tensor_tpu.train.async_sgd import PodTrainer
    from shared_tensor_tpu.utils.profiling import trace

    cfg = m.CharRNNConfig()  # flagship
    n_peer = len(jax.devices())
    mesh = make_mesh(n_peer, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)
    text = b"the quick brown fox jumps over the lazy dog. " * 200
    batch = m.make_batches(
        text, batch=args.batch, seq=args.seq, key=jax.random.key(1),
        n_peer=n_peer, vocab=cfg.vocab,
    )

    arms = [
        ("sync_off", dict(sync=False)),
        ("compressed", dict(sync=True, compressed=True)),
        ("compressed_overlap", dict(sync=True, compressed=True, overlap=True)),
    ]
    for name, kw in arms:
        tr = PodTrainer(mesh, params, loss, **kw)
        b = tr.shard_batch(batch)
        for _ in range(3):  # compile + warm
            tr.step(b, lr=0.1)
        jax.block_until_ready(tr.state.values)
        with trace(os.path.join(args.out, name)):
            for _ in range(args.steps):
                losses, _ = tr.step(b, lr=0.1)
            jax.block_until_ready(losses)
        print(f"traced {name} -> {args.out}/{name}", flush=True)


if __name__ == "__main__":
    main()
