"""Capture a jax.profiler trace of the flagship training step (verdict r2
item 6: a committed trace artifact attributing step time) — and, since
r09, a Perfetto/Chrome ``trace_event`` export of the OBSERVABILITY
timeline (the flight recorder's merged native+Python events, with
cross-node flow arrows per update generation).

Default mode runs a few warm steps, then traces a short chained run of
each arm (sync_off / compressed / compressed_overlap) into ``--out``
(default PROFILE_TRACE_r03/); load with TensorBoard's profile plugin.

``--events-out FILE`` instead runs a 3-node loopback CHAIN (max_children=1
so hops reach depth 2), streams a few updates through it, and exports the
flight-recorder timeline as Chrome trace JSON — open in
https://ui.perfetto.dev or chrome://tracing. This is how TRACE_r09.json
is produced.

Usage: python benchmarks/profile_trace.py [--out DIR] [--steps 20]
       python benchmarks/profile_trace.py --events-out TRACE_r09.json
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _events_demo(out_path: str) -> None:
    """3-node chain, multi-hop traffic, Perfetto export (r09)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import socket

    import jax.numpy as jnp
    import numpy as np

    from shared_tensor_tpu import obs
    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import Config, ObsConfig, TransportConfig
    from shared_tensor_tpu.obs import trace_export

    hub = obs.hub()
    hub.poll_native()
    hub.recorder.clear()
    hub.recorder.set_capacity(100_000)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    cfg = Config(
        transport=TransportConfig(peer_timeout_sec=20.0, max_children=1),
        obs=ObsConfig(digest_interval_sec=0.2),
    )
    n = 4096
    seed = jnp.zeros((n,), jnp.float32)
    peers = [
        create_or_fetch("127.0.0.1", port, seed, cfg, timeout=60.0)
        for _ in range(3)
    ]
    try:
        total = np.zeros(n, np.float64)
        rng = np.random.default_rng(0)
        for i in range(12):
            d = rng.normal(size=n).astype(np.float32)
            peers[i % len(peers)].add(jnp.asarray(d))
            total += d
            time.sleep(0.02)
        deadline = time.time() + 60.0
        while time.time() < deadline and not all(
            np.allclose(np.asarray(p.read()), total, atol=1e-4)
            for p in peers
        ):
            time.sleep(0.05)
        for p in peers:
            p.drain(timeout=20.0, tol=1e-30)
        hub.poll_native()
        timeline = hub.recorder.timeline()
        stats = trace_export.path_stats(trace_export.trace_paths(timeline))
        trace_export.export_file(out_path, timeline)
        print(
            f"exported {len(timeline)} events / {stats['paths']} update "
            f"paths (max {stats['max_hops']} hops) -> {out_path}",
            flush=True,
        )
    finally:
        for p in peers:
            p.close()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="PROFILE_TRACE_r03")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument(
        "--events-out", default="",
        help="export the obs timeline as Chrome trace JSON instead of "
        "running the jax.profiler arms (r09; writes e.g. TRACE_r09.json)",
    )
    args = ap.parse_args()
    if args.events_out:
        _events_demo(args.events_out)
        return

    import jax
    import jax.numpy as jnp

    from shared_tensor_tpu.models import char_rnn as m
    from shared_tensor_tpu.parallel.mesh import make_mesh
    from shared_tensor_tpu.train.async_sgd import PodTrainer
    from shared_tensor_tpu.utils.profiling import trace

    cfg = m.CharRNNConfig()  # flagship
    n_peer = len(jax.devices())
    mesh = make_mesh(n_peer, 1)
    params = m.init_params(jax.random.key(0), cfg)
    loss = lambda p, b: m.loss_fn(p, b, cfg)
    text = b"the quick brown fox jumps over the lazy dog. " * 200
    batch = m.make_batches(
        text, batch=args.batch, seq=args.seq, key=jax.random.key(1),
        n_peer=n_peer, vocab=cfg.vocab,
    )

    arms = [
        ("sync_off", dict(sync=False)),
        ("compressed", dict(sync=True, compressed=True)),
        ("compressed_overlap", dict(sync=True, compressed=True, overlap=True)),
    ]
    for name, kw in arms:
        tr = PodTrainer(mesh, params, loss, **kw)
        b = tr.shard_batch(batch)
        for _ in range(3):  # compile + warm
            tr.step(b, lr=0.1)
        jax.block_until_ready(tr.state.values)
        with trace(os.path.join(args.out, name)):
            for _ in range(args.steps):
                losses, _ = tr.step(b, lr=0.1)
            jax.block_until_ready(losses)
        print(f"traced {name} -> {args.out}/{name}", flush=True)


if __name__ == "__main__":
    main()
