"""Wall-clock comparison of the pod trainer's sync arms on a virtual mesh.

Round-3 verdict Weak #2: the claim "overlap ≤ fused" (the collective
scheduled under the backward pass, SURVEY.md §7.4 hard part 1) had no
measurement attached anywhere — the dryrun only proves it *runs*. This
captures the measurable CPU-mesh analog as an artifact (MESH_TIMING_r{N}
.json): 8 virtual devices, flagship char-rnn shape, fused vs overlap vs
exact vs no-sync, median step wall-clock after warmup.

A CPU mesh can't show ICI latency hiding (XLA:CPU runs one program per
"device" on threads; there's no real interconnect to overlap), so the
honest claim this artifact supports is bounded: overlap adds no wall-clock
overhead vs fused at equal semantics, and both compressed arms price
against exact/no-sync. The on-chip 4-arm train bench (TRAIN_BENCH) is the
hardware measurement; this is its always-available mesh-level companion.

Emits one JSON line; run via
  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python benchmarks/mesh_timing.py
(the script forces both itself when unset).
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# env alone cannot demote the platform when the site hook pinned the TPU
# plugin; the config update works pre-backend-init (e2e_sync.py pattern)
jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from shared_tensor_tpu.models import char_rnn as m  # noqa: E402
from shared_tensor_tpu.parallel.mesh import make_mesh  # noqa: E402
from shared_tensor_tpu.train import PodTrainer  # noqa: E402

CFG = m.CharRNNConfig(vocab=96, embed=64, hidden=192, layers=2)
TEXT = (b"the quick brown fox jumps over the lazy dog. " * 400)
N_PEER = 8
BATCH, SEQ = 8, 32
WARMUP, MEASURE = 3, 20


def _arm(name: str, **kw) -> dict:
    mesh = make_mesh(N_PEER, 1)
    params = m.init_params(jax.random.key(0), CFG)
    loss = lambda p, b: m.loss_fn(p, b, CFG)
    tr = PodTrainer(mesh, params, loss, **kw)
    batches = [
        tr.shard_batch(
            m.make_batches(
                TEXT, batch=BATCH, seq=SEQ, key=jax.random.key(i),
                n_peer=N_PEER, vocab=CFG.vocab,
            )
        )
        for i in range(4)
    ]
    for i in range(WARMUP):
        tr.step(batches[i % 4], lr=0.1)
    jax.block_until_ready(tr.state.values)
    times = []
    for i in range(MEASURE):
        t0 = time.perf_counter()
        losses, _ = tr.step(batches[i % 4], lr=0.1)
        jax.block_until_ready((tr.state.values, losses))
        times.append(time.perf_counter() - t0)
    times.sort()
    med = times[len(times) // 2]
    return {
        "arm": name,
        "median_step_s": round(med, 6),
        "p10_s": round(times[len(times) // 10], 6),
        "p90_s": round(times[(len(times) * 9) // 10], 6),
        "final_loss": round(float(jnp.mean(losses)), 4),
    }


def main() -> None:
    arms = [
        _arm("no_sync", sync=False),
        _arm("exact_allreduce", compressed=False),
        _arm("compressed_fused", compressed=True),
        _arm("compressed_overlap", compressed=True, overlap=True),
    ]
    by = {a["arm"]: a for a in arms}
    fused = by["compressed_fused"]["median_step_s"]
    over = by["compressed_overlap"]["median_step_s"]
    out = {
        "bench": "mesh_timing",
        "backend": jax.default_backend(),
        "n_devices": jax.device_count(),
        "n_peer": N_PEER,
        "model": {
            "vocab": CFG.vocab, "embed": CFG.embed,
            "hidden": CFG.hidden, "layers": CFG.layers,
            "params": sum(
                int(np.prod(s))
                for s in jax.tree.map(
                    lambda x: x.shape, jax.tree.leaves(
                        m.init_params(jax.random.key(0), CFG)
                    )
                )
            ),
        },
        "batch": BATCH,
        "seq": SEQ,
        "measure_steps": MEASURE,
        "arms": arms,
        "overlap_vs_fused": round(over / fused, 4),
        "note": (
            "CPU mesh: no real interconnect to hide latency under, so the "
            "supported claim is overlap ~= fused wall-clock at equal "
            "semantics; the on-chip TRAIN_BENCH measures the hardware "
            "benefit."
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
