"""E2E host-tier sync benchmark: a REAL 2-process loopback exchange through
the full production stack — device codec -> device_get -> native C++ TCP
transport -> peer -> device apply — measured against the reference's E2E
number (BASELINE.md: 242 frames/s, 1.01 GB/s equiv-fp32 deltas per link at
n = 1 Mi on loopback; probe of reference src/sharedtensor.c:113-189).

Round-2 verdict Missing #1: the codec microbench (bench.py) proves the kernel
tier, but nobody had measured what `SharedTensorPeer` actually sustains
end-to-end on the chip. This does: the parent peer runs on the default
backend (TPU when available), the child is a CPU-codec peer in a subprocess
(the reference's dev story — two processes on localhost, SURVEY.md §4.1).

Both sides continuously add() small updates so residual mass never quiesces
and links stream at full rate (the reference's "fills all bandwidth",
README.md:31). Equiv bandwidth counts the fp32 delta volume a frame applies
(n * 4 bytes), the same accounting as BASELINE.md.

Prints ONE JSON line. Orchestrator: `python benchmarks/e2e_sync.py`.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("ST_E2E_N", str(1 << 20)))
SECONDS = float(os.environ.get("ST_E2E_SECONDS", "10"))
WARMUP = float(os.environ.get("ST_E2E_WARMUP", "3"))
#: Seconds between add() calls on each side. An add costs one O(n) pass per
#: link residual + replica; at large n a fixed 0.2 s cadence would burn a
#: big share of the single core on adds instead of the codec stream being
#: measured — scale the period with the table size.
ADD_PERIOD = float(
    os.environ.get("ST_E2E_ADD_PERIOD", str(max(0.2, N / (1 << 20) * 0.05)))
)


#: ST_E2E_CHILD=c runs the wire-compat arm: the child is native/stc_harness —
#: a real compiled-C peer speaking the reference's exact wire protocol — so
#: the measurement is our peer engine vs a C peer ON THE REFERENCE'S OWN
#: PROTOCOL (single tensor, single global scale, no handshake/ACKs). That
#: arm is bounded by the C PEER's ~5 ms/frame loop, not by us; set
#: ST_E2E_COMPAT=1 to instead run BOTH python peers on the reference
#: protocol — our compat data plane's own ceiling, directly comparable to
#: the reference's 242 f/s C<->C loopback at the same n.
CHILD = os.environ.get("ST_E2E_CHILD", "py")
COMPAT = os.environ.get("ST_E2E_COMPAT", "0") == "1"


def _mk_peer(port: int):
    import numpy as np

    from shared_tensor_tpu.comm.peer import create_or_fetch
    from shared_tensor_tpu.config import Config, TransportConfig

    cfg = Config(
        transport=TransportConfig(
            peer_timeout_sec=30.0, wire_compat=(CHILD == "c" or COMPAT)
        ),
        send_pipeline_depth=int(os.environ.get("ST_E2E_DEPTH", "8")),
        # ST_E2E_DEVICE_BURST=1 pins single-frame device messages (the r03
        # comparison arm); default 0 = auto K-frame bursts (chip_runbook
        # step 5 measures both on the real tunnel)
        device_frame_burst=int(os.environ.get("ST_E2E_DEVICE_BURST", "0")),
    )
    # numpy template: a host-tier (CPU) peer then never initializes a jax
    # backend — the XLA CPU client's thread pool costs ~2.7x frame rate in
    # contention with the C codec loops on a small host (bench.py rationale)
    template = {"t": np.zeros((N,), np.float32)}
    return create_or_fetch("127.0.0.1", port, template, cfg, timeout=60.0)


def child(port: int) -> None:
    """CPU-side peer: join, then stream continuously until the parent dies."""
    import jax

    # the env alone cannot demote the platform (the site hook pins the TPU
    # plugin); the config update works as long as no backend is initialized
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    peer = _mk_peer(port)
    rng = np.random.default_rng(1)
    # numpy delta: keep this process jax-backend-free (see _mk_peer)
    delta = {"t": rng.normal(size=N).astype(np.float32) * 1e-2}
    try:
        while True:
            peer.add(delta)  # keep residual mass alive -> links never idle
            time.sleep(ADD_PERIOD)  # big infrequent adds: the add itself is O(n)
            # host work and must not contend with the codec stream
    except Exception:
        pass


def main() -> None:
    if len(sys.argv) > 1 and sys.argv[1] == "child":
        child(int(sys.argv[2]))
        return

    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    import jax

    # ST_E2E_PARENT_PLATFORM=cpu measures the host engine tunnel-free — the
    # apples-to-apples arm against the reference's CPU-only C loop (its 1.01
    # GB/s is 2 CPU processes on loopback, BASELINE.md). Default: the real
    # accelerator backend, with the device link in the loop.
    plat = os.environ.get("ST_E2E_PARENT_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)
    if plat == "cpu":
        # Don't initialize the backend at all: a host-tier parent with a
        # live XLA CPU client loses ~2.7x frame rate to its thread pool
        # (bench.py host-arm rationale). The tier decision in core.py reads
        # the configured platform string, not the live backend.
        backend, on_tpu = "cpu", False
    else:
        backend = jax.default_backend()
        from shared_tensor_tpu.ops import codec_pallas

        on_tpu = not codec_pallas._interpret()

    peer = _mk_peer(port)  # master, on the default (TPU) backend
    if CHILD == "c":
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        harness = os.path.join(repo, "native", "stc_harness")
        if not os.path.exists(harness):
            subprocess.run(
                ["make", "-C", os.path.join(repo, "native"), "stc_harness"],
                check=True, capture_output=True,
            )
        proc = subprocess.Popen(
            [harness, "127.0.0.1", str(port), str(N),
             str(WARMUP + SECONDS + 60), "1.0"],
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
    else:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "child", str(port)],
            env=env,
            stderr=subprocess.DEVNULL,
        )
    try:
        import numpy as np

        rng = np.random.default_rng(0)
        # numpy delta: host-tier parents stay backend-free; device tiers
        # convert inside their jitted codec anyway
        delta = {"t": rng.normal(size=N).astype(np.float32) * 1e-2}

        deadline = time.time() + 120
        while not peer.node.links and time.time() < deadline:
            time.sleep(0.05)
        t_end = time.time() + WARMUP
        while time.time() < t_end:
            peer.add(delta)
            time.sleep(ADD_PERIOD)

        link = peer.node.links[0]
        s0 = peer.node.stats(link)
        f_out0, f_in0 = peer.st.frames_out, peer.st.frames_in
        t0 = time.time()
        t_end = t0 + SECONDS
        while time.time() < t_end:
            peer.add(delta)
            time.sleep(ADD_PERIOD)
        dt = time.time() - t0
        s1 = peer.node.stats(link)
        frames_out = (peer.st.frames_out - f_out0) / dt
        frames_in = (peer.st.frames_in - f_in0) / dt
        wire_out = (s1.bytes_out - s0.bytes_out) / dt
        wire_in = (s1.bytes_in - s0.bytes_in) / dt
        equiv_out = frames_out * N * 4
        equiv_in = frames_in * N * 4
        # BASELINE.md E2E rows, equiv-fp32 B/s per link per DIRECTION
        # (78 k f/s @4 Ki, 242 @1 Mi, 7.8 @16 Mi; log-interpolated between
        # measured sizes so off-grid N still gets a sane yardstick)
        _ref_rows = [(4096, 1.28e9), (1 << 20, 1.01e9), (16 << 20, 0.52e9)]
        if N <= _ref_rows[0][0]:
            baseline = _ref_rows[0][1]
        elif N >= _ref_rows[-1][0]:
            baseline = _ref_rows[-1][1]
        else:
            import math

            for (n0, b0), (n1, b1) in zip(_ref_rows, _ref_rows[1:]):
                if n0 <= N <= n1:
                    t = (math.log(N) - math.log(n0)) / (
                        math.log(n1) - math.log(n0)
                    )
                    baseline = math.exp(
                        (1 - t) * math.log(b0) + t * math.log(b1)
                    )
                    break
        # The reference streams full-duplex too, so its 242 f/s row is a
        # PER-DIRECTION number: the honest headline ratio compares one
        # direction to it (or the mean of both), never the bidirectional
        # sum (VERDICT r04 Weak #1).
        per_dir = {
            "vs_baseline_out": round(equiv_out / baseline, 2),
            "vs_baseline_in": round(equiv_in / baseline, 2),
        }
        out = {
            "metric": "e2e_host_sync",
            # compat rows must be distinguishable from native-framing rows
            # (same rule as engine_bench.py / soak.py): C child implies the
            # reference protocol too
            "wire": "compat" if (COMPAT or CHILD == "c") else "native",
            "n": N,
            "seconds": round(dt, 2),
            "backend": backend,
            "on_tpu": on_tpu,
            "frames_out_per_s": round(frames_out, 1),
            "frames_in_per_s": round(frames_in, 1),
            "wire_out_GBps": round(wire_out / 1e9, 4),
            "wire_in_GBps": round(wire_in / 1e9, 4),
            "equiv_out_GBps": round(equiv_out / 1e9, 3),
            "equiv_in_GBps": round(equiv_in / 1e9, 3),
            "baseline_equiv_GBps": round(baseline / 1e9, 3),
            # fair average of the two per-direction ratios — the headline
            **per_dir,
            "vs_baseline": round((equiv_out + equiv_in) / 2 / baseline, 2),
        }
        print(json.dumps(out), flush=True)
    finally:
        proc.kill()
        peer.close()
        # the TPU plugin's background threads can abort during interpreter
        # teardown (harmless but noisy); the JSON line is already out
        os._exit(0)


if __name__ == "__main__":
    main()
