"""Calibrated-latency proof of the device K-frame burst (verdict r03 item 3).

The real tunneled device link pays ~8 ms per blocking device->host fetch,
which capped the round-3 E2E tpu_parent arm at 109 f/s at ANY pipeline
depth (E2E_r03.json: depth scaling 6.7 -> 45 -> 109 plateaued — every
frame still costs one fetch round trip). The device burst quantizes K
successive halvings in ONE dispatch and fetches them with ONE device_get,
so a high-latency link carries K frames per round trip.

With the tunnel down, this bench injects the MEASURED latency instead:
the parent runs the XLA device tier (ST_HOST_CODEC=xla pins it on the CPU
backend — same code path the TPU parent takes, minus the chip) with
jax.device_get wrapped to add the calibrated per-fetch delay, and measures
delivered frames/s for burst=1 vs burst=K. What it proves: the burst
multiplies frames-per-round-trip exactly as designed; what it cannot
prove: tunnel BANDWIDTH effects at K x frame-size fetches (noted in the
artifact; the real-chip E2E re-run captures that when the tunnel heals).

Emits one JSON line. Run: python benchmarks/device_burst_bench.py
"""

import json
import multiprocessing as mp
import os
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# N sets the emulated compute:latency ratio. The REAL chip quantizes 1 Mi
# in ~0.07 ms (PROFILE_r03) against the ~8 ms tunnel round trip — compute
# is negligible, latency dominates. XLA-CPU quantize costs ~13 ms at 1 Mi
# (it would swamp the injected delay and the harness would measure compute,
# not amortization); 64 Ki puts XLA-CPU quantize at ~0.8 ms << 8 ms — the
# same latency-dominated regime the chip sits in, slightly conservative.
N = int(os.environ.get("ST_DBB_N", str(1 << 16)))
FETCH_DELAY_S = float(os.environ.get("ST_DBB_DELAY", "0.008"))
MEASURE_S = float(os.environ.get("ST_DBB_SECONDS", "10"))
BURSTS = [1, 16]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child(port, done):
    # plain host-tier CPU peer (the fast side, like the reference's CPU
    # child under a TPU parent)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from shared_tensor_tpu import create_or_fetch

    peer = create_or_fetch(
        "127.0.0.1", port, {"t": np.zeros(N, np.float32)}, timeout=60.0
    )
    done.wait(timeout=MEASURE_S + 120)
    peer.close()


def _parent(port, burst, q):
    os.environ["ST_HOST_CODEC"] = "xla"  # pin the device tier (engine off)
    import jax

    jax.config.update("jax_platforms", "cpu")

    # calibrated tunnel: every blocking fetch pays the measured round trip
    real_get = jax.device_get

    def delayed_get(x):
        time.sleep(FETCH_DELAY_S)
        return real_get(x)

    jax.device_get = delayed_get
    import shared_tensor_tpu.core as core

    core.jax.device_get = delayed_get

    import numpy as np

    from shared_tensor_tpu import create_or_fetch
    from shared_tensor_tpu.config import Config

    cfg = Config(device_frame_burst=burst)
    peer = create_or_fetch(
        "127.0.0.1", port, {"t": np.zeros(N, np.float32)}, cfg, timeout=60.0
    )
    assert peer._engine is None and not peer.st.host_tier
    rng = np.random.default_rng(0)
    delta = {"t": rng.normal(size=N).astype(np.float32) * 1e-2}
    deadline = time.time() + 60
    while not peer.node.links and time.time() < deadline:
        time.sleep(0.05)
    t_add_end = time.time() + MEASURE_S + 3
    f0 = peer.st.frames_out
    t0 = time.time()
    t_meas_end = t0 + MEASURE_S
    fps = 0.0
    while time.time() < t_add_end:
        peer.add(delta)  # keep residual mass alive
        time.sleep(0.1)
        if time.time() >= t_meas_end and fps == 0.0:
            fps = (peer.st.frames_out - f0) / (time.time() - t0)
    q.put({"burst": burst, "frames_out_per_s": round(fps, 1)})
    peer.close()


def run_arm(burst: int) -> dict:
    port = _free_port()
    q = mp.Queue()
    done = mp.Event()
    pp = mp.Process(target=_parent, args=(port, burst, q))
    pc = mp.Process(target=_child, args=(port, done))
    pp.start()
    time.sleep(1.0)
    pc.start()
    out = q.get(timeout=MEASURE_S + 180)
    done.set()
    pp.join(timeout=30)
    pc.join(timeout=30)
    return out


def main() -> None:
    mp.set_start_method("spawn")
    arms = [run_arm(b) for b in BURSTS]
    base = arms[0]["frames_out_per_s"]
    k = BURSTS[-1]
    # Projection to the chip's 1 Mi row — ARITHMETIC, not a measurement:
    # frames per fetch cycle / (tunnel RTT + K x on-chip quantize time).
    # On-chip 1 Mi quantize is ~0.07 ms (PROFILE_r03); the r03 plateau
    # pins the RTT at ~1/109 s. Needs the real chip to confirm (tunnel
    # bandwidth at Kx-size fetches is not modeled).
    chip_quantize_s = 0.00007
    rtt_s = 1.0 / 109.0
    projected = k / (rtt_s + k * chip_quantize_s)
    out = {
        "bench": "device_burst_calibrated",
        "n": N,
        "fetch_delay_ms": FETCH_DELAY_S * 1e3,
        "arms": arms,
        "speedup": round(arms[-1]["frames_out_per_s"] / max(base, 1e-9), 2),
        "projected_1mi_fps_on_chip": round(projected, 1),
        "projected_vs_reference_1mi": round(projected / 242.0, 2),
        "note": (
            "XLA device tier + injected per-fetch delay calibrated to the "
            "measured tunnel round trip (~8 ms; r03 tpu_parent plateaued "
            "at 109 f/s — matching this harness's burst=1 arm). The "
            "MEASURED claim is the speedup (K frames per round trip) in "
            "the chip's latency-dominated regime; frames here are 64 Ki, "
            "NOT comparable 1:1 to the reference's 1 Mi E2E row. The "
            "projected_* fields are arithmetic from measured RTT + "
            "on-chip quantize time and need the real chip to confirm."
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
