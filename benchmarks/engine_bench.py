"""Native-engine steady-state throughput (2-process loopback, host tier).

The round-3 gap this measures (verdict item 2): at 4 Ki elements the Python
peer engine delivered ~8.8 k frames/s against the reference C loop's 78 k
(reference src/sharedtensor.c:133-189; BASELINE.md E2E table). The native
engine (native/stengine.cpp) moves the whole steady-state cycle into C;
this bench drives a master (adds fresh deltas continuously, so links never
idle) and one child, and reports the child's delivered frames/s + the
equivalent applied-fp32-delta bandwidth per size.

Emits one JSON line. Run: JAX_PLATFORMS=cpu python benchmarks/engine_bench.py
"""

import json
import multiprocessing as mp
import os
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZES = [
    int(x)
    for x in os.environ.get(
        "ST_ENGINE_BENCH_SIZES", f"4096,65536,{1 << 20}"
    ).split(",")
]
MEASURE_S = float(os.environ.get("ST_ENGINE_BENCH_S", "8"))
#: Master add() cadence. An add is O(n) host work (values + residual, ~2
#: full-table passes); a fixed 2 ms period at 16 Mi saturates the core on
#: adds and measures add-flooded — not steady-state — codec throughput.
#: Scale with n like e2e_sync.py: fast enough that residual mass never
#: quiesces (drain needs ~30 successive halvings), slow enough that the
#: codec stream owns the core.
def _add_period(n: int) -> float:
    # r11: the cascade quantizer drains a residual in ~tens of frames and
    # idles (instead of free-running a junk tail), so "fast enough that
    # residual mass never quiesces" now means ~1 ms at 1 Mi (measured:
    # 1 ms saturates the cascade-32 pass loop — ~74 GB/s equiv after the
    # TxPool warm fix — while 4 ms starves the wire to a fraction of
    # that; the add itself is 2 fused table passes, ~0.3 ms, still well
    # under the period).
    return max(0.001, n / (1 << 20) * 0.001)
#: ST_ENGINE_BENCH_COMPAT=1 runs both peers on the reference's raw wire
#: protocol (engine compat data plane, K-frame compat bursts) — the
#: saturation measurement behind the "faster than the reference at its own
#: protocol" claim.
COMPAT = os.environ.get("ST_ENGINE_BENCH_COMPAT", "0") == "1"


def _force_cpu():
    # The env var alone cannot demote the platform on the real-chip box (the
    # site hook pins the TPU plugin at interpreter start, before this runs);
    # the config update works as long as no backend is initialized yet —
    # same pattern as e2e_sync.py.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


#: r11 link striping: sockets per logical link for the native arm
#: (ST_ENGINE_BENCH_STRIPES; the stripe sweep drives this 1/2/4).
#: Default 1 since r14: the same-host shm lane is the loopback data plane
#: now — extra TCP stripes only add idle keepalive threads beneath it
#: (ENGINE_SWEEP_r14 carries the shm-vs-2-stripe-TCP comparison; run the
#: TCP arms with ST_SHM=0).
STRIPES = int(os.environ.get("ST_ENGINE_BENCH_STRIPES", "1"))
#: r11 cascade depth (frames quantized per memory pass; 0 = the
#: CodecConfig default). The sweep knob behind the committed retune.
CASCADE = int(os.environ.get("ST_ENGINE_BENCH_CASCADE", "0"))


def _cfg():
    from shared_tensor_tpu.config import CodecConfig, Config, TransportConfig

    if COMPAT:
        return Config(
            transport=TransportConfig(peer_timeout_sec=30.0, wire_compat=True)
        )
    codec = CodecConfig(cascade_frames=CASCADE) if CASCADE > 0 else None
    return Config(
        transport=TransportConfig(
            peer_timeout_sec=30.0,
            stripe_count=max(1, min(8, STRIPES)),
        ),
        **({"codec": codec} if codec else {}),
    )


def _master(n, port, q, done: "mp.Event"):
    _force_cpu()
    import numpy as np

    from shared_tensor_tpu import create_or_fetch

    peer = create_or_fetch(
        "127.0.0.1", port, {"w": np.zeros(n, np.float32)}, _cfg()
    )
    rng = np.random.default_rng(0)
    delta = {"w": rng.standard_normal(n).astype(np.float32)}
    # keep streaming until the child reports its window closed — a fixed
    # wall budget understates fps when child spawn/join runs long on a
    # loaded box (the master would exit mid-measurement)
    t_bail = time.time() + MEASURE_S + 120
    period = float(
        os.environ.get("ST_ENGINE_BENCH_ADD_PERIOD", str(_add_period(n)))
    )
    while not done.is_set() and time.time() < t_bail:
        peer.add(delta)
        time.sleep(period)
    q.put(("master", peer._engine is not None))
    peer.close()


def _child(n, port, q, done: "mp.Event"):
    _force_cpu()
    import numpy as np

    from shared_tensor_tpu import create_or_fetch

    peer = create_or_fetch(
        "127.0.0.1", port, {"w": np.zeros(n, np.float32)}, _cfg()
    )
    # Open the measure window only once frames actually flow: a fixed sleep
    # undershoots on a loaded box (large-n join state transfer can outlast
    # it, measuring zero) and silently folds startup into the rate.
    # 25 s: ample for the join transfer even 10x contended, yet short enough
    # that bench.py's engine arm (timeout >= 30 s) still sees the fail-fast
    # "no frames" diagnostic instead of SIGKILLing a still-waiting child.
    deadline = time.time() + 25
    while peer.st.frames_in == 0 and time.time() < deadline:
        time.sleep(0.1)
    time.sleep(0.5)  # settle just past the first delivery
    f0, t0 = peer.st.frames_in, time.time()
    time.sleep(MEASURE_S)
    f1, t1 = peer.st.frames_in, time.time()
    done.set()  # release the master only after the window closed
    fps = (f1 - f0) / (t1 - t0)
    q.put(
        (
            "child",
            {
                "frames_in_per_s": round(fps, 1),
                "equiv_fp32_GBps": round(fps * n * 4 / 1e9, 3),
                "engine": peer._engine is not None,
            },
        )
    )
    peer.close()


def _free_port() -> int:
    # ephemeral-bind then release (e2e_sync.py pattern): a fixed scheme can
    # land on an occupied port, where create_or_fetch silently JOINS the
    # squatter's tree instead of creating a fresh table
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_size(n: int) -> dict:
    port = _free_port()
    q = mp.Queue()
    done = mp.Event()
    pm = mp.Process(target=_master, args=(n, port, q, done))
    pc = mp.Process(target=_child, args=(n, port, q, done))
    pm.start()
    time.sleep(1.0)
    pc.start()
    out = {}
    for _ in range(2):
        who, data = q.get(timeout=MEASURE_S + 150)
        out[who] = data
    pm.join(timeout=30)
    pc.join(timeout=30)
    row = dict(out["child"])
    row["master_engine"] = bool(out["master"])
    row["n"] = n
    return row


def main() -> None:
    mp.set_start_method("spawn")
    rows = [run_size(n) for n in SIZES]
    ref = {4096: 78000.0, 65536: None, 1 << 20: 242.0}
    for r in rows:
        if ref.get(r["n"]):
            r["vs_reference_e2e"] = round(r["frames_in_per_s"] / ref[r["n"]], 2)
    print(
        json.dumps(
            {
                "bench": "engine_steady_state",
                "tier": "host-native-engine",
                # compat runs must be distinguishable from native rows: a
                # 155 k f/s compat measurement pasted as a native row (or
                # vice versa) would silently mislabel the artifact
                "wire": "compat" if COMPAT else "native",
                "measure_s": MEASURE_S,
                "rows": rows,
                "reference": "BASELINE.md E2E loopback table "
                "(78 k f/s @4Ki, 242 f/s @1Mi)",
            }
        )
    )


if __name__ == "__main__":
    main()
