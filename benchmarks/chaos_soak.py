"""Chaos soak: peers training under a randomized fault schedule (r06).

The deterministic fault layer (comm/faults.py) drives every recovery path
this framework claims over the reference's exit(-1) — in one continuous
run, on BOTH data planes:

- **python arm** — a master plus three Python-tier joiners, each with a
  seeded :class:`FaultConfig` drawn from a randomized (but seeded, so the
  whole soak replays) schedule: one link drops/duplicates/delays frames,
  one bit-corrupts and truncates them, one stalls and then severs its
  uplink mid-stream (forced carry re-graft).
- **native arm** — a master plus two native-engine joiners, one created
  under the ``ST_FAULT_PLAN`` env hook table so the C transport's sender
  loop injects the same fault classes (drop, stall, sever) below Python.

Every peer "trains": it adds structured deltas on its own cadence for the
whole window while the chaos runs; the chaos window ends WITH training
(injection is then disabled, like soak.py stopping its churn), and the
recovery machinery must repair everything the chaos stranded. Because the
soak is in-process, the exact expected state (seed + every delta) is
known, so the final check is the delivery contract itself, not a
statistical smell test:

- **convergence-within-bound**: with the r06 go-back-N wire discipline
  (comm/wire.py tx_seq), drop / duplicate / truncate / stall / delay and
  sever-into-carry all recover EXACTLY; the only fault class that may
  leave a residue is bit-corruption, which mis-applies at most one
  element by 2*scale per corrupted message (the flip lands in the sign
  words; scales for these unit-range deltas stay O(1)). The documented
  bound is therefore ``atol + 4.0 * corrupted_messages`` per element —
  chaos-proportional, not a fudge factor: a schedule that corrupts
  nothing must converge to float exactness.
- **zero wedged threads**: after drain + close of every peer, no ``st-*``
  daemon thread may survive — the round-5 failure mode (a dead recv
  thread wedging a peer forever) is exactly what this asserts away.

Emits one JSON line. Run:  python benchmarks/chaos_soak.py > CHAOS_r06.json
Knobs: ST_CHAOS_SECONDS (per arm, default 40), ST_CHAOS_SEED (default 6),
ST_CHAOS_ARMS (comma list, default "python,native" — the sanitizer harness
runs a single arm under ASan+UBSan, tests/test_sanitizers.py).
"""

import json
import os
import socket
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

N = int(os.environ.get("ST_CHAOS_N", "512"))
SECONDS = float(os.environ.get("ST_CHAOS_SECONDS", "40"))
SEED = int(os.environ.get("ST_CHAOS_SEED", "6"))
ARMS = tuple(
    a.strip()
    for a in os.environ.get("ST_CHAOS_ARMS", "python,native").split(",")
    if a.strip()
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _st_threads() -> set:
    return {
        t for t in threading.enumerate()
        if t.name.startswith("st-") and t.is_alive()
    }


def _train(peer, np, jnp, rng, stop, contrib_lock, contrib):
    """One peer's training loop: structured deltas (linspace converges
    exactly; Gaussian tails oscillate forever at the +/-scale floor),
    tracked exactly under the lock so the soak knows the true sum."""
    while not stop.is_set():
        lo, hi = sorted(rng.uniform(-0.5, 0.5, size=2))
        d = np.linspace(lo, hi, N, dtype=np.float32)
        peer.add(jnp.asarray(d))
        with contrib_lock:
            contrib += d.astype(np.float64)
        stop.wait(0.1)
    return contrib


#: FaultPlan.counts key -> obs timeline event name (obs/events.py): the
#: accounting bridge between "what the injector says it did" and "what the
#: flight recorder saw" — the r08 acceptance bar is that these MATCH.
_FAULT_EVENT_OF = {
    "dropped": "fault_drop",
    "duplicated": "fault_dup",
    "delayed": "fault_delay",
    "corrupted": "fault_corrupt",
    "truncated": "fault_truncate",
    "stalled": "fault_stall",
    "severed": "fault_sever",
}


def _run_arm(arm: str, np, jnp, rng) -> dict:
    from shared_tensor_tpu import obs
    from shared_tensor_tpu.comm import faults
    from shared_tensor_tpu.comm.peer import SharedTensorPeer, create_or_fetch
    from shared_tensor_tpu.config import Config, FaultConfig, TransportConfig

    native = arm == "native"
    # fresh timeline for this arm: flush stale native events, zero counts,
    # and baseline the (process-cumulative) ring-overflow counter so this
    # arm's clean-ring check measures ITS OWN delta, not an earlier arm's
    from shared_tensor_tpu.obs import events as obs_events

    hub = obs.hub()
    hub.poll_native()
    hub.recorder.clear()
    ring_dropped_base = obs_events.native_dropped()

    def cfg(fault=None):
        return Config(
            transport=TransportConfig(
                peer_timeout_sec=30.0, ack_timeout_sec=1.0
            ),
            faults=fault or FaultConfig(),
            native_engine=native,
        )

    port = _free_port()
    seed_state = jnp.zeros((N,), jnp.float32)
    master = create_or_fetch("127.0.0.1", port, seed_state, cfg())
    peers = [master]
    plans = []
    env_schedule = None
    if native:
        # chaotic C-tier joiner: the env table is parsed per st_node_create,
        # so only this node's transport injects (drop + stall + sever on
        # its first uplink -> go-back-N retransmission, then black-hole
        # teardown / sever -> rollback -> carry -> re-graft, all in C)
        env = faults.to_env(FaultConfig(
            enabled=True, seed=SEED, drop_pct=float(rng.uniform(0.1, 0.3)),
            stall_after_frames=int(rng.integers(20, 40)),
            sever_after_frames=int(rng.integers(45, 60)), only_link=1,
        ))
        env_schedule = env["ST_FAULT_PLAN"]
        os.environ.update(env)
        try:
            peers.append(SharedTensorPeer(
                "127.0.0.1", port, jnp.zeros((N,), jnp.float32), cfg()
            ))
        finally:
            for k in env:
                os.environ.pop(k, None)
        peers.append(SharedTensorPeer(
            "127.0.0.1", port, jnp.zeros((N,), jnp.float32), cfg()
        ))
    else:
        schedules = [
            FaultConfig(  # lossy link: drop + duplicate + delay
                enabled=True, seed=SEED + 1,
                drop_pct=float(rng.uniform(0.1, 0.3)),
                dup_pct=float(rng.uniform(0.05, 0.2)),
                delay_pct=float(rng.uniform(0.1, 0.3)), delay_sec=0.003,
            ),
            FaultConfig(  # corrupting link: bit flips + truncation
                enabled=True, seed=SEED + 2,
                corrupt_pct=float(rng.uniform(0.05, 0.15)),
                truncate_pct=float(rng.uniform(0.05, 0.15)),
            ),
            FaultConfig(  # stalled-then-severed uplink: forced carry
                enabled=True, seed=SEED + 3,
                stall_after_frames=int(rng.integers(10, 25)),
                sever_after_frames=int(rng.integers(30, 45)), only_link=1,
            ),
        ]
        for fc in schedules:
            p = SharedTensorPeer(
                "127.0.0.1", port, jnp.zeros((N,), jnp.float32), cfg(fc)
            )
            peers.append(p)
            plans.append(p._faults)
    for p in peers[1:]:
        p.wait_ready(60.0)

    stop = threading.Event()
    lock = threading.Lock()
    contribs = [np.zeros(N, np.float64) for _ in peers]
    trainers = [
        threading.Thread(
            target=_train,
            args=(p, np, jnp, np.random.default_rng(SEED + 10 + i), stop,
                  lock, contribs[i]),
            daemon=True, name=f"chaos-train-{i}",
        )
        for i, p in enumerate(peers)
    ]
    for t in trainers:
        t.start()
    time.sleep(SECONDS)
    stop.set()
    for t in trainers:
        t.join(timeout=30.0)
    trainers_ok = all(not t.is_alive() for t in trainers)

    # End of the chaos window: DETACH the plans first, then harvest their
    # injected-event tallies (in this order — the peers' free-running send
    # loops keep dripping residual frames through an attached plan, so a
    # harvest-then-detach would let late hits land in the flight recorder
    # but not in `injected`, flaking the r08 exact-accounting check), then
    # quiesce (soak.py stops its churn the same way). The recovery
    # machinery must now repair EVERYTHING the chaos stranded — under
    # NONSTOP injection a drain-to-zero would race the fault schedule
    # itself (each repair round can be re-faulted, with go-back-N backoff
    # stretching the tail), which tests the schedule's patience, not the
    # delivery contract.
    for p in peers:
        p._faults = None
    # settle the detach: a send thread that loaded the plan just before
    # the None landed may still be inside on_send; its hit lands in both
    # tallies, which is fine — the harvest happens AFTER drain+settle,
    # adjacent to the recorder read (see the obs verdict below)
    time.sleep(0.5)
    # quiesce: every peer drains what it still owes (retransmission clears
    # fault-stranded ledgers; severed links re-graft and redeliver)
    drains_ok = sum(1 for p in peers if p.drain(timeout=120.0, tol=1e-30))
    # settle: flood until the tree stops changing
    settle_end = time.time() + 30.0
    prev = None
    while time.time() < settle_end:
        cur = np.asarray(master.read()).copy()
        if prev is not None and np.array_equal(cur, prev):
            break
        prev = cur
        time.sleep(1.0)

    expected = sum(contribs)
    dev = 0.0
    spread = 0.0
    base = np.asarray(master.read(), np.float64)
    for p in peers:
        v = np.asarray(p.read(), np.float64)
        dev = max(dev, float(np.abs(v - expected).max()))
        spread = max(spread, float(np.abs(v - base).max()))

    # r08 obs verdict: drain the native ring one last time, then check the
    # merged timeline accounts for every injected fault event. Python arm:
    # the injector's own tallies must EQUAL the recorder's per-name totals
    # (the plans emit one timeline event per hit, under the same plan
    # lock — harvesting BOTH sides here, at the same long-quiesced
    # instant, is what makes the equality exact; an early harvest left a
    # minutes-wide window where a straggler hit landed in one tally only).
    # Native arm: the C injector IS the emitter, so the bar is presence of
    # every configured class (drop + stall + sever rode ST_FAULT_PLAN)
    # with a clean ring (no overflow drops — else counts are lower
    # bounds, not accounting).
    injected = {
        k: int(sum(pl.counts[k] for pl in plans if pl is not None))
        for k in (
            "dropped", "duplicated", "delayed", "corrupted", "truncated",
            "stalled", "severed",
        )
    }
    corrupted = injected["corrupted"]
    # documented +/-scale bound (module docstring): only corruption leaves
    # a residue, <= 2*scale per corrupted message with O(1) scales here
    bound = 0.05 + 4.0 * corrupted
    hub.poll_native()
    ring_dropped = obs_events.native_dropped() - ring_dropped_base
    ev_counts = {k: int(hub.recorder.counts[k]) for k in _FAULT_EVENT_OF.values()}
    if plans:
        obs_accounted = all(
            ev_counts[_FAULT_EVENT_OF[k]] == injected[k] for k in injected
        )
    else:
        obs_accounted = (
            ev_counts["fault_drop"] > 0
            and ev_counts["fault_stall"] > 0
            and ev_counts["fault_sever"] >= 1
            and ring_dropped == 0
        )
    timeline = hub.recorder.timeline()
    tiers = sorted({e.tier for e in timeline})
    # the postmortem dump is the artifact the acceptance bar asks for: the
    # last-N merged events + every peer registry, written like a real
    # crash would write it
    dump_path = hub.dump(f"chaos_soak_{arm}", min_interval_sec=0.0)
    dump_ok = False
    if dump_path:
        try:
            with open(dump_path) as f:
                doc = json.load(f)
            dump_ok = (
                doc["reason"] == f"chaos_soak_{arm}"
                and len(doc["timeline"]) > 0
                and all(
                    doc["event_counts"].get(n, 0) == ev_counts[n]
                    for n in ev_counts
                )
            )
        except (OSError, ValueError, KeyError):
            dump_ok = False

    for p in peers:
        p.close()
    deadline = time.time() + 15.0
    while time.time() < deadline and _st_threads():
        time.sleep(0.2)
    wedged = sorted(t.name for t in _st_threads())

    result = {
        "peers": len(peers),
        # python arm: per-class event tallies from the FaultPlans; native
        # arm: the injection runs in the C transport below Python (no
        # counters exported), so the configured ST_FAULT_PLAN schedule is
        # recorded instead
        "faults_injected": injected if plans else None,
        "native_env_schedule": env_schedule,
        "trainers_joined": trainers_ok,
        "final_drains_ok": f"{drains_ok}/{len(peers)}",
        "max_dev_vs_expected": dev,
        "cross_replica_spread": spread,
        "dev_bound": bound,
        "wedged_threads": wedged,
        # r08 flight-recorder accounting (see the obs verdict block above)
        "obs": {
            "fault_event_counts": ev_counts,
            "accounted": obs_accounted,
            "timeline_events": len(timeline),
            "timeline_tiers": tiers,
            "native_ring_dropped": ring_dropped,
            "postmortem": dump_path,
            "postmortem_ok": dump_ok,
        },
        "pass": bool(
            trainers_ok
            and drains_ok == len(peers)
            and dev <= bound
            and spread <= bound
            and not wedged
            and obs_accounted
            and dump_ok
            and tiers == ["c", "py"]
        ),
    }
    return result


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(SEED)
    arms = {arm: _run_arm(arm, np, jnp, rng) for arm in ARMS}
    out = {
        "bench": "chaos_soak",
        "n": N,
        "seconds_per_arm": SECONDS,
        "seed": SEED,
        "arms": arms,
        "pass": all(a["pass"] for a in arms.values()),
    }
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
