"""Host-tier codec microbench: M elem/s per core for the native C hot loops.

The reference's codec measures 202 M elem/s on one core of this box class
(BASELINE.md, probe replicating src/sharedtensor.c:106-111,153-174); the host
tier's throughput hangs on these same loops (ops/codec_np.py dispatches to
native/stcodec.c). Prints one JSON line per op with elem/s and the
vs-reference ratio at matched work (quantize = RMS pass + sign/pack/feedback
pass; apply = unpack+accumulate pass).

Usage: python benchmarks/host_codec_bench.py [--n 1048576] [--reps 50]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1 << 20)
    ap.add_argument("--reps", type=int, default=50)
    args = ap.parse_args()

    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.ops import codec_np
    from shared_tensor_tpu.ops.table import make_spec

    lib = codec_np._native()
    n = args.n
    spec = make_spec(np.zeros(n, np.float32))
    rng = np.random.default_rng(0)
    resid = rng.uniform(-1.0, 1.0, n).astype(np.float32)

    def timeit(fn, reps):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        return (time.perf_counter() - t0) / reps

    t_q = timeit(
        lambda: codec_np.quantize_table_np(resid, spec, ScalePolicy.POW2_RMS),
        args.reps,
    )
    scales, words, _ = codec_np.quantize_table_np(resid, spec)
    values = rng.uniform(-1.0, 1.0, n).astype(np.float32)
    t_a = timeit(
        lambda: codec_np.apply_table_many_np((values,), scales, words, spec),
        args.reps,
    )
    ref_meps = 202.0  # BASELINE.md: quantize+apply fused, 1 core
    for op, t in (("quantize", t_q), ("apply", t_a)):
        meps = n / t / 1e6
        print(
            json.dumps(
                {
                    "op": op,
                    "n": n,
                    "ms": round(t * 1e3, 3),
                    "meps": round(meps, 1),
                    "native": lib is not None,
                    "vs_ref_202meps": round(meps / ref_meps, 2),
                }
            ),
            flush=True,
        )


if __name__ == "__main__":
    main()
