"""BASELINE config 5: dense-tensor sweep — approximation-error vs
sync-bandwidth Pareto.

For each table size, measures (a) the fused codec roundtrip rate on the chip
(long-chain device-side timing, utils/timing.py) giving equivalent-fp32-delta
GB/s per link at 1 bit/element/frame wire cost, and (b) the measured residual-RMS
decay per frame on uniform data — the matched-approximation-error yardstick
(the reference halves residual RMS each frame on homogeneous data,
BASELINE.md convergence table; the codec here is bit-identical, and this
sweep re-measures rather than assumes it).

Prints one JSON line per size. The reference crashes past ~60 Mi elements
(stack VLA, SURVEY.md quirk Q6); sizes here are bounded only by HBM.

Usage: python benchmarks/pareto.py [--sizes 20,22,24,26]
"""

from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp

BASELINE_GBPS = {  # reference E2E loopback equiv-delta GB/s (BASELINE.md)
    1 << 12: 1.28,
    1 << 20: 1.01,
    1 << 24: 0.52,
}


def measure_size(codec, n: int, policy) -> dict:
    from shared_tensor_tpu.utils.timing import codec_frame_time

    uniform = lambda seed: jax.random.uniform(
        jax.random.key(seed), (n,), jnp.float32, -1.0, 1.0
    )
    t_frame = codec_frame_time(codec, n, policy, make_residual=uniform)
    equiv_gbps = n * 4 / t_frame / 1e9

    # Error curve: residual RMS per frame on U(-1,1) (matched-error check).
    @jax.jit
    def rms_curve(resid):
        def body(r, _):
            frame, r = codec.quantize(r, n, policy)
            return r, jnp.sqrt(jnp.mean(r * r))
        _, curve = jax.lax.scan(body, resid, None, length=8)
        return curve

    r0 = jax.random.uniform(jax.random.key(7), (n,), jnp.float32, -1.0, 1.0)
    rms0 = float(jnp.sqrt(jnp.mean(r0 * r0)))
    curve = [float(x) for x in jax.device_get(rms_curve(r0))]
    halving = (curve[-1] / rms0) ** (1 / len(curve)) if rms0 else 0.0

    base = BASELINE_GBPS.get(n)
    return {
        "n_elements": n,
        "mbytes": round(n * 4 / 1e6, 1),
        "equiv_gbps": round(equiv_gbps, 2),
        "wire_gbps": round(equiv_gbps / 32, 3),
        "frame_us": round(t_frame * 1e6, 1),
        "rms_decay_per_frame": round(halving, 4),  # reference: 0.5
        "vs_baseline": round(equiv_gbps / base, 1) if base else None,
        "backend": jax.default_backend(),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="12,16,20,24,26")
    ap.add_argument("--policy", default="POW2_RMS")
    args = ap.parse_args()

    from shared_tensor_tpu.config import ScalePolicy
    from shared_tensor_tpu.ops import codec_pallas as codec

    policy = ScalePolicy[args.policy]
    for log2n in (int(s) for s in args.sizes.split(",")):
        print(json.dumps(measure_size(codec, 1 << log2n, policy)), flush=True)


if __name__ == "__main__":
    main()
