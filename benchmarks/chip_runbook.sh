#!/bin/bash
# On-chip artifact runbook: produces the round's on-chip evidence
# (AXON suite groups, 4-arm train bench, headline bench sanity, pareto
# spot-check, device-burst E2E). Run when the axon tunnel is up; see
# AXON_SUITE_r03.txt for the wedge failure modes this script's structure
# avoids. Every step is timeout-wrapped (SIGTERM, never SIGKILL) and
# sequential: exactly ONE process touches the chip at a time (grant-wedge
# avoidance, .claude/skills/verify/SKILL.md).
set -x
cd /root/repo
AX="env ST_TEST_PLATFORM=axon PYTHONPATH=/root/repo:/root/.axon_site"

step() { echo "=== $* ==="; }

step "1/6 device-relevant suite on chip -> AXON groups"
$AX timeout 560 python -m pytest tests/test_codec.py tests/test_codec_pallas.py \
    tests/test_table.py tests/test_table_pallas.py -q 2>&1 | tail -2 | tee /tmp/ax_g1.txt
$AX timeout 560 python -m pytest tests/test_core.py tests/test_checkpoint.py \
    tests/test_trainer.py tests/test_ici.py -q 2>&1 | tail -2 | tee /tmp/ax_g2.txt
$AX timeout 560 python -m pytest tests/test_char_rnn.py tests/test_resnet.py \
    tests/test_codec_np.py tests/test_compat.py tests/test_profiling.py \
    tests/test_wire_robustness.py tests/test_codec.py -q 2>&1 | tail -2 | tee /tmp/ax_g3.txt

step "2/6 train bench (4 arms incl. overlap) -> TRAIN_BENCH_r05.json"
PYTHONPATH=/root/repo:/root/.axon_site ST_TRAIN_BENCH_BUDGET_S=420 \
  timeout 500 python benchmarks/train_bench.py > /tmp/train_bench_r05.json 2>/tmp/tb_err.log
tail -1 /tmp/train_bench_r05.json

step "3/6 headline bench sanity"
PYTHONPATH=/root/repo:/root/.axon_site ST_BENCH_BUDGET_S=300 \
  timeout 380 python bench.py 2>/dev/null | tail -1 | tee /tmp/bench_sanity.json

step "4/6 pareto spot-check (1Mi only, confirms chip state)"
PYTHONPATH=/root/repo:/root/.axon_site timeout 300 \
  python benchmarks/pareto.py --sizes 20 2>/dev/null | tail -1

step "5/6 device-burst E2E on the real tunnel -> E2E_r05 tpu_parent arm"
# The parent runs the real chip (device tier, K-frame bursts by default);
# the child is a CPU host-tier peer. This is the measurement the
# DEVICE_BURST_r04.json projection (~1554 f/s at 1 Mi) stands in for.
PYTHONPATH=/root/repo:/root/.axon_site ST_E2E_SECONDS=20 timeout 300 \
  python benchmarks/e2e_sync.py 2>/dev/null | tail -1 | tee /tmp/e2e_tpu_burst.json
# single-frame comparison arm (burst disabled): should reproduce ~109 f/s
PYTHONPATH=/root/repo:/root/.axon_site ST_E2E_SECONDS=15 timeout 240 \
  env ST_E2E_DEVICE_BURST=1 python benchmarks/e2e_sync.py 2>/dev/null | tail -1

step "6/6 done — assemble artifacts manually (BENCH_r05, TRAIN_BENCH_r05, AXON_SUITE_r05, E2E_r05)"
