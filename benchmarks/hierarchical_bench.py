"""Hierarchical-tier timing: pods over ICI, bridged over the TCP tree.

The composition SURVEY.md §5.8 requires — intra-pod compressed sync plus
the peer-tier tree — has correctness tests (test_hierarchical.py) but no
timing artifact. This measures it end to end: two PROCESSES, each a
4-virtual-device pod training the char-rnn, bridged by the native-engine
peer tier over loopback TCP, exchanging every pod step. Reported:
per-pod steps/s solo vs bridged (the bridge's wall-clock overhead), and
the cross-pod parameter gap after a final exchange+settle (the two pods
train on DIFFERENT data streams; the bridge is what keeps them in the
same model neighborhood).

Emits one JSON line. Run: python benchmarks/hierarchical_bench.py
"""

import json
import multiprocessing as mp
import os
import socket
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

STEPS = int(os.environ.get("ST_HIER_STEPS", "30"))
WARMUP = 3
N_POD = 4


def _env():
    os.environ["JAX_PLATFORMS"] = "cpu"
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={N_POD}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")


def _setup():
    import jax

    from shared_tensor_tpu.models import char_rnn as m
    from shared_tensor_tpu.parallel.mesh import make_mesh

    cfg = m.CharRNNConfig(vocab=96, embed=64, hidden=192, layers=2)
    text = b"the quick brown fox jumps over the lazy dog. " * 400
    mesh = make_mesh(N_POD, 1)
    loss = lambda p, b: m.loss_fn(p, b, cfg)

    def batches(i, seed):
        return m.make_batches(
            text, batch=4, seq=24, key=jax.random.key(seed * 10_000 + i),
            n_peer=N_POD, vocab=cfg.vocab,
        )

    params = m.init_params(jax.random.key(0), cfg)
    return mesh, params, loss, batches


def _solo(q):
    _env()
    import jax

    from shared_tensor_tpu.train import PodTrainer

    mesh, params, loss, batches = _setup()
    tr = PodTrainer(mesh, params, loss)
    for i in range(WARMUP):
        tr.step(tr.shard_batch(batches(i, 0)), lr=0.1)
    jax.block_until_ready(tr.state.values)
    t0 = time.perf_counter()
    for i in range(STEPS):
        losses, _ = tr.step(tr.shard_batch(batches(i, 0)), lr=0.1)
    jax.block_until_ready(tr.state.values)
    q.put(("solo", STEPS / (time.perf_counter() - t0)))


def _get_checked(q, procs, timeout):
    """q.get that fails fast with diagnostics when a child dies unreported
    (a bare 900 s block on a crashed pod hides the actual failure)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            return q.get(timeout=5)
        except Exception:
            dead = [p for p in procs if not p.is_alive() and p.exitcode not in (0, None)]
            if dead:
                raise RuntimeError(
                    f"child died: exitcodes {[p.exitcode for p in dead]}"
                )
    raise TimeoutError(f"no result within {timeout}s")


def _reap(procs):
    """Join children and guarantee none leak into the next arm's timing."""
    for p in procs:
        p.join(timeout=60)
        if p.is_alive():
            p.kill()
            p.join(timeout=10)


def _pod(rank, port, q, done, peer_drained, other_drained, sync_every=1):
    _env()
    import jax
    import numpy as np

    from shared_tensor_tpu.train.hierarchical import HierarchicalTrainer

    mesh, params, loss, batches = _setup()
    tr = HierarchicalTrainer.create(
        mesh, "127.0.0.1", port, params, loss, sync_every=sync_every,
        timeout=60.0,
    )
    if rank == 0:
        # rank 0 bound port 0: tell the coordinator the real port so rank 1
        # can join — no bind-close-rebind TOCTOU window
        q.put(("port", tr.peer.node.listen_port))
    for i in range(WARMUP):
        tr.step(tr.pod.shard_batch(batches(i, rank)), lr=0.1)
    jax.block_until_ready(tr.pod.state.values)
    t0 = time.perf_counter()
    for i in range(STEPS):
        tr.step(tr.pod.shard_batch(batches(i, rank)), lr=0.1)
    jax.block_until_ready(tr.pod.state.values)
    sps = STEPS / (time.perf_counter() - t0)
    # settle: push the tail, drain, then BARRIER on the sibling having
    # drained too before the final pull — otherwise a fast pod reads its
    # mean while the slow pod's tail deltas are still in flight and the
    # reported gap flakes by ~one training delta
    for _ in range(5):
        tr.exchange()
        time.sleep(0.5)
    tr.peer.drain(timeout=30.0, tol=1e-30)
    peer_drained.set()
    other_drained.wait(timeout=120)
    time.sleep(1.0)
    tr.exchange()
    time.sleep(0.5)
    tr.exchange()
    mean = np.asarray(jax.device_get(tr._pod_mean()))
    q.put((f"pod{rank}", {"steps_per_s": round(sps, 3), "mean": mean}))
    done.wait(timeout=120)
    tr.close()


def main() -> None:
    mp.set_start_method("spawn")
    q = mp.Queue()
    sp = mp.Process(target=_solo, args=(q,))
    sp.start()
    _, solo_sps = _get_checked(q, [sp], 600)
    _reap([sp])

    # contention-only arm: TWO unbridged pods sharing the box — separates
    # core contention (which the bridged arm also pays) from bridge cost
    d0 = mp.Process(target=_solo, args=(q,))
    d1 = mp.Process(target=_solo, args=(q,))
    d0.start()
    d1.start()
    duals = [_get_checked(q, [d0, d1], 900)[1] for _ in range(2)]
    _reap([d0, d1])
    dual_sps = min(duals)

    def bridged_arm(sync_every):
        done = mp.Event()
        dr0, dr1 = mp.Event(), mp.Event()
        # rank 0 binds port 0 itself and reports the kernel-assigned port
        p0 = mp.Process(target=_pod, args=(0, 0, q, done, dr0, dr1, sync_every))
        p0.start()
        who, port = _get_checked(q, [p0], 300)
        assert who == "port", who
        p1 = mp.Process(
            target=_pod, args=(1, port, q, done, dr1, dr0, sync_every)
        )
        p1.start()
        out = {}
        for _ in range(2):
            who, data = _get_checked(q, [p0, p1], 900)
            out[who] = data
        done.set()
        _reap([p0, p1])
        return out

    out = bridged_arm(1)
    out8 = bridged_arm(8)

    import numpy as np

    gap = float(np.abs(out["pod0"]["mean"] - out["pod1"]["mean"]).max())
    scale = float(np.abs(out["pod0"]["mean"]).max())
    bridged = min(out["pod0"]["steps_per_s"], out["pod1"]["steps_per_s"])
    bridged8 = min(out8["pod0"]["steps_per_s"], out8["pod1"]["steps_per_s"])
    gap8 = float(np.abs(out8["pod0"]["mean"] - out8["pod1"]["mean"]).max())
    print(
        json.dumps(
            {
                "bench": "hierarchical_two_tier",
                "pods": 2,
                "devices_per_pod": N_POD,
                "steps": STEPS,
                "solo_steps_per_s": round(solo_sps, 3),
                "dual_unbridged_steps_per_s": round(dual_sps, 3),
                "bridged_every_step_steps_per_s": round(bridged, 3),
                "bridged_every_8_steps_per_s": round(bridged8, 3),
                "contention_pct": round(100 * (1 - dual_sps / solo_sps), 1),
                "bridge_overhead_pct_every_step": round(
                    100 * (1 - bridged / dual_sps), 1
                ),
                "bridge_overhead_pct_every_8": round(
                    100 * (1 - bridged8 / dual_sps), 1
                ),
                "cross_pod_param_gap_every_step": round(gap, 6),
                "cross_pod_param_gap_every_8": round(gap8, 6),
                "param_magnitude": round(scale, 3),
                "note": (
                    "two 4-device pods training char-rnn on DIFFERENT data "
                    "streams, bridged by the native-engine peer tier over "
                    "TCP with an exchange every pod step; the dual arm "
                    "(two UNbridged pods sharing the box) isolates core "
                    "contention, so bridge_overhead_pct_vs_dual is the "
                    "bridge protocol's own cost"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
