#!/usr/bin/env python
"""Fleet-health acceptance bench (r18 tentpole evidence -> CHAOS_r18.json).

Three phases, each a fresh loopback fleet, each producing a pass/fail
verdict the suite gate reads:

- **heat** — a 7-node SHARDED fleet (python tier, 4 shards: nodes 0-3
  own one each, nodes 4-6 are shardless writers) under zipf-skewed
  writes (~79% of the writes land in one shard). The root's health
  analyzer must NAME that shard (``heat.hot_shard``) within 3 digest
  beats of shard telemetry first reaching it.
- **slo** — a 7-node full-replica peer tree, every node writing, with a
  staleness SLO of 3 s (5% budget, page = 2x burn over 6 s/1 s
  windows). The sender is paced (``sync_interval_sec=0.5``) so the
  go-back-N window drains between frame cuts — an unpaced python-tier
  sender keeps the window full and end-to-end generation latency
  (which IS what the live-aged staleness gauge reports) sits at many
  seconds even on a healthy fleet. Paced, the steady-state worst sits
  near 1 s; all writers then stall: the page alert must FIRE
  (alert == 2 in health.json + an ``slo_alert_fire`` timeline event)
  while the stall holds, and CLEAR after the writers resume and the
  fleet quiesces back under the objective.
- **skew** — a 3-node python-tier tree whose children run the r18 clock
  simulator at +50 ms / -50 ms (``ObsConfig.clock_skew_sim_sec``). The
  control-plane offset estimator must agree with the simulated skew
  within its own reported uncertainty, and the root's offset-corrected
  staleness for the skewed writer's link must shift by that offset
  (i.e. ``corrected - raw ~ +50 ms``) — cross-host honesty, proven on
  an adversarial clock.

Run:  JAX_PLATFORMS=cpu python benchmarks/fleet_health.py CHAOS_r18.json
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from shared_tensor_tpu import obs  # noqa: E402
from shared_tensor_tpu.config import (  # noqa: E402
    Config, ObsConfig, ShardConfig, TransportConfig,
)
from tests._ports import free_port  # noqa: E402

HEAT_N = int(os.environ.get("ST_FLEET_HEAT_N", 1 << 14))
HOT_SHARD = 2
BEAT_S = 0.2


def _tmpfile(tag: str) -> str:
    return os.path.join(
        os.environ.get("TMPDIR", "/tmp"), f"st_fleet_{tag}_{os.getpid()}.json"
    )


def _read_json(path: str) -> dict | None:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _wait(pred, timeout: float, msg: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.05)
    raise TimeoutError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# phase 1: zipf heat on a 7-node sharded fleet
# ---------------------------------------------------------------------------


def phase_heat() -> dict:
    from shared_tensor_tpu.shard import create_or_fetch_sharded

    tmpl = {"t": np.zeros(HEAT_N, np.float32)}
    health_path = _tmpfile("heat")
    port = free_port()

    def cfg(idx: int, root: bool = False) -> Config:
        return Config(
            shard=ShardConfig(
                n_shards=4, shard_index=idx, engine_lane=False
            ),
            transport=TransportConfig(peer_timeout_sec=30.0),
            obs=ObsConfig(
                digest_interval_sec=BEAT_S,
                health_json_path=health_path if root else "",
            ),
        )

    handles = [
        create_or_fetch_sharded(
            "127.0.0.1", port, tmpl, cfg(0, root=True), timeout=60.0
        )
    ]
    for i in range(1, 7):
        handles.append(
            create_or_fetch_sharded(
                "127.0.0.1", port, tmpl,
                cfg(i if i <= 3 else -1), timeout=60.0,
            )
        )
    stop = threading.Event()
    threads: list[threading.Thread] = []
    try:
        m = handles[0].node.map
        ranges = [m.element_range(k) for k in range(4)]
        # zipf-ish write mix: the hot shard takes the rank-1 weight, the
        # rest split a steep tail (~79/14/5/2%). The exponent and the
        # write pace below are chosen together: the python shard tier
        # COALESCES same-shard writes in the outbox, so a write rate
        # near the pump frequency flattens per-shard apply rates toward
        # uniform no matter how skewed the draw — writes must stay
        # sparse enough that (nearly) each one becomes its own FWD
        order = [HOT_SHARD] + [k for k in range(4) if k != HOT_SHARD]
        weights = np.array([1.0 / (r + 1) ** 2.5 for r in range(4)])
        weights /= weights.sum()
        shard_of_draw = {i: order[i] for i in range(4)}

        deltas = {}
        for k, (lo, hi) in enumerate(ranges):
            width = min(hi, HEAT_N) - lo
            d = np.zeros(HEAT_N, np.float32)
            d[lo:lo + width] = 0.01
            deltas[k] = d

        def writer(seed: int):
            rng = np.random.default_rng(seed)
            while not stop.is_set():
                k = shard_of_draw[int(rng.choice(4, p=weights))]
                handles[4 + seed % 3].add({"t": deltas[k]})
                stop.wait(0.04)

        # writers are started only once the analyzer is beating, so the
        # detection clock below starts at first telemetry, not mid-warmup
        _wait(
            lambda: (_read_json(health_path) or {}).get("beats", 0) >= 2,
            30.0, "first health beats",
        )
        for i in range(3):
            t = threading.Thread(target=writer, args=(i,), daemon=True)
            t.start()
            threads.append(t)

        def beat_with(pred):
            doc = _read_json(health_path)
            if doc and pred(doc):
                return doc
            return None

        # the naming clock starts when naming first becomes POSSIBLE: the
        # analyzer needs >= 2 shards with live apply rates before the
        # skew ratio is even computable — a single cold shard's counter
        # arriving one beat early must not start the stopwatch
        evidence = _wait(
            lambda: beat_with(
                lambda d: len(d["heat"]["shards"]) >= 2
                and any(
                    s["apply_rate"] > 0
                    for s in d["heat"]["shards"].values()
                )
            ),
            30.0, "computable shard telemetry reaching the root",
        )
        named = _wait(
            lambda: beat_with(
                lambda d: d["heat"]["hot_shard"] == HOT_SHARD
            ),
            30.0, f"hot shard {HOT_SHARD} named",
        )
        beats_to_name = named["beats"] - evidence["beats"]
        return {
            "hot_shard_expected": HOT_SHARD,
            "hot_shard_named": named["heat"]["hot_shard"],
            "skew_ratio": named["heat"]["skew_ratio"],
            "evidence_beat": evidence["beats"],
            "named_beat": named["beats"],
            "beats_to_name": beats_to_name,
            "shards": named["heat"]["shards"],
            "pass": bool(
                named["heat"]["hot_shard"] == HOT_SHARD
                and beats_to_name <= 3
            ),
        }
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        for h in reversed(handles):
            h.close()


# ---------------------------------------------------------------------------
# phase 2: SLO fire on stall, clear at quiesce (7-node peer tree)
# ---------------------------------------------------------------------------


def phase_slo() -> dict:
    import jax.numpy as jnp

    from shared_tensor_tpu.comm.peer import create_or_fetch

    health_path = _tmpfile("slo")
    port = free_port()
    n = 1024
    seed = jnp.zeros((n,), jnp.float32)

    def cfg(root: bool) -> Config:
        return Config(
            native_engine=False,  # python tier: live-aged staleness
            # pace frame production: a free-running python sender always
            # has residual to halve, keeps the go-back-N window full, and
            # the queueing delay (honestly reported by the live-aged
            # staleness gauge) dwarfs any objective. Paced at 0.5 s the
            # window drains between cuts and steady state sits near 1 s.
            sync_interval_sec=0.5,
            transport=TransportConfig(
                peer_timeout_sec=30.0, ack_timeout_sec=0.4
            ),
            obs=ObsConfig(
                digest_interval_sec=BEAT_S,
                health_json_path=health_path if root else "",
                staleness_slo_sec=3.0,
                slo_budget=0.05,
                slo_windows=(
                    ("page", 6.0, 1.0, 2.0),
                    ("ticket", 12.0, 2.0, 1.5),
                ),
            ),
        )

    hub = obs.hub()
    hub.poll_native()
    fire0 = hub.recorder.counts["slo_alert_fire"]
    clear0 = hub.recorder.counts["slo_alert_clear"]
    peers = [
        create_or_fetch("127.0.0.1", port, seed, cfg(i == 0), timeout=60.0)
        for i in range(7)
    ]
    stalled = threading.Event()
    stop = threading.Event()
    threads = []
    try:
        rng = np.random.default_rng(1)
        ds = [
            jnp.asarray(rng.uniform(-0.01, 0.01, n).astype(np.float32))
            for _ in range(4)
        ]

        def writer(i: int):
            j = 0
            while not stop.is_set():
                if not stalled.is_set():
                    peers[i].add(ds[j % len(ds)])
                    j += 1
                stop.wait(0.25)

        for i in range(7):
            t = threading.Thread(target=writer, args=(i,), daemon=True)
            t.start()
            threads.append(t)

        def alert() -> int:
            doc = _read_json(health_path)
            return int((doc or {}).get("slo", {}).get("alert", -1))

        _wait(lambda: alert() == 0, 30.0, "steady state under the objective")
        time.sleep(2.0)  # a few green beats on the record
        pre_stall_alert = alert()
        stalled.set()
        t_stall = time.monotonic()
        fired = _wait(lambda: alert() == 2, 20.0, "page alert firing")
        fire_latency = time.monotonic() - t_stall
        fired_doc = _read_json(health_path)
        stalled.clear()
        t_resume = time.monotonic()
        cleared = _wait(lambda: alert() == 0, 30.0, "alert clearing")
        clear_latency = time.monotonic() - t_resume
        return {
            "pre_stall_alert": pre_stall_alert,
            "fired": bool(fired),
            "fire_latency_s": round(fire_latency, 2),
            "fired_windows": (fired_doc or {}).get("slo", {}).get("windows"),
            "cleared": bool(cleared),
            "clear_latency_s": round(clear_latency, 2),
            "fire_events": hub.recorder.counts["slo_alert_fire"] - fire0,
            "clear_events": hub.recorder.counts["slo_alert_clear"] - clear0,
            "pass": bool(
                pre_stall_alert == 0
                and fired
                and cleared
                and hub.recorder.counts["slo_alert_fire"] > fire0
                and hub.recorder.counts["slo_alert_clear"] > clear0
            ),
        }
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5.0)
        for p in reversed(peers):
            p.close()


# ---------------------------------------------------------------------------
# phase 3: +/-50 ms simulated skew vs the offset estimator
# ---------------------------------------------------------------------------


def phase_skew() -> dict:
    import jax.numpy as jnp

    from shared_tensor_tpu.comm.peer import create_or_fetch

    health_path = _tmpfile("skew")
    port = free_port()
    n = 2048
    seed = jnp.zeros((n,), jnp.float32)
    skews = [0.0, 0.05, -0.05]

    def cfg(i: int) -> Config:
        return Config(
            native_engine=False,
            transport=TransportConfig(peer_timeout_sec=30.0),
            obs=ObsConfig(
                digest_interval_sec=BEAT_S,
                health_json_path=health_path if i == 0 else "",
                clock_sync_interval_sec=0.2,
                clock_skew_sim_sec=skews[i],
            ),
        )

    peers = [
        create_or_fetch("127.0.0.1", port, seed, cfg(i), timeout=60.0)
        for i in range(3)
    ]
    stop = threading.Event()
    try:
        rng = np.random.default_rng(2)
        d = jnp.asarray(rng.uniform(-0.01, 0.01, n).astype(np.float32))

        def writer():
            # the +50ms child writes, so the ROOT's staleness record for
            # that link carries a skewed-origin generation stamp
            while not stop.is_set():
                peers[1].add(d)
                stop.wait(0.05)

        t = threading.Thread(target=writer, daemon=True)
        t.start()

        ids = [p.node.obs_id for p in peers]

        def clock_ready() -> dict | None:
            doc = _read_json(health_path)
            if not doc:
                return None
            table = doc.get("clock", {})
            if all(str(i) in table for i in ids[1:]):
                return doc
            return None

        doc = _wait(clock_ready, 30.0, "clock estimates for both children")
        time.sleep(2.0)  # let the min-RTT sample window fill
        doc = _read_json(health_path) or doc
        table = doc["clock"]
        nodes = []
        est_ok = True
        for i, skew in enumerate(skews):
            if i == 0:
                continue  # the root pins (0, 0) by construction
            ent = table[str(ids[i])]
            err = abs(ent["off_sec"] - skew)
            ok = err <= ent["unc_sec"] + 0.002
            est_ok = est_ok and ok
            nodes.append(
                {
                    "node": ids[i],
                    "skew_sim_sec": skew,
                    "off_est_sec": ent["off_sec"],
                    "unc_sec": ent["unc_sec"],
                    "abs_err_sec": err,
                    "within_uncertainty": ok,
                }
            )
        # corrected staleness at the root must shift by the writer's
        # offset: corrected - raw = off_origin (applier = root, off 0)
        def stale_rec() -> dict | None:
            d2 = _read_json(health_path)
            if not d2:
                return None
            rec = d2.get("staleness", {}).get("nodes", {}).get(str(ids[0]))
            if rec and rec.get("origin") == ids[1] and rec["unc_sec"] is not None:
                return rec
            return None

        rec = _wait(stale_rec, 30.0, "root's corrected staleness record")
        shift = rec["corrected_sec"] - rec["raw_sec"]
        shift_ok = abs(shift - skews[1]) <= rec["unc_sec"] + 0.005
        # raw staleness here is skew-distorted DOWN (the origin's stamps
        # run 50ms ahead) and may clamp at 0; corrected must restore the
        # offset unless the clamp ate part of it — tolerate the clamped
        # case by checking the shift only when raw > 0
        if rec["raw_sec"] == 0.0:
            shift_ok = shift <= skews[1] + rec["unc_sec"] + 0.005
        return {
            "nodes": nodes,
            "estimator_within_uncertainty": est_ok,
            "root_staleness_record": rec,
            "corrected_minus_raw_sec": shift,
            "shift_matches_skew": bool(shift_ok),
            "pass": bool(est_ok and shift_ok),
        }
    finally:
        stop.set()
        for p in reversed(peers):
            p.close()


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "CHAOS_r18.json"
    if not os.path.isabs(out_path):
        out_path = os.path.join(REPO, out_path)
    import jax

    jax.config.update("jax_platforms", "cpu")

    phases = {}
    for name, fn in (
        ("heat", phase_heat), ("slo", phase_slo), ("skew", phase_skew)
    ):
        t0 = time.monotonic()
        try:
            phases[name] = fn()
        except Exception as e:  # a wedged phase fails loudly, not silently
            phases[name] = {"pass": False, "error": f"{type(e).__name__}: {e}"}
        phases[name]["wall_s"] = round(time.monotonic() - t0, 2)
        print(
            f"fleet_health/{name}: "
            f"{'PASS' if phases[name]['pass'] else 'FAIL'} "
            f"({phases[name]['wall_s']}s)",
            file=sys.stderr,
        )
    doc = {
        "bench": "fleet_health",
        "beat_s": BEAT_S,
        "phases": phases,
        "pass": bool(all(p["pass"] for p in phases.values())),
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
    print(json.dumps(doc, indent=2))
    return 0 if doc["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
