"""Codec-lab Pareto: error-vs-bytes-vs-frames for the experimental
compression methods (ops/codec_lab.py; reference README.md:45 "try
different compression methods" TODO).

For each (method, residual distribution): run the error-feedback loop on
one link trajectory and record how fast the residual RMS falls per frame
and per byte sent, plus host encode throughput. Emits one JSON line
(-> CODEC_LAB_r{N}.json).

Run: python benchmarks/codec_lab.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from shared_tensor_tpu.ops.codec_lab import standard_lab

N = int(os.environ.get("ST_CODEC_LAB_N", str(1 << 18)))
MAX_FRAMES = 400
TARGET = 1e-2  # "converged" mark for the frames/bytes-to-target columns


def distributions(rng):
    heavy = (rng.standard_t(1.2, N) * 1e-3).astype(np.float32)
    heavy[rng.integers(0, N, max(8, N // 8192))] += rng.choice(
        [-100.0, 100.0], max(8, N // 8192)
    ).astype(np.float32)
    # two "leaves" three orders of magnitude apart, concatenated — the flat
    # single-scale view of BASELINE config 3's mixed-magnitude table (the
    # per-leaf-scale table codec solves this properly; the lab measures how
    # much each POLICY suffers without that)
    mixed = np.concatenate(
        [
            rng.standard_normal(N // 2).astype(np.float32),
            (rng.standard_normal(N - N // 2) * 1e-3).astype(np.float32),
        ]
    )
    return {
        "uniform": rng.uniform(-1.0, 1.0, N).astype(np.float32),
        "gaussian": rng.standard_normal(N).astype(np.float32),
        "heavy_tail": heavy,
        "mixed_magnitude": mixed,
    }


def _rms(r):
    return float(np.sqrt(np.mean(r.astype(np.float64) ** 2)))


def run(codec, r0):
    r = r0.copy()
    rms0 = _rms(r0)
    bytes_total = 0
    first_payload = None
    frames_to_target = None
    bytes_to_target = None
    rms_at_20 = None
    t_encode = 0.0
    for i in range(1, MAX_FRAMES + 1):
        t0 = time.perf_counter()
        frame, r = codec.encode(r)
        t_encode += time.perf_counter() - t0
        bytes_total += frame.payload_bytes
        if first_payload is None:
            first_payload = frame.payload_bytes
        rel = _rms(r) / rms0
        if i == 20:
            rms_at_20 = rel
        if frames_to_target is None and rel < TARGET:
            frames_to_target, bytes_to_target = i, bytes_total
        if frame.payload_bytes <= 4 and not r.any():
            break
    rel_final = _rms(r) / rms0
    return {
        "method": codec.name,
        "frames_to_1pct": frames_to_target,
        "bytes_to_1pct": bytes_to_target,
        "bytes_per_frame": first_payload,
        "rms_decay_per_frame_20": (
            round(rms_at_20 ** (1 / 20), 4) if rms_at_20 is not None else None
        ),
        "final_rel_rms": float(f"{rel_final:.3e}"),
        "frames_run": i,
        "encode_Melem_s": round(N * i / t_encode / 1e6, 1),
    }


def main():
    rng = np.random.default_rng(0)
    rows = []
    for dist_name, r0 in distributions(rng).items():
        for codec in standard_lab(N):
            row = run(codec, r0)
            row["dist"] = dist_name
            rows.append(row)
    print(
        json.dumps(
            {
                "bench": "codec_lab_pareto",
                "n_elements": N,
                "target_rel_rms": TARGET,
                "rows": rows,
                "reading": (
                    "per-byte winner: min bytes_to_1pct per dist; per-frame "
                    "(latency) winner: min frames_to_1pct. Measured regimes: "
                    "sign1 byte-optimal on uniform (the reference's choice, "
                    "exact drain); sign2 wins gaussian per frame AND per "
                    "byte to 1% (sign1's tail stalls at ±s/frame); topk "
                    "dominant on heavy tails (1 frame to 1%, sign1 never "
                    "in 400); mixed_magnitude is why the production table "
                    "codec has per-leaf scales"
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
