"""Multi-peer churn soak of the native engine (evidence, not a unit test).

A 5-process tree (master + 4 joiners) streams continuously for
ST_SOAK_SECONDS (default 300): every peer adds structured deltas on its own
cadence; two designated chaos peers repeatedly (a) hard-drop a live link
mid-stream (transport-level kill -> re-graft with carried residual) and
(b) gracefully leave (drain + close) and rejoin as a fresh process.

What the delivery contract promises here (core.SharedTensor, README):
AGREEMENT within the codec's oscillation floor — after quiescing, every
replica converges to the same value to within a few final-frame scales
(checked via a fresh verifier peer joining at the end); EXACTNESS under
graceful operations (pinned deterministically, without kills, by
tests/test_engine.py::test_engine_midstream_leave_loses_nothing — leave()
seals ingress so in-transit mass re-routes instead of dying with the
leaver); and AT-LEAST-ONCE under hard link kills — a message applied
whose ACK died with the link re-delivers from the rolled-back carry (the
two-generals window). A re-delivered FRAME adds +/-scale noise per
element (its bits are sign patterns, not the original delta), so the
deviation from the true global sum is SYMMETRIC frame noise bounded per
kill — it cannot be decomposed into "lost" vs "duplicated" mass from the
totals alone. The reference kills the entire tree at the first event of
any kind.

Emits one JSON line (max cross-replica deviation, churn counts, frame
totals). Run: python benchmarks/soak.py
"""

import glob
import json
import shutil
import multiprocessing as mp
import os
import socket
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("ST_SOAK_N", "8192"))
SECONDS = float(os.environ.get("ST_SOAK_SECONDS", "300"))
PEERS = 4  # joiners; +1 master
CRASH = os.environ.get("ST_SOAK_CRASH", "0") == "1"  # SIGKILL arm (see EOF note)
#: ST_SOAK_COMPAT=1 runs the whole chaos profile on the reference's raw wire
#: protocol (engine compat data plane + compat bursts + compat re-graft).
#: Delivery degrades to the protocol's own semantics (no ACKs), so the
#: deviation bounds are looser than native mode's ledger-backed ones.
COMPAT = os.environ.get("ST_SOAK_COMPAT", "0") == "1"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _mk(port):
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from shared_tensor_tpu import create_or_fetch

    cfg = None
    if COMPAT:
        from shared_tensor_tpu.config import Config, TransportConfig

        cfg = Config(
            transport=TransportConfig(peer_timeout_sec=30.0, wire_compat=True)
        )
    return create_or_fetch(
        "127.0.0.1", port, {"w": np.zeros(N, np.float32)}, cfg, timeout=60.0
    ), np


def _worker(rank, port, stop_ev, exit_ev, out_q, ledger_dir, chaos):
    peer, np = _mk(port)
    rng = np.random.default_rng(rank)
    # Per-worker APPEND-ONLY file ledger: contributions and chaos events
    # stream to disk as they happen, so a SIGKILLed worker's ledger
    # survives it. A file per worker (no shared lock, no pickling) is
    # crash-safe where a shared mp.Queue is not — a kill landing while the
    # victim's feeder thread holds the queue lock or is mid-pickle would
    # corrupt/deadlock every survivor's channel. Written AFTER the add and
    # flushed per line: a kill between add and write undercounts by at most
    # one delta (reads as duplicate); a kill mid-write leaves one partial
    # final line the reader skips.
    ledger = open(os.path.join(ledger_dir, f"ledger_{rank}.txt"), "a")
    kills = leaves = 0
    last_chaos = time.time()
    while not stop_ev.is_set():
        # structured deltas (converge exactly; Gaussian tails would
        # oscillate forever at the +/-scale floor)
        lo, hi = sorted(rng.uniform(-1, 1, size=2))
        d = np.linspace(lo, hi, N, dtype=np.float32)
        peer.add({"w": d})
        ledger.write(f"A {float(lo)!r} {float(hi)!r}\n")
        ledger.flush()
        time.sleep(0.05 + 0.05 * rank / PEERS)
        if chaos and time.time() - last_chaos > 7:
            last_chaos = time.time()
            if kills <= leaves:
                links = peer.node.links
                if links:
                    peer.node.drop_link(links[0])  # hard uplink kill
                    kills += 1
                    ledger.write("K\n")
                    ledger.flush()
            else:
                # graceful MID-STREAM leave: seal-drain-close (peer.leave)
                # — the sealed ingress makes in-transit third-party mass
                # re-route around us instead of dying with our residuals
                peer.leave(timeout=30.0)
                leaves += 1
                ledger.write("L\n")
                ledger.flush()
                peer, np = _mk(port)
    # quiesce: drain everything we still owe (peers stay open so late
    # siblings can still converge through us; exit_ev gates the close)
    ok = peer.drain(timeout=90.0, tol=1e-30)
    ledger.close()
    out_q.put((rank, kills, leaves, ok, peer.metrics(canonical=True)))
    # stay alive until the coordinator says every sibling finished draining
    # and settling THROUGH us (an interior leaver closing early would drop
    # ACKed-but-not-yet-flooded frames — the drain-then-close race the
    # peer tests quiesce around)
    exit_ev.wait(timeout=300)
    peer.close()


def main() -> None:
    mp.set_start_method("spawn")
    port = _free_port()
    master, np = _mk(port)
    stop_ev = mp.Event()
    exit_ev = mp.Event()
    out_q = mp.Queue()
    ledger_dir = tempfile.mkdtemp(prefix="st_soak_")

    def spawn(rank, chaos):
        p = mp.Process(
            target=_worker,
            args=(rank, port, stop_ev, exit_ev, out_q, ledger_dir, chaos),
        )
        p.start()
        return p

    procs = []
    for r in range(1, PEERS + 1):
        procs.append(spawn(r, r in (1, 3)))
        time.sleep(0.4)  # stagger the initial join herd
    chaos_idx = [0, 2]  # indices into procs of the chaos workers
    crashes = 0
    next_rank = PEERS + 1
    master_contrib = np.zeros(N, np.float64)
    rng = np.random.default_rng(0)
    t_end = time.time() + SECONDS
    last_crash = time.time()
    while time.time() < t_end:
        lo, hi = sorted(rng.uniform(-1, 1, size=2))
        d = np.linspace(lo, hi, N, dtype=np.float32)
        master.add({"w": d})
        master_contrib += d
        if CRASH and time.time() - last_crash > 20:
            last_crash = time.time()
            # SIGKILL one chaos worker (no drain, no seal — the crash arm)
            # and replace it with a fresh joiner
            idx = chaos_idx[crashes % len(chaos_idx)]
            victim = procs[idx]
            if victim.is_alive():
                victim.kill()
                victim.join(timeout=10)
                crashes += 1
                procs[idx] = spawn(next_rank, True)
                next_rank += 1
        time.sleep(0.05)
    stop_ev.set()
    live = [p for p in procs if p.is_alive()]
    # population invariant: crash-arm replacements keep it at PEERS; an
    # UNEXPECTED worker death (unhandled exception) must fail the soak,
    # not silently shrink the result set
    population_ok = len(live) == PEERS
    results = [out_q.get(timeout=180) for _ in range(len(live))]
    # replay every worker's file ledger (survives SIGKILL; skip at most one
    # partial final line per victim)
    worker_contrib = np.zeros(N, np.float64)
    ledger_kills = ledger_leaves = 0
    for f in sorted(glob.glob(os.path.join(ledger_dir, "ledger_*.txt"))):
        for line in open(f):
            if not line.endswith("\n"):
                continue  # partial final write of a SIGKILLed worker
            if line.startswith("A "):
                try:
                    _, lo, hi = line.split()
                    worker_contrib += np.linspace(
                        float(lo), float(hi), N, dtype=np.float32
                    ).astype(np.float64)
                except ValueError:
                    continue  # torn line
            elif line[0] == "K":
                ledger_kills += 1
            elif line[0] == "L":
                ledger_leaves += 1
    # settle: keep applying incoming until the tree quiesces
    settle_end = time.time() + 30
    prev = None
    while time.time() < settle_end:
        cur = master.read()["w"].copy()
        if prev is not None and np.array_equal(cur, prev):
            break
        prev = cur
        time.sleep(1.0)
    time.sleep(1.0)
    mv = master.read()["w"].astype(np.float64)
    expected = master_contrib + worker_contrib
    signed = mv - expected
    # symmetric frame noise from at-least-once re-delivery (see module
    # docstring): report both tails, bound the magnitude per kill
    neg_dev = float(-signed.min()) if signed.min() < 0 else 0.0
    pos_dev = float(signed.max()) if signed.max() > 0 else 0.0
    # event counts from the crash-safe ledgers (out_q counts die with a
    # SIGKILLed victim; the files do not)
    kills = ledger_kills
    leaves = ledger_leaves
    drains_ok = sum(1 for r in results if r[3])
    # AGREEMENT check: a fresh verifier joins the quiesced tree and must
    # converge to the state the master holds (state transfer + flood agree)
    verifier, _ = _mk(port)
    agreement_dev = float("inf")
    v_end = time.time() + 30
    while time.time() < v_end:
        vv = verifier.read()["w"].astype(np.float64)
        agreement_dev = float(np.abs(vv - master.read()["w"].astype(np.float64)).max())
        if agreement_dev < 1e-4:
            break
        time.sleep(0.5)
    exit_ev.set()  # all measurements done: workers may now close
    # noise bounds: each hard link kill can re-deliver at most one link's
    # in-flight window (burst frames x scales ~ O(1) per element for these
    # unit-range deltas; 2.0/kill is generous). A process CRASH additionally
    # LOSES its un-propagated recent adds and relay window (~a few deltas,
    # each |mass| <= ~1/element) — the contract's bounded-loss arm.
    if COMPAT:
        # The reference protocol has no ACKs, so there is no ledger to roll
        # back or redeliver from: EVERY event — link kill AND sealed leave
        # (sealed ingress discards without redelivery when nothing re-sends)
        # — loses or double-counts its TCP-buffered in-flight window, up to
        # the send queue depth of halving frames (~2x the leading frame's
        # mass, plus slack for bursts in flight). 4.0/event is that window's
        # envelope PER DEVIATION TAIL (the gate below checks neg_dev and
        # pos_dev each against it); measured runs sit near 1.4/event per
        # tail. Against the protocol's own yardstick this is the win: the
        # reference loses the WHOLE TREE at the first such event.
        noise_bound = 4.0 * max(kills + leaves, 1) + 5.0 * crashes
    else:
        noise_bound = 2.0 * max(kills, 1) + 5.0 * crashes
    out = {
        "bench": "engine_churn_soak",
        "wire": "compat" if COMPAT else "native",
        "n": N,
        "seconds": SECONDS,
        "peers": PEERS + 1,
        "hard_link_kills": kills,
        "process_crashes_sigkill": crashes,
        "graceful_leave_rejoin_cycles": leaves,
        "final_drains_ok": f"{drains_ok}/{len(results)}",
        "population_ok": population_ok,
        "agreement_dev_master_vs_fresh_joiner": agreement_dev,
        "agreement_bar": round(0.01 + 2e-3 * float(np.abs(mv).max()), 4),
        "state_magnitude_max": round(float(np.abs(mv).max()), 2),
        "sum_dev_neg": neg_dev,
        "sum_dev_pos": pos_dev,
        "redelivery_noise_bound": noise_bound,
        "master_frames_in": master.metrics(canonical=True)["st_frames_in_total"],
        "pass": bool(
            # agreement floor: the verifier's state transfer converges
            # geometrically, so its plateau is RELATIVE to the state
            # magnitude (a 300 s run accumulates ~50-magnitude elements;
            # 0.2% relative + a small absolute floor covers the codec's
            # +/-final-scale oscillation)
            agreement_dev < 0.01 + 2e-3 * float(np.abs(mv).max())
            and neg_dev < noise_bound
            and pos_dev < noise_bound
            and drains_ok == len(results)
            and population_ok
        ),
    }
    print(json.dumps(out))
    shutil.rmtree(ledger_dir, ignore_errors=True)
    verifier.close()
    master.close()
    for p in procs:
        p.join(timeout=30)


if __name__ == "__main__":
    main()


# ---- process-crash variant -------------------------------------------------
# ST_SOAK_CRASH=1 adds the contract's third arm: SIGKILL a chaos worker
# mid-stream (no drain, no seal — the process just dies). The contract
# allows BOUNDED loss here: mass sitting in the victim's replica that had
# not yet flooded onward (its own recent adds + in-transit relay mass)
# dies with it; everything that finished propagating survives, and the
# tree still converges to agreement. The soak restarts a fresh worker
# after each crash and reports the deficit attributable to the crashes.
