/* stc_harness — a standalone C peer speaking the reference wire protocol.
 *
 * Purpose (VERDICT.md round-1 item 5): prove byte-level interop of the
 * framework's wire-compat mode against a real compiled-C counterpart, not a
 * Python mock. This file is written fresh from the protocol/codec SPEC
 * (SURVEY.md §2.3 + Appendix B, citing reference src/sharedtensor.c for the
 * behavior it must match); it is NOT a copy of the reference implementation
 * (different structure: single uplink leaf peer, mutex'd state, bounded
 * runtime, heap buffers, clean shutdown).
 *
 * Protocol (reference src/sharedtensor.c:121-122, :176-177, :281-300):
 *   join:   connect; read 1 byte; 'Y' => stream on this socket;
 *           'N' => 16-byte raw sockaddr_in redirect, retry there.
 *   frames: [4-byte little-endian f32 scale][ceil(n/8) bytes bitmask],
 *           bit i at byte[i/8], position i%8 (LSB-first);
 *           set bit = -scale, clear = +scale.
 *   codec:  scale = 2^floor(log2(RMS(residual))) (0 => idle frame, 1/s);
 *           sender: b_i = (r_i <= 0); r_i -= (1-2*b_i)*scale  (error
 *           feedback); receiver: values_i += (1-2*b_i)*scale.
 *
 * Usage: stc_harness <host> <port> <n> <seconds> <add>
 *   Joins the tree at host:port for a tensor of n floats, immediately
 *   contributes `add` to every element (the reference addFromTensor
 *   semantics: values += add, residual += add), streams full-duplex for
 *   `seconds`, then prints the final replica (one float per line, %.9g) on
 *   stdout and exits 0. Any protocol error exits nonzero with a message.
 */

#include <arpa/inet.h>
#include <errno.h>
#include <math.h>
#include <netinet/in.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

typedef struct {
    int fd;
    int n;
    int mask_bytes;
    float *values;   /* replica */
    float *resid;    /* uplink residual (error feedback) */
    pthread_mutex_t mu;
    volatile int stop;
} Peer;

static int read_full(int fd, void *buf, size_t len) {
    char *p = buf;
    while (len > 0) {
        ssize_t r = read(fd, p, len);
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        if (r == 0) return -1; /* EOF */
        p += r;
        len -= (size_t)r;
    }
    return 0;
}

static int write_full(int fd, const void *buf, size_t len) {
    const char *p = buf;
    while (len > 0) {
        ssize_t r = write(fd, p, len);
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        p += r;
        len -= (size_t)r;
    }
    return 0;
}

/* Join walk: connect, follow 'N' redirects until a 'Y' (bounded depth). */
static int join_tree(const char *host, int port) {
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof addr);
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        fprintf(stderr, "stc_harness: bad host %s\n", host);
        return -1;
    }
    for (int depth = 0; depth < 64; depth++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return -1;
        if (connect(fd, (struct sockaddr *)&addr, sizeof addr) != 0) {
            perror("stc_harness: connect");
            close(fd);
            return -1;
        }
        char reply;
        if (read_full(fd, &reply, 1) != 0) {
            close(fd);
            return -1;
        }
        if (reply == 'Y') return fd;
        if (reply != 'N') {
            fprintf(stderr, "stc_harness: bad join reply 0x%02x\n", reply);
            close(fd);
            return -1;
        }
        /* raw sockaddr_in redirect (x86-layout, reference :229-231) */
        if (read_full(fd, &addr, sizeof addr) != 0) {
            close(fd);
            return -1;
        }
        close(fd);
    }
    fprintf(stderr, "stc_harness: redirect loop\n");
    return -1;
}

static void *sender(void *arg) {
    Peer *pe = arg;
    unsigned char *frame = malloc(4 + (size_t)pe->mask_bytes);
    if (!frame) return NULL;
    while (!pe->stop) {
        pthread_mutex_lock(&pe->mu);
        double ss = 0.0;
        for (int i = 0; i < pe->n; i++)
            ss += (double)pe->resid[i] * pe->resid[i];
        float rms = (float)sqrt(ss / pe->n);
        float scale = rms > 0.0f ? exp2f(floorf(log2f(rms))) : 0.0f;
        memset(frame + 4, 0, (size_t)pe->mask_bytes);
        for (int i = 0; i < pe->n; i++) {
            if (pe->resid[i] <= 0.0f) { /* send -scale; zero counts negative */
                frame[4 + i / 8] |= (unsigned char)(1u << (i % 8));
                pe->resid[i] += scale;
            } else {
                pe->resid[i] -= scale;
            }
        }
        pthread_mutex_unlock(&pe->mu);
        memcpy(frame, &scale, 4); /* little-endian f32 on the wire */
        if (scale == 0.0f)
            sleep(1); /* idle keepalive frame, 1/s (quirk Q2 semantics) */
        if (write_full(pe->fd, frame, 4 + (size_t)pe->mask_bytes) != 0)
            break;
    }
    free(frame);
    return NULL;
}

static void *receiver(void *arg) {
    Peer *pe = arg;
    unsigned char *frame = malloc(4 + (size_t)pe->mask_bytes);
    if (!frame) return NULL;
    while (!pe->stop) {
        if (read_full(pe->fd, frame, 4 + (size_t)pe->mask_bytes) != 0) break;
        float scale;
        memcpy(&scale, frame, 4);
        if (scale == 0.0f) continue;
        pthread_mutex_lock(&pe->mu);
        for (int i = 0; i < pe->n; i++) {
            int bit = (frame[4 + i / 8] >> (i % 8)) & 1;
            pe->values[i] += bit ? -scale : scale;
        }
        pthread_mutex_unlock(&pe->mu);
    }
    free(frame);
    return NULL;
}

int main(int argc, char **argv) {
    if (argc != 6) {
        fprintf(stderr, "usage: %s host port n seconds add\n", argv[0]);
        return 2;
    }
    /* write() on a peer-closed socket must return EPIPE, not kill us
     * mid-shutdown before the final replica dump. */
    signal(SIGPIPE, SIG_IGN);

    const char *host = argv[1];
    int port = atoi(argv[2]);
    int n = atoi(argv[3]);
    double seconds = atof(argv[4]);
    float add = (float)atof(argv[5]);
    if (n <= 0 || port <= 0) {
        fprintf(stderr, "stc_harness: bad n/port\n");
        return 2;
    }

    Peer pe;
    memset(&pe, 0, sizeof pe);
    pe.n = n;
    pe.mask_bytes = (n + 7) / 8;
    pe.values = calloc((size_t)n, sizeof(float));
    pe.resid = calloc((size_t)n, sizeof(float));
    pthread_mutex_init(&pe.mu, NULL);
    if (!pe.values || !pe.resid) return 1;

    pe.fd = join_tree(host, port);
    if (pe.fd < 0) return 1;

    /* addFromTensor semantics: visible locally at once, queued for the
     * uplink (reference :334-344). */
    for (int i = 0; i < n; i++) {
        pe.values[i] += add;
        pe.resid[i] += add;
    }

    pthread_t ts, tr;
    if (pthread_create(&tr, NULL, receiver, &pe) != 0) return 1;
    if (pthread_create(&ts, NULL, sender, &pe) != 0) return 1;

    struct timespec dur;
    dur.tv_sec = (time_t)seconds;
    dur.tv_nsec = (long)((seconds - (double)dur.tv_sec) * 1e9);
    nanosleep(&dur, NULL);

    pe.stop = 1;
    shutdown(pe.fd, SHUT_RDWR); /* unblocks both threads */
    pthread_join(ts, NULL);
    pthread_join(tr, NULL);
    close(pe.fd);

    for (int i = 0; i < n; i++)
        printf("%.9g\n", (double)pe.values[i]);
    return 0;
}
