/* stc_harness — a standalone C peer speaking the reference wire protocol.
 *
 * Purpose (VERDICT.md round-1 item 5, extended round 4): prove byte-level
 * interop of the framework's wire-compat mode against a real compiled-C
 * counterpart, not a Python mock — including as an INTERIOR node: with
 * `children=1` this peer binds a listener via the reference's addressing
 * trick, accepts one child, and floods frames between its uplink and child
 * with per-hop re-quantization through its own residuals (reference
 * src/sharedtensor.c:124-127 — the behavior round-3 VERDICT Weak #5 noted
 * was only ever interoperated at the edge). This file is written fresh from
 * the protocol/codec SPEC (SURVEY.md §2.3 + Appendix B, citing reference
 * src/sharedtensor.c for the behavior it must match); it is NOT a copy of
 * the reference implementation (different structure: link array, mutex'd
 * state, bounded runtime, heap buffers, clean shutdown).
 *
 * Protocol (reference src/sharedtensor.c:121-122, :176-177, :192-300):
 *   join:   connect; read 1 byte; 'Y' => stream on this socket;
 *           'N' => 16-byte raw sockaddr_in redirect, retry there.
 *   listen: bind to the uplink socket's LOCAL endpoint (SO_REUSEADDR +
 *           getsockname — the addressing trick :292-316), so the address a
 *           parent observed via accept() doubles as our listen address and
 *           its redirects reach us.
 *   frames: [4-byte little-endian f32 scale][ceil(n/8) bytes bitmask],
 *           bit i at byte[i/8], position i%8 (LSB-first);
 *           set bit = -scale, clear = +scale.
 *   codec:  scale = 2^floor(log2(RMS(residual))) (0 => idle frame, 1/s);
 *           sender: b_i = (r_i <= 0); r_i -= (1-2*b_i)*scale  (error
 *           feedback); receiver: values_i += (1-2*b_i)*scale applied to the
 *           replica AND to every other link's residual (split horizon).
 *
 * Usage: stc_harness <host> <port> <n> <seconds> <add> [children]
 *   Joins the tree at host:port for a tensor of n floats, immediately
 *   contributes `add` to every element (the reference addFromTensor
 *   semantics: values += add, every residual += add), streams full-duplex
 *   for `seconds`, then prints the final replica (one float per line,
 *   %.9g) on stdout and exits 0. `children` (default 0) enables the
 *   listener with that many child slots (0 or 1); extra joiners are
 *   redirected to the child, reference-style. Any protocol error exits
 *   nonzero with a message.
 */

#include <arpa/inet.h>
#include <errno.h>
#include <math.h>
#include <netinet/in.h>
#include <pthread.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#define MAX_LINKS 2 /* 0 = uplink, 1 = child */

typedef struct Peer Peer;

typedef struct {
    Peer *pe;
    int idx;                  /* slot in pe->links */
    int fd;
    float *resid;             /* this link's residual (error feedback) */
    volatile int open;
    struct sockaddr_in peer_addr; /* accept()-observed (redirect target) */
    pthread_t ts, tr;
} Link;

struct Peer {
    int n;
    int mask_bytes;
    float *values; /* replica */
    Link links[MAX_LINKS];
    pthread_mutex_t mu;
    volatile int stop;
    int listen_fd;
    int max_children;
};

static int read_full(int fd, void *buf, size_t len) {
    char *p = buf;
    while (len > 0) {
        ssize_t r = read(fd, p, len);
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        if (r == 0) return -1; /* EOF */
        p += r;
        len -= (size_t)r;
    }
    return 0;
}

static int write_full(int fd, const void *buf, size_t len) {
    const char *p = buf;
    while (len > 0) {
        ssize_t r = write(fd, p, len);
        if (r < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        p += r;
        len -= (size_t)r;
    }
    return 0;
}

/* Join walk: connect, follow 'N' redirects until a 'Y' (bounded depth). */
static int join_tree(const char *host, int port) {
    struct sockaddr_in addr;
    memset(&addr, 0, sizeof addr);
    addr.sin_family = AF_INET;
    addr.sin_port = htons((uint16_t)port);
    if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
        fprintf(stderr, "stc_harness: bad host %s\n", host);
        return -1;
    }
    for (int depth = 0; depth < 64; depth++) {
        int fd = socket(AF_INET, SOCK_STREAM, 0);
        if (fd < 0) return -1;
        /* SO_REUSEADDR on the CONNECTING socket too (as the reference does,
         * :264): the listener later binds to this socket's local endpoint,
         * and Linux requires every socket sharing the port to carry the
         * flag — without it that bind fails EADDRINUSE */
        int yes = 1;
        setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &yes, sizeof yes);
        if (connect(fd, (struct sockaddr *)&addr, sizeof addr) != 0) {
            perror("stc_harness: connect");
            close(fd);
            return -1;
        }
        char reply;
        if (read_full(fd, &reply, 1) != 0) {
            close(fd);
            return -1;
        }
        if (reply == 'Y') return fd;
        if (reply != 'N') {
            fprintf(stderr, "stc_harness: bad join reply 0x%02x\n", reply);
            close(fd);
            return -1;
        }
        /* raw sockaddr_in redirect (x86-layout, reference :229-231) */
        if (read_full(fd, &addr, sizeof addr) != 0) {
            close(fd);
            return -1;
        }
        close(fd);
    }
    fprintf(stderr, "stc_harness: redirect loop\n");
    return -1;
}

static void *sender(void *arg) {
    Link *lk = arg;
    Peer *pe = lk->pe;
    unsigned char *frame = malloc(4 + (size_t)pe->mask_bytes);
    if (!frame) return NULL;
    while (!pe->stop) {
        pthread_mutex_lock(&pe->mu);
        double ss = 0.0;
        for (int i = 0; i < pe->n; i++)
            ss += (double)lk->resid[i] * lk->resid[i];
        float rms = (float)sqrt(ss / pe->n);
        float scale = rms > 0.0f ? exp2f(floorf(log2f(rms))) : 0.0f;
        memset(frame + 4, 0, (size_t)pe->mask_bytes);
        for (int i = 0; i < pe->n; i++) {
            if (lk->resid[i] <= 0.0f) { /* send -scale; zero counts negative */
                frame[4 + i / 8] |= (unsigned char)(1u << (i % 8));
                lk->resid[i] += scale;
            } else {
                lk->resid[i] -= scale;
            }
        }
        pthread_mutex_unlock(&pe->mu);
        memcpy(frame, &scale, 4); /* little-endian f32 on the wire */
        if (scale == 0.0f)
            sleep(1); /* idle keepalive frame, 1/s (quirk Q2 semantics) */
        if (write_full(lk->fd, frame, 4 + (size_t)pe->mask_bytes) != 0)
            break;
    }
    free(frame);
    return NULL;
}

static void *receiver(void *arg) {
    Link *lk = arg;
    Peer *pe = lk->pe;
    unsigned char *frame = malloc(4 + (size_t)pe->mask_bytes);
    if (!frame) return NULL;
    while (!pe->stop) {
        if (read_full(lk->fd, frame, 4 + (size_t)pe->mask_bytes) != 0) break;
        float scale;
        memcpy(&scale, frame, 4);
        if (scale == 0.0f) continue;
        pthread_mutex_lock(&pe->mu);
        for (int i = 0; i < pe->n; i++) {
            int bit = (frame[4 + i / 8] >> (i % 8)) & 1;
            float d = bit ? -scale : scale;
            pe->values[i] += d;
            /* split-horizon flood with per-hop re-quantization: the delta
             * lands in every OTHER link's residual and leaves on that
             * link's own schedule and scale (reference :124-127) */
            for (int l = 0; l < MAX_LINKS; l++)
                if (l != lk->idx && pe->links[l].open)
                    pe->links[l].resid[i] += d;
        }
        pthread_mutex_unlock(&pe->mu);
    }
    pthread_mutex_lock(&pe->mu);
    lk->open = 0; /* stop flooding into a dead link */
    pthread_mutex_unlock(&pe->mu);
    free(frame);
    return NULL;
}

/* Interior-node listener (reference do_listening, :192-242, one child
 * slot): first joiner gets 'Y' + a link engine; later joiners get 'N' +
 * the child's accept()-observed sockaddr (which, by the addressing trick,
 * is also its listen address). */
static void *listener(void *arg) {
    Peer *pe = arg;
    while (!pe->stop) {
        struct sockaddr_in peer_addr;
        socklen_t plen = sizeof peer_addr;
        int fd = accept(pe->listen_fd, (struct sockaddr *)&peer_addr, &plen);
        if (fd < 0) {
            if (errno == EINTR) continue;
            break; /* listen socket shut down */
        }
        pthread_mutex_lock(&pe->mu);
        Link *child = &pe->links[1];
        /* fd < 0 = never used: a died child's slot stays closed (its old
         * threads may still hold the resid buffer; a retake would race) */
        int take = pe->max_children > 0 && !child->open && child->fd < 0;
        if (take) {
            child->fd = fd;
            child->peer_addr = peer_addr;
            /* seed the new child with complete state-to-date through the
             * normal codec stream: residual = current replica (the
             * reference achieves this by accumulating into unconnected
             * slots from birth, :124-126/:338-342 — same net effect) */
            memcpy(child->resid, pe->values, (size_t)pe->n * sizeof(float));
            child->open = 1;
        }
        pthread_mutex_unlock(&pe->mu);
        if (take) {
            int fail = write_full(fd, "Y", 1) != 0 ||
                       pthread_create(&child->tr, NULL, receiver, child) != 0;
            if (!fail && pthread_create(&child->ts, NULL, sender, child) != 0) {
                /* receiver already owns the link; let it die via shutdown */
                shutdown(fd, SHUT_RDWR);
                pthread_join(child->tr, NULL);
                fail = 1;
            }
            if (fail) {
                /* no threads hold the slot: fully reopen it (fd = -1) so a
                 * later joiner can take it — leaving fd set would brick the
                 * slot AND make shutdown touch a stale/reused descriptor */
                pthread_mutex_lock(&pe->mu);
                child->open = 0;
                child->fd = -1;
                pthread_mutex_unlock(&pe->mu);
                close(fd);
                continue;
            }
        } else {
            struct sockaddr_in redir;
            int live;
            pthread_mutex_lock(&pe->mu);
            redir = child->peer_addr;
            live = child->open;
            pthread_mutex_unlock(&pe->mu);
            if (live) {
                write_full(fd, "N", 1);
                write_full(fd, &redir, sizeof redir); /* raw, ref :229-231 */
            }
            /* dead child: no live address to redirect to — close, rather
             * than black-hole the joiner at a non-listening endpoint (the
             * slot stays closed; bounded-runtime harness, not production) */
            close(fd);
        }
    }
    return NULL;
}

int main(int argc, char **argv) {
    if (argc != 6 && argc != 7) {
        fprintf(stderr, "usage: %s host port n seconds add [children]\n",
                argv[0]);
        return 2;
    }
    /* write() on a peer-closed socket must return EPIPE, not kill us
     * mid-shutdown before the final replica dump. */
    signal(SIGPIPE, SIG_IGN);

    const char *host = argv[1];
    int port = atoi(argv[2]);
    int n = atoi(argv[3]);
    double seconds = atof(argv[4]);
    float add = (float)atof(argv[5]);
    int children = argc == 7 ? atoi(argv[6]) : 0;
    if (n <= 0 || port <= 0 || children < 0 || children > 1) {
        fprintf(stderr, "stc_harness: bad n/port/children\n");
        return 2;
    }

    Peer pe;
    memset(&pe, 0, sizeof pe);
    pe.n = n;
    pe.mask_bytes = (n + 7) / 8;
    pe.max_children = children;
    pe.listen_fd = -1;
    pe.values = calloc((size_t)n, sizeof(float));
    pthread_mutex_init(&pe.mu, NULL);
    if (!pe.values) return 1;
    for (int l = 0; l < MAX_LINKS; l++) {
        pe.links[l].pe = &pe;
        pe.links[l].idx = l;
        pe.links[l].fd = -1;
        pe.links[l].resid = calloc((size_t)n, sizeof(float));
        if (!pe.links[l].resid) return 1;
    }

    Link *up = &pe.links[0];
    up->fd = join_tree(host, port);
    if (up->fd < 0) return 1;
    up->open = 1;

    pthread_t tl = 0;
    if (children > 0) {
        /* the addressing trick: listen on the uplink's local endpoint so
         * the parent's redirects (which hand out our accept()-observed
         * address) reach this listener (reference :292-316) */
        struct sockaddr_in self;
        socklen_t slen = sizeof self;
        if (getsockname(up->fd, (struct sockaddr *)&self, &slen) != 0) {
            perror("stc_harness: getsockname");
            return 1;
        }
        pe.listen_fd = socket(AF_INET, SOCK_STREAM, 0);
        int yes = 1;
        setsockopt(pe.listen_fd, SOL_SOCKET, SO_REUSEADDR, &yes, sizeof yes);
        if (bind(pe.listen_fd, (struct sockaddr *)&self, sizeof self) != 0 ||
            listen(pe.listen_fd, 16) != 0) {
            perror("stc_harness: bind/listen");
            return 1;
        }
        if (pthread_create(&tl, NULL, listener, &pe) != 0) return 1;
    }

    /* addFromTensor semantics: visible locally at once, queued for every
     * link (reference :334-344). */
    pthread_mutex_lock(&pe.mu);
    for (int i = 0; i < n; i++) {
        pe.values[i] += add;
        for (int l = 0; l < MAX_LINKS; l++)
            if (pe.links[l].open) pe.links[l].resid[i] += add;
    }
    pthread_mutex_unlock(&pe.mu);

    if (pthread_create(&up->tr, NULL, receiver, up) != 0) return 1;
    if (pthread_create(&up->ts, NULL, sender, up) != 0) return 1;

    struct timespec dur;
    dur.tv_sec = (time_t)seconds;
    dur.tv_nsec = (long)((seconds - (double)dur.tv_sec) * 1e9);
    nanosleep(&dur, NULL);

    pe.stop = 1;
    if (pe.listen_fd >= 0) shutdown(pe.listen_fd, SHUT_RDWR);
    for (int l = 0; l < MAX_LINKS; l++)
        if (pe.links[l].fd >= 0) shutdown(pe.links[l].fd, SHUT_RDWR);
    if (tl) pthread_join(tl, NULL);
    pthread_join(up->ts, NULL);
    pthread_join(up->tr, NULL);
    if (pe.links[1].fd >= 0) {
        /* child threads exist only if a child attached */
        if (pe.links[1].ts) pthread_join(pe.links[1].ts, NULL);
        if (pe.links[1].tr) pthread_join(pe.links[1].tr, NULL);
        close(pe.links[1].fd);
    }
    close(up->fd);
    if (pe.listen_fd >= 0) close(pe.listen_fd);

    for (int i = 0; i < n; i++)
        printf("%.9g\n", (double)pe.values[i]);
    return 0;
}
